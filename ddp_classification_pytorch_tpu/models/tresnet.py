"""Flax TResNet-M backbone — the reference's `timm` high-throughput option.

Parity target: `timm.create_model('tresnet_m_miil_in21k', num_classes=...)`
selected by `--model timm` (BASELINE/main.py:141-144), whose native
dependency is the `inplace_abn` CUDA extension (requirements.txt:5-8). Here
every activated ABN site uses `ops.pallas_kernels` — the Pallas fused
BatchNorm+LeakyReLU with exact VJP — so the model is TPU-native end to end.

Architecture (TResNet: "TResNet: High Performance GPU-Dedicated
Architecture", Ridnik et al. 2020), laid out NHWC for XLA but
structurally EXACT to timm's `tresnet.py` so pretrained checkpoints import
weight-for-weight (models/import_torch.py::convert_tresnet_state_dict):
- SpaceToDepth stem (x4 patchify, (bh, bw, c) channel order matching timm's
  permute) -> conv 3x3 + ABN — a reshape XLA fuses for free, MXU-friendly
  from layer 1;
- stages [3, 4, 11, 3] for TResNet-M: BasicBlock in stages 1-2, Bottleneck
  in 3-4; widths 64/128/256/512;
- Leaky-ReLU (slope 1e-3) on activated ABNs; identity ABNs are plain BN;
- stride-2 paths are conv+ABN followed by the fixed 3x3 binomial blur-pool
  (timm AntiAliasDownsampleLayer: non-learned filter, stride 2, pad 1);
- shortcut downsample: 2x2 avg-pool (stride 2) then 1x1 conv + identity ABN;
- SE in stages 1-3 with timm's reduced widths: basic
  max(planes*exp//4, 64) on the block output, bottleneck
  max(planes*exp//8, 64) on the mid width between conv2 and conv3.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..ops.pallas_kernels import batch_norm_leaky_relu, fused_bn_leaky_relu

SLOPE = 1e-3  # TResNet's leaky-relu slope (inplace_abn activation_param)


class FusedABN(nn.Module):
    """BatchNorm + LeakyReLU as one Pallas kernel, with running stats kept in
    the `batch_stats` collection (flax BatchNorm conventions)."""

    momentum: float = 0.9
    epsilon: float = 1e-5
    slope: float = SLOPE
    use_running_average: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((c,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((c,), jnp.float32))
        if self.use_running_average:
            return fused_bn_leaky_relu(
                x, scale, bias, ra_mean.value, ra_var.value,
                self.epsilon, self.slope)
        y, mean, var = batch_norm_leaky_relu(
            x, scale, bias, self.epsilon, self.slope)
        if not self.is_initializing():
            ra_mean.value = self.momentum * ra_mean.value + (1 - self.momentum) * mean
            ra_var.value = self.momentum * ra_var.value + (1 - self.momentum) * var
        return y


def space_to_depth(x: jnp.ndarray, block: int = 4) -> jnp.ndarray:
    """(B, H, W, C) → (B, H/b, W/b, b²·C), channel order (bh, bw, c) —
    identical to timm SpaceToDepth's permute, so stem conv weights import."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // block, block, w // block, block, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // block, w // block, c * block * block)


class BlurPool(nn.Module):
    """Fixed 3×3 binomial depthwise blur, stride 2, pad (1,1) — timm's
    AntiAliasDownsampleLayer (the filter is a constant buffer, not a
    parameter); explicit torch-style padding keeps the sampling grid
    aligned with the checkpoint's training-time grid."""

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        import jax.lax as lax

        c = x.shape[-1]
        k2 = np.outer([1.0, 2.0, 1.0], [1.0, 2.0, 1.0])
        k2 /= k2.sum()
        kernel = jnp.asarray(np.tile(k2[:, :, None, None], (1, 1, 1, c)), x.dtype)
        return lax.conv_general_dilated(
            x, kernel, window_strides=(2, 2), padding=((1, 1), (1, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c,
        )


class SE(nn.Module):
    """Squeeze-excitation with an explicit reduced width (timm SEModule uses
    1×1 convs; Dense is the same contraction in NHWC — weights import with a
    squeeze)."""

    reduced: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        s = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        s = nn.relu(nn.Dense(self.reduced, name="fc1")(s))
        s = nn.sigmoid(nn.Dense(c, name="fc2")(s))
        return x * s[:, None, None, :].astype(x.dtype)


class TBasicBlock(nn.Module):
    filters: int
    strides: int
    use_se: bool
    abn: Any
    dtype: Any = jnp.bfloat16
    expansion: int = 1

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        residual = x
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME")
        use_ra = self.abn.keywords["use_running_average"]
        bn = functools.partial(nn.BatchNorm, use_running_average=use_ra,
                               momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        # timm: conv+ABN at stride 1, then anti-alias blur when downsampling
        y = self.abn(name="abn1")(conv(self.filters, (3, 3), name="conv1")(x))
        if self.strides == 2:
            y = BlurPool(name="aa")(y)
        y = conv(self.filters, (3, 3), name="conv2")(y)
        y = bn(name="bn2")(y)  # identity-activation ABN == plain BN
        if self.use_se:
            y = SE(reduced=max(self.filters * self.expansion // 4, 64),
                   name="se")(y)
        if residual.shape != y.shape:
            r = residual
            if self.strides == 2:
                # timm shortcut: AvgPool2d(2, 2, ceil_mode=True,
                # count_include_pad=False) — pad the odd edge only, exclude
                # the pad from the mean, so the shortcut's ceil(H/2) matches
                # BlurPool's padded output on odd dims
                h, w = r.shape[1], r.shape[2]
                r = nn.avg_pool(r, (2, 2), strides=(2, 2),
                                padding=((0, h % 2), (0, w % 2)),
                                count_include_pad=False)
            r = conv(self.filters * self.expansion, (1, 1), name="downsample")(r)
            residual = bn(name="bn_down")(r)
        return nn.leaky_relu(y + residual, SLOPE)


class TBottleneck(nn.Module):
    filters: int
    strides: int
    use_se: bool
    abn: Any
    dtype: Any = jnp.bfloat16
    expansion: int = 4

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        residual = x
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME")
        use_ra = self.abn.keywords["use_running_average"]
        bn = functools.partial(nn.BatchNorm, use_running_average=use_ra,
                               momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        y = self.abn(name="abn1")(conv(self.filters, (1, 1), name="conv1")(x))
        y = self.abn(name="abn2")(conv(self.filters, (3, 3), name="conv2")(y))
        if self.strides == 2:
            y = BlurPool(name="aa")(y)
        if self.use_se:
            # timm applies SE on the MID width between conv2 and conv3
            y = SE(reduced=max(self.filters * self.expansion // 8, 64),
                   name="se")(y)
        y = conv(self.filters * self.expansion, (1, 1), name="conv3")(y)
        y = bn(name="bn3")(y)
        if residual.shape != y.shape:
            r = residual
            if self.strides == 2:
                # ceil_mode avg-pool as in TBasicBlock (odd-dim parity with
                # the blurred main path)
                h, w = r.shape[1], r.shape[2]
                r = nn.avg_pool(r, (2, 2), strides=(2, 2),
                                padding=((0, h % 2), (0, w % 2)),
                                count_include_pad=False)
            r = conv(self.filters * self.expansion, (1, 1), name="downsample")(r)
            residual = bn(name="bn_down")(r)
        return nn.leaky_relu(y + residual, SLOPE)


class TResNet(nn.Module):
    """TResNet-M topology: stages [3,4,11,3], width factor 1."""

    num_classes: int = 0
    stages: Sequence[int] = (3, 4, 11, 3)
    width: float = 1.0
    dtype: Any = jnp.bfloat16
    feat_dim_out: int = 2048

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        abn = functools.partial(FusedABN, use_running_average=not train)
        w = int(64 * self.width)
        x = space_to_depth(x.astype(self.dtype), 4)
        x = nn.Conv(w, (3, 3), use_bias=False, dtype=self.dtype, padding="SAME",
                    name="stem_conv")(x)
        x = abn(name="stem_abn")(x)

        plan = [
            (TBasicBlock, w, 1, True),        # stage 1
            (TBasicBlock, w * 2, 2, True),    # stage 2
            (TBottleneck, w * 4, 2, True),    # stage 3 (SE)
            (TBottleneck, w * 8, 2, False),   # stage 4 (no SE)
        ]
        for s, (block, filters, stride, use_se) in enumerate(plan):
            for b in range(self.stages[s]):
                x = block(
                    filters=filters,
                    strides=stride if b == 0 else 1,
                    use_se=use_se,
                    abn=abn,
                    dtype=self.dtype,
                    name=f"stage{s + 1}_block{b}",
                )(x)

        x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        if self.num_classes:
            x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)
        return x


def tresnet_m(num_classes: int = 0, dtype=jnp.bfloat16, **_: Any) -> TResNet:
    return TResNet(num_classes=num_classes, dtype=dtype)
