"""Flax Vision Transformer backbones with sequence-parallel (ring) attention.

The reference's model zoo is all-convolutional (torchvision/timm backbones at
BASELINE/main.py:134-144, hand-written ResNets/VGG at NESTED/model/*.py — no
attention, no sequence axis, SURVEY §2.2). This family is the framework's
long-context extension: a standard ViT classifier whose token axis can shard
over the mesh `model` axis, with exact ring attention (ops/attention.py)
rotating KV shards over ICI. It slots into the same backbone contract as the
ResNet/VGG zoos — `num_classes=0` → pooled feature vector (the NetFeat role,
NESTED/model/model.py:12-61), else logits — so every workload head (fc /
arcface / nested) composes with it unchanged.

TPU-first choices:
- patch embedding is a stride-`patch` conv → one big MXU matmul;
- bf16 compute, f32 params / LayerNorm / softmax accumulators;
- mean-pool over tokens (no CLS token): pooling commutes with the sharded
  token axis, so the head never needs a gather from shard 0;
- static shapes end to end; the ring loop is a `lax.fori_loop`.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from ..ops.attention import ring_attention

# name → (patch, dim, depth, heads). feat dim == dim (backbone contract).
VIT_CONFIGS = {
    "vit_t16": (16, 192, 12, 3),
    "vit_s16": (16, 384, 12, 6),
    "vit_b16": (16, 768, 12, 12),
}
FEAT_DIMS = {name: dim for name, (_, dim, _, _) in VIT_CONFIGS.items()}


class MHA(nn.Module):
    """Multi-head self-attention over (B, T, C) tokens; ring-parallel when a
    mesh axis is configured (mesh/seq_axis are static module attrs);
    `use_flash` switches the unsharded path to the Pallas streaming kernel
    (ops/flash_attention.py)."""

    dim: int
    heads: int
    dtype: Any = jnp.bfloat16
    mesh: Optional[Any] = None
    seq_axis: Optional[str] = None
    use_flash: bool = False
    # unsharded-path auto-pick: below this (static) token count the dense
    # XLA op is used even when use_flash is set (0 = kernel always). The
    # ring path is exempt — see ModelConfig.flash_min_tokens.
    flash_min_tokens: int = 0

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        b, t, _ = x.shape
        d = self.dim // self.heads
        qkv = nn.Dense(3 * self.dim, dtype=self.dtype, name="qkv")(x)
        qkv = qkv.reshape(b, t, 3, self.heads, d)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        use_flash = self.use_flash and (
            self.seq_axis is not None or t >= self.flash_min_tokens)
        # ring_attention owns the whole dispatch: sharded token axis → ring
        # (with the flash kernel consuming each visiting KV shard when
        # use_flash), unsharded → direct flash or dense.
        out = ring_attention(q, k, v, mesh=self.mesh,
                             axis_name=self.seq_axis,
                             use_flash=use_flash)
        out = out.reshape(b, t, self.dim)
        return nn.Dense(self.dim, dtype=self.dtype, name="proj")(out)


class Block(nn.Module):
    """Pre-LN transformer block: LN→MHA→res, LN→FFN→res. The FFN is either
    the standard MLP(4×, GELU) or, with `moe_experts` > 0, a dropless
    split-FFN mixture-of-experts (ops/moe.py) whose experts shard over the
    mesh `moe_axis` — expert parallelism.

    `ln_bf16` runs the LayerNorms in the block compute dtype instead of
    f32 — a bandwidth experiment for the HBM-bound ViT step (VERDICT r3
    #5; the bench-scale A/B lives in scripts/ab_vit_perf.py). Params stay
    f32 either way; default remains the f32-LN recipe."""

    dim: int
    heads: int
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0
    mesh: Optional[Any] = None
    seq_axis: Optional[str] = None
    use_flash: bool = False
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_axis: Optional[str] = None
    flash_min_tokens: int = 0
    ln_bf16: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        ln_dtype = self.dtype if self.ln_bf16 else jnp.float32
        y = nn.LayerNorm(dtype=ln_dtype, name="ln1")(x).astype(self.dtype)
        x = x + MHA(self.dim, self.heads, self.dtype, self.mesh,
                    self.seq_axis, self.use_flash,
                    self.flash_min_tokens, name="attn")(y)
        y = nn.LayerNorm(dtype=ln_dtype, name="ln2")(x).astype(self.dtype)
        if self.moe_experts > 0:
            from ..ops.moe import (
                load_balance_loss,
                moe_mlp,
                router_logits,
                topk_gates,
            )
            from ..parallel.mesh import DATA_AXIS

            e = self.moe_experts
            if self.dropout:
                raise ValueError(
                    "moe_experts does not support dropout (the expert mix "
                    "has no dropout slot); set --dropout 0")
            if (4 * self.dim) % e:
                raise ValueError(
                    f"moe_experts={e} must divide the FFN hidden width "
                    f"{4 * self.dim} (split-FFN param/FLOP parity)")
            hidden = (4 * self.dim) // e  # split-FFN: total params/FLOPs
            # match the dense MLP; routing redistributes capacity
            init = nn.initializers.xavier_uniform()
            router = self.param("moe_router", init, (self.dim, e), jnp.float32)
            w_in = self.param("moe_w_in", init, (e, self.dim, hidden), jnp.float32)
            b_in = self.param("moe_b_in", nn.initializers.zeros, (e, hidden), jnp.float32)
            w_out = self.param("moe_w_out", init, (e, hidden, self.dim), jnp.float32)
            b_out = self.param("moe_b_out", nn.initializers.zeros, (e, self.dim), jnp.float32)
            # batch sharding only when it divides (model.init's 2-sample
            # dummy batch doesn't; correctness never depends on it)
            dp = (self.mesh.shape.get(DATA_AXIS, 1)
                  if self.mesh is not None else 1)
            batch_axis = (DATA_AXIS
                          if dp > 1 and y.shape[0] % dp == 0 else None)
            # one router evaluation feeds both the gates and the balance
            # penalty (harvested by the train step via the 'losses'
            # collection; sow accumulates across blocks)
            logits = router_logits(y, router)
            gates = topk_gates(logits, self.moe_top_k)
            self.sow("losses", "moe_aux",
                     load_balance_loss(logits, self.moe_top_k))
            y = moe_mlp(y, gates, w_in, b_in, w_out, b_out,
                        dtype=self.dtype,
                        mesh=self.mesh if self.moe_axis else None,
                        axis=self.moe_axis, batch_axis=batch_axis)
        else:
            y = nn.Dense(4 * self.dim, dtype=self.dtype, name="mlp_in")(y)
            y = nn.gelu(y)
            if self.dropout:
                y = nn.Dropout(self.dropout, deterministic=not train)(y)
            y = nn.Dense(self.dim, dtype=self.dtype, name="mlp_out")(y)
        return x + y


class ViT(nn.Module):
    """ViT backbone → pooled feature (num_classes=0) or logits.

    `seq_axis` + `mesh` switch every attention layer to ring attention with
    tokens sharded over that mesh axis. Token count (image_size/patch)² must
    then be divisible by the axis size.
    """

    patch: int = 16
    dim: int = 384
    depth: int = 12
    heads: int = 6
    num_classes: int = 0
    dtype: Any = jnp.bfloat16
    dropout: float = 0.0
    mesh: Optional[Any] = None
    seq_axis: Optional[str] = None
    remat: bool = False
    use_flash: bool = False
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_axis: Optional[str] = None
    flash_min_tokens: int = 0
    ln_bf16: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        x = x.astype(self.dtype)
        x = nn.Conv(self.dim, (self.patch, self.patch),
                    strides=(self.patch, self.patch), padding="VALID",
                    dtype=self.dtype, name="patch_embed")(x)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, h * w, self.dim), jnp.float32)
        x = x + pos.astype(self.dtype)
        if self.remat:
            # checkpoint the blocks but keep every matmul (dot) output
            # saved: the ViT's recompute cost is dominated by its matmuls,
            # so the checkpoint_dots policy trades ~all of the activation
            # memory the elementwise/LN chains hold for near-zero extra
            # FLOPs — the remat policy VERDICT r3 #5 asks to exercise.
            import jax as _jax

            block_cls = nn.remat(
                Block, static_argnums=(2,),
                policy=_jax.checkpoint_policies.checkpoint_dots)
        else:
            block_cls = Block
        for i in range(self.depth):
            x = block_cls(self.dim, self.heads, self.dtype, self.dropout,
                          self.mesh, self.seq_axis, self.use_flash,
                          self.moe_experts, self.moe_top_k, self.moe_axis,
                          self.flash_min_tokens, self.ln_bf16,
                          name=f"block{i}")(x, train)
        # ln_final stays f32 even under --ln_bf16: its output feeds only the
        # f32 pool/head, so a bf16 affine here buys no matmul throughput and
        # just rounds the logits' inputs (dtype audit D6)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        # token mean-pool; shard-friendly (see module doc). f32 output: the
        # pool feeds the f32 head, so rounding the mean back to the compute
        # dtype would only discard mantissa bits in between (dtype audit D6)
        x = x.mean(axis=1, dtype=jnp.float32)
        if self.num_classes > 0:
            x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)
        return x


def build_vit(arch: str, num_classes: int = 0, dtype: Any = jnp.bfloat16,
              dropout: float = 0.0, mesh: Optional[Any] = None,
              seq_axis: Optional[str] = None, remat: bool = False,
              use_flash: bool = False, moe_experts: int = 0,
              moe_top_k: int = 2, moe_axis: Optional[str] = None,
              flash_min_tokens: int = 0, ln_bf16: bool = False) -> ViT:
    patch, dim, depth, heads = VIT_CONFIGS[arch]
    return ViT(patch=patch, dim=dim, depth=depth, heads=heads,
               num_classes=num_classes, dtype=dtype, dropout=dropout,
               mesh=mesh, seq_axis=seq_axis, remat=remat,
               use_flash=use_flash, moe_experts=moe_experts,
               moe_top_k=moe_top_k, moe_axis=moe_axis,
               flash_min_tokens=flash_min_tokens, ln_bf16=ln_bf16)
