from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152, FEAT_DIMS
from .vgg import VGG, vgg19_bn
from .heads import FCHead, ArcEmbedding, ArcMarginHead, NetClassifier
from .factory import build_backbone, build_model

__all__ = [
    "ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "FEAT_DIMS", "VGG", "vgg19_bn",
    "FCHead", "ArcEmbedding", "ArcMarginHead", "NetClassifier",
    "build_backbone", "build_model",
]
