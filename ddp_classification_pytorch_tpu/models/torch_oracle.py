"""Torch oracles for checkpoint-import verification.

From-scratch torch implementations of the torchvision ResNet topology
(v1.5: stride on the Bottleneck's 3x3 conv), torchvision vgg19_bn, and
timm tresnet_m — each with its upstream parameter naming (`conv1`,
`layer1.0.conv1`, `downsample.0/1`, `features.<seq>`, `body.layerL.B`…),
so their `state_dict()`s are exactly the formats the
`models/import_torch` converters consume.

Two consumers:
- the parity tests (tests/test_torch_oracle_parity.py): randomize every
  parameter AND buffer, push the state_dict through the converter, and
  require full-model flax-vs-torch forward equality — the strongest
  offline proxy for "pretrained torchvision/timm checkpoints load
  correctly" in a zero-egress sandbox;
- `cli.verify_import`: the same equality check against a REAL `.pth`
  the moment one exists on disk (VERDICT r3 #8) — the oracle loads the
  real state_dict, so the comparison then verifies true pretrained
  weights, not randomized stand-ins.

Reference role of the weights being verified: every reference trainer
defaults to pretrained torchvision models (BASELINE/main.py:135,
CDR/main.py:330, NESTED/model/imagenet_resnet.py:195-203). torch is a
host-side verification dependency only — nothing on the TPU path
imports it; callers import this module lazily. This file re-types
public architectures from their published definitions; it is not a copy
of the reference's `NESTED/model/imagenet_resnet.py` (that file carries
extra vestigial buffers and a custom forward these oracles deliberately
omit).
"""

from __future__ import annotations

import torch
import torch.nn as nn


class BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, inplanes: int, planes: int, stride: int = 1,
                 downsample: nn.Module | None = None):
        super().__init__()
        self.conv1 = nn.Conv2d(inplanes, planes, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = torch.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return torch.relu(out + identity)


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, inplanes: int, planes: int, stride: int = 1,
                 downsample: nn.Module | None = None):
        super().__init__()
        self.conv1 = nn.Conv2d(inplanes, planes, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        # v1.5: the stride lives on the 3x3, matching models/resnet.py
        self.conv2 = nn.Conv2d(planes, planes, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, planes * self.expansion, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * self.expansion)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = torch.relu(self.bn1(self.conv1(x)))
        out = torch.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return torch.relu(out + identity)


class TorchResNet(nn.Module):
    def __init__(self, block, layers, num_classes: int = 1000):
        super().__init__()
        self.inplanes = 64
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes: int, blocks: int, stride: int = 1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2d(self.inplanes, planes * block.expansion, 1, stride,
                          bias=False),
                nn.BatchNorm2d(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = torch.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        x = x.mean(dim=(2, 3))  # adaptive avg pool to 1x1, flattened
        return self.fc(x)


_DEPTHS = {
    "resnet18": (BasicBlock, [2, 2, 2, 2]),
    "resnet34": (BasicBlock, [3, 4, 6, 3]),
    "resnet50": (Bottleneck, [3, 4, 6, 3]),
    "resnet101": (Bottleneck, [3, 4, 23, 3]),
    "resnet152": (Bottleneck, [3, 8, 36, 3]),
}


def make_torch_resnet(arch: str, num_classes: int = 1000) -> TorchResNet:
    block, layers = _DEPTHS[arch]
    return TorchResNet(block, layers, num_classes)


def randomize_(model: TorchResNet, seed: int = 0) -> None:
    """Randomize every parameter AND buffer so the parity check can catch
    any mapping swap. Torch's defaults would mask whole bug classes:
    running_mean=0/var=1 hides a mean<->var swap, BN weight=1/bias=0 hides
    a scale<->bias swap."""
    gen = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for name, p in model.named_parameters():
            if p.ndim >= 2:  # conv / linear weights, fan-in scaled so
                # activations stay O(1) — unscaled noise compounds to ~1e6
                # by layer4 and f32 accumulation noise then swamps tight
                # tolerances
                fan_in = p.numel() // p.shape[0]
                p.normal_(0.0, fan_in ** -0.5, generator=gen)
            elif "weight" in name:  # BN scale
                p.uniform_(0.5, 1.5, generator=gen)
            else:  # biases
                p.normal_(0.0, 0.1, generator=gen)
        for name, b in model.named_buffers():
            if name.endswith("running_mean"):
                b.normal_(0.0, 0.2, generator=gen)
            elif name.endswith("running_var"):
                b.uniform_(0.5, 2.0, generator=gen)


class TorchVGG19BN(nn.Module):
    """torchvision vgg19_bn topology with its parameter naming
    (features.<seq>.*, classifier.{0,3,6}.*), re-typed for the same
    zero-egress reason as TorchResNet. Reference role:
    NESTED/model/vgg.py:10-76 wraps exactly this torchvision model."""

    CFG_E = [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
             512, 512, 512, 512, "M", 512, 512, 512, 512, "M"]

    def __init__(self, num_classes: int = 1000):
        super().__init__()
        layers, c_in = [], 3
        for v in self.CFG_E:
            if v == "M":
                layers.append(nn.MaxPool2d(2, 2))
            else:
                layers += [nn.Conv2d(c_in, v, 3, padding=1),
                           nn.BatchNorm2d(v), nn.ReLU(inplace=True)]
                c_in = v
        self.features = nn.Sequential(*layers)
        self.classifier = nn.Sequential(
            nn.Linear(512 * 7 * 7, 4096), nn.ReLU(inplace=True), nn.Dropout(),
            nn.Linear(4096, 4096), nn.ReLU(inplace=True), nn.Dropout(),
            nn.Linear(4096, num_classes),
        )

    def forward(self, x):
        x = self.features(x)
        return self.classifier(torch.flatten(x, 1))


def make_torch_vgg19_bn(num_classes: int = 1000) -> TorchVGG19BN:
    return TorchVGG19BN(num_classes)


# ------------------------------------------------------ TResNet-M oracle ---
# timm `tresnet_m` topology with timm's parameter naming (body.conv1.{0,1},
# body.layerL.B.convJ.* with the stride-2 conv wrapped as (Sequential(conv,
# bn), blur.filt), se.fc1/fc2 as 1x1 convs, downsample.1.{0,1}, head.fc) —
# the exact key layout convert_tresnet_state_dict consumes. Re-typed from
# the published architecture; the reference selects this model via
# BASELINE/main.py:141-144.

TRESNET_SLOPE = 1e-3


class _SpaceToDepth(nn.Module):
    def forward(self, x):
        b, c, h, w = x.shape
        x = x.view(b, c, h // 4, 4, w // 4, 4)
        x = x.permute(0, 3, 5, 1, 2, 4).contiguous()
        return x.view(b, c * 16, h // 4, w // 4)


class _Blur(nn.Module):
    """Fixed 3x3 binomial depthwise blur, stride 2, pad 1 (buffer `filt`)."""

    def __init__(self, channels):
        super().__init__()
        k = torch.tensor([1.0, 2.0, 1.0])
        k2 = torch.outer(k, k)
        k2 = (k2 / k2.sum()).expand(channels, 1, 3, 3).contiguous()
        self.register_buffer("filt", k2)
        self.channels = channels

    def forward(self, x):
        return torch.nn.functional.conv2d(
            x, self.filt, stride=2, padding=1, groups=self.channels)


def _conv_abn(c_in, c_out, k, activated, aa=False):
    pad = k // 2
    inner = [nn.Conv2d(c_in, c_out, k, 1, pad, bias=False),
             nn.BatchNorm2d(c_out)]
    if activated:
        inner.append(nn.LeakyReLU(TRESNET_SLOPE, inplace=True))
    if aa:
        return nn.Sequential(nn.Sequential(*inner), _Blur(c_out))
    return nn.Sequential(*inner)


class _SE(nn.Module):
    def __init__(self, channels, reduced):
        super().__init__()
        self.fc1 = nn.Conv2d(channels, reduced, 1)
        self.fc2 = nn.Conv2d(reduced, channels, 1)

    def forward(self, x):
        s = x.mean(dim=(2, 3), keepdim=True)
        s = torch.sigmoid(self.fc2(torch.relu(self.fc1(s))))
        return x * s


def _downsample(c_in, c_out):
    return nn.Sequential(
        nn.AvgPool2d(2, 2, ceil_mode=True, count_include_pad=False),
        nn.Sequential(nn.Conv2d(c_in, c_out, 1, 1, bias=False),
                      nn.BatchNorm2d(c_out)),
    )


class _TBasic(nn.Module):
    expansion = 1

    def __init__(self, c_in, planes, stride, use_se):
        super().__init__()
        self.conv1 = _conv_abn(c_in, planes, 3, True, aa=(stride == 2))
        self.conv2 = _conv_abn(planes, planes, 3, False)
        self.se = _SE(planes, max(planes // 4, 64)) if use_se else None
        self.downsample = (_downsample(c_in, planes)
                           if stride == 2 or c_in != planes else None)

    def forward(self, x):
        y = self.conv2(self.conv1(x))
        if self.se is not None:
            y = self.se(y)
        r = x if self.downsample is None else self.downsample(x)
        return torch.nn.functional.leaky_relu(y + r, TRESNET_SLOPE)


class _TBottleneck(nn.Module):
    expansion = 4

    def __init__(self, c_in, planes, stride, use_se):
        super().__init__()
        self.conv1 = _conv_abn(c_in, planes, 1, True)
        self.conv2 = _conv_abn(planes, planes, 3, True, aa=(stride == 2))
        self.se = (_SE(planes, max(planes * self.expansion // 8, 64))
                   if use_se else None)
        self.conv3 = _conv_abn(planes, planes * self.expansion, 1, False)
        self.downsample = (
            _downsample(c_in, planes * self.expansion)
            if stride == 2 or c_in != planes * self.expansion else None)

    def forward(self, x):
        y = self.conv2(self.conv1(x))
        if self.se is not None:
            y = self.se(y)
        y = self.conv3(y)
        r = x if self.downsample is None else self.downsample(x)
        return torch.nn.functional.leaky_relu(y + r, TRESNET_SLOPE)


class TorchTResNetM(nn.Module):
    def __init__(self, num_classes: int = 1000):
        super().__init__()
        import collections

        w = 64

        def stage(block, c_in, planes, n, stride, use_se):
            blocks = [block(c_in, planes, stride, use_se)]
            for _ in range(1, n):
                blocks.append(block(planes * block.expansion, planes, 1, use_se))
            return nn.Sequential(*blocks)

        self.s2d = _SpaceToDepth()
        self.body = nn.Sequential(collections.OrderedDict([
            ("conv1", _conv_abn(48, w, 3, True)),
            ("layer1", stage(_TBasic, w, w, 3, 1, True)),
            ("layer2", stage(_TBasic, w, w * 2, 4, 2, True)),
            ("layer3", stage(_TBottleneck, w * 2, w * 4, 11, 2, True)),
            ("layer4", stage(_TBottleneck, w * 16, w * 8, 3, 2, False)),
        ]))
        self.head = nn.Module()
        self.head.fc = nn.Linear(w * 8 * 4, num_classes)

    def forward(self, x):
        x = self.body(self.s2d(x))
        x = x.mean(dim=(2, 3))
        return self.head.fc(x)


def make_torch_tresnet_m(num_classes: int = 1000) -> TorchTResNetM:
    return TorchTResNetM(num_classes)
