"""Unified CLI — replaces the reference's four launch stacks.

Reference (SURVEY L6): `torch.distributed.launch --nproc_per_node=N main.py
--world_size=N --local_rank …` per silo (BASELINE/train.sh:1,
ARCFACE/arc_train.sh:1, CDR/train.sh:1-4, NESTED/train.sh:1-7). On TPU there
is no process-per-device launcher: ONE process per host sees all local chips,
and `jax.distributed.initialize()` is the only multi-host setup. So
`--nproc_per_node/--world_size/--local_rank` cease to exist by design — the
`--device` branch the north star asks for is the `--platform` flag here.

Every behavior-affecting reference flag maps to a field of the Config tree:

    python -m ddp_classification_pytorch_tpu.cli.train baseline \
        --folder /data/food --batchsize 16 --model resnet50 --lr 0.001
    python -m ddp_classification_pytorch_tpu.cli.train arcface  --optimizer adam
    python -m ddp_classification_pytorch_tpu.cli.train cdr      --noise_rate 0.2
    python -m ddp_classification_pytorch_tpu.cli.train nested   --nested 100 \
        --warmUpIter 10000 --freeze-bn
"""

from __future__ import annotations

import argparse
import os
from typing import Optional, Sequence

from ..config import Config, get_preset


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ddp_classification_pytorch_tpu.cli.train",
        description="TPU-native classification training (all reference workloads)",
    )
    p.add_argument("workload", choices=["baseline", "arcface", "cdr", "nested", "plc"],
                   help="which reference silo's recipe to run")

    d = p.add_argument_group("data")
    d.add_argument("--folder", "-f", default="", help="dataset root containing "
                   "train/val dirs (reference --folder, BASELINE/main.py:27)")
    d.add_argument("--train_dir", default="", help="explicit train dir (overrides --folder)")
    d.add_argument("--val_dir", default="", help="explicit val dir (overrides --folder)")
    d.add_argument("--dataset", default="",
                   help="imagefolder | synthetic | plc | cifar10 | cifar100")
    d.add_argument("--synthetic_size", type=int, default=0,
                   help="train-set size for --dataset synthetic (default "
                        "512); drills shrink it so multi-process restart "
                        "cycles stay control-path-bound, not compute-bound")
    d.add_argument("--batchsize", "-b", type=int, default=0,
                   help="PER-HOST batch size; the global batch is "
                   "batchsize × num_hosts (cf. reference per-GPU batch, "
                   "BASELINE/main.py:29)")
    d.add_argument("--num_classes", type=int, default=0)
    d.add_argument("--imgs_per_class", type=int, default=0,
                   help="per-class cap (500 baseline / 400 arcface)")
    d.add_argument("--num_workers", type=int, default=0, help="host loader threads")
    d.add_argument("--device_prefetch", type=int, default=-1,
                   help="device batches staged ahead of the step loop by a "
                        "background H2D stager thread (default 2; each "
                        "staged batch holds device memory; 0 = synchronous "
                        "assembly inside the step loop)")
    d.add_argument("--h2d-overlap", dest="h2d_overlap", action="store_true",
                   help="double-buffered H2D dispatch: fetch host batch N+1 "
                        "on a separate thread while batch N's "
                        "make_global_array transfer is in flight (one-slot "
                        "in-flight budget; ignored at --device_prefetch 0)")
    d.add_argument("--image_size", type=int, default=0)
    d.add_argument("--crop_size", type=int, default=0,
                   help="train-crop / resize-short side (default 256, the "
                        "reference's RandomResizedCrop(256); set ~= "
                        "--image_size for small-image folders)")
    d.add_argument("--transform", default="",
                   help="transform preset for imagefolder data: baseline | "
                        "cdr | cifar | clothing1m (default: workload preset; "
                        "'cifar' = pad-4 random crop + flip at --image_size, "
                        "for small-image folders)")
    d.add_argument("--input_dtype", default="", choices=["", "uint8", "float32"],
                   help="H2D wire format (default uint8): 'uint8' ships raw "
                        "pixels at ¼ the bytes and fuses normalization + the "
                        "train flip into the jitted step; 'float32' is the "
                        "legacy host-normalize path, numerically exact to "
                        "the pre-uint8 framework")

    m = p.add_argument_group("model")
    m.add_argument("--model", "--arch", dest="model", default="",
                   help="resnet18/34/50/101/152 | vgg19_bn | tresnet_m | "
                        "vit_t16/s16/b16 (reference --model + extensions)")
    m.add_argument("--flash_attention", action="store_true",
                   help="ViT: Pallas streaming attention kernel for the "
                        "unsharded path")
    m.add_argument("--flash_min_tokens", type=int, default=-1,
                   help="auto-pick floor: below this token count "
                        "--flash_attention uses XLA's fused dense attention "
                        "instead of the kernel (default 1024, the measured "
                        "v5e crossover region; 0 = kernel always)")
    m.add_argument("--ln_bf16", action="store_true",
                   help="ViT: LayerNorms in bf16 instead of f32 (bandwidth "
                        "experiment; scripts/ab_vit_perf.py measures it)")
    m.add_argument("--variant", default="", help="imagenet | cifar stem")
    m.add_argument("--pretrained", action="store_true",
                   help="load converted torchvision weights")
    m.add_argument("--pretrained_path", default="",
                   help=".pth/.pt torch checkpoint to import (torchvision "
                   "state_dict or NESTED {'feat','cls'} format)")
    m.add_argument("--dtype", default="", help="bfloat16 | float32 compute dtype")
    m.add_argument("--dropout", type=float, default=-1.0)
    m.add_argument("--remat", action="store_true",
                   help="rematerialize residual blocks (trade FLOPs for HBM; "
                   "enables larger global batches)")

    o = p.add_argument_group("optimization")
    o.add_argument("--optimizer", default="", help="sgd | adam (arc_main.py:34-43)")
    o.add_argument("--lr", type=float, default=0.0)
    o.add_argument("--momentum", type=float, default=-1.0)
    o.add_argument("--weight_decay", type=float, default=-1.0)
    o.add_argument("--epochs", type=int, default=0)
    o.add_argument("--lrSchedule", type=int, nargs="*", default=None,
                   help="multistep milestones (NESTED/train.py:472)")
    o.add_argument("--warmUpIter", type=int, default=-1,
                   help="linear warmup iterations (NESTED/train.py:466)")

    a = p.add_argument_group("arcface")
    a.add_argument("--arc_s", type=float, default=-1.0)
    a.add_argument("--arc_m", type=float, default=-1.0)
    a.add_argument("--head_lr", type=float, default=-1.0,
                   help="lr for the margin-head param group (reference's "
                        "optimizer group 2, arc_main.py:248-253); unset = "
                        "inherit --lr")
    a.add_argument("--head_weight_decay", type=float, default=-1.0,
                   help="weight decay for the margin-head param group; "
                        "unset = inherit --weight_decay")
    a.add_argument("--easy_margin", dest="easy_margin", default=None,
                   action="store_true")

    c = p.add_argument_group("cdr")
    c.add_argument("--noise_rate", type=float, default=-1.0, help="CDR/main.py:37")
    c.add_argument("--num_gradual", type=int, default=-1, help="CDR/main.py:41")
    c.add_argument("--live_clip_schedule", action="store_true",
                   help="use the reference's INTENDED gradual clip schedule "
                   "instead of its actual dead-code constant (CDR/main.py:222-227)")

    n = p.add_argument_group("nested")
    n.add_argument("--nested", type=float, default=-1.0,
                   help="Gaussian σ over feature dims (NESTED/train.py:512-530)")
    n.add_argument("--freeze-bn", dest="freeze_bn", default=None, action="store_true")
    n.add_argument("--no-freeze-bn", dest="freeze_bn", action="store_false",
                   help="train BN normally (the preset's freeze-BN mirrors "
                        "NESTED/train.py:529, which assumes a pretrained "
                        "backbone; from-scratch runs want live BN)")
    n.add_argument("--resumePth", default="", help="NESTED/train.py:481")

    pl = p.add_argument_group("plc")
    pl.add_argument("--correction", default="", choices=["", "lrt", "prob"],
                    help="label-correction method (PLC/utils.py:291,321)")
    pl.add_argument("--delta", type=float, default=-1.0, help="initial θ threshold")
    pl.add_argument("--delta_increment", type=float, default=-1.0, help="β step")
    pl.add_argument("--thd", type=float, default=-1.0, help="prob-correction confidence")
    pl.add_argument("--plc_warmup_epochs", type=int, default=-1)
    pl.add_argument("--plc_max_flip_frac", type=float, default=-1.0,
                    help="cap the label fraction one correction pass may "
                         "flip, keeping the most-confident flips; guards "
                         "against self-confirming collapse on an immature "
                         "model (1.0 = uncapped reference semantics)")
    pl.add_argument("--plc_batch_stat_predictions", action="store_true",
                    help="harvest correction f(x) with each batch's own BN "
                         "statistics (the reference's during-training "
                         "flavor, PLC/utils.py:269-271); UNSAFE on the "
                         "default class-sorted scan — measured 63%% vs 99%% "
                         "prediction accuracy vs the running-stat default")

    r = p.add_argument_group("run")
    r.add_argument("--seed", type=int, default=-1)
    r.add_argument("--out", default="", help="output dir (records + checkpoints)")
    r.add_argument("--resume", default="", help="checkpoint path to resume from")
    r.add_argument("--auto_resume", action="store_true",
                   help="resume from the latest checkpoint in --out if any "
                        "(preemption recovery; see scripts/supervise.sh)")
    r.add_argument("--tensorboard", action="store_true",
                   help="write TensorBoard event files to <out>/tb "
                        "(dependency-free writer, utils/tensorboard.py)")
    r.add_argument("--log_every", type=int, default=0)
    r.add_argument("--save_best_only", action="store_true")
    r.add_argument("--keep_checkpoints", type=int, default=0,
                   help="prune per-epoch checkpoints beyond the newest N "
                        "(0 = keep all; ckpt_best is always kept)")
    r.add_argument("--profile_steps", type=int, default=0,
                   help="capture a jax.profiler trace of N train steps")
    r.add_argument("--debug_nans", action="store_true",
                   help="enable jax_debug_nans (fail fast on NaN)")
    r.add_argument("--hang_timeout_s", type=float, default=0.0,
                   help="mid-run hang watchdog: exit 7 when no host-observed "
                        "progress lands for this many seconds, so "
                        "supervise.sh + --auto_resume can recover (0 = off; "
                        "set WELL above the slowest compile — 900+ for "
                        "tunneled TPU, more for TResNet)")
    r.add_argument("--max_bad_steps", type=int, default=-1,
                   help="non-finite step sentinel: every train step skips "
                        "its update (identity) when loss/grad-norm go "
                        "NaN/Inf; after N CONSECUTIVE skips exit 8 "
                        "('diverged' — deterministic, supervise.sh does "
                        "not restart it). Default 25; 0 = skip forever, "
                        "never exit")
    r.add_argument("--strict_compile", action="store_true",
                   help="make a steady-state recompile fatal (rc 2 at the "
                        "epoch boundary): after the first eval'd epoch a "
                        "compile sentinel treats any further XLA compile as "
                        "a signature drift; default logs it warn-only "
                        "(analysis/compile_sentinel.py)")
    r.add_argument("--fault_spec", default="",
                   help="deterministic fault injection (utils/chaos.py), "
                        "e.g. 'nan_loss@step=7..9,ckpt_io@epoch=1,"
                        "loader_io@batch=3,sigterm@step=20'; "
                        "CHAOS_FAULT_SPEC env overrides; see "
                        "scripts/chaos_drill.sh")
    r.add_argument("--grad_accum", type=int, default=0,
                   help="microbatch accumulation factor")
    r.add_argument("--platform", default="", choices=["", "tpu", "cpu"],
                   help="force a JAX platform (the north star's --device branch); "
                   "default: whatever jax finds (TPU when present)")

    par = p.add_argument_group("parallelism")
    par.add_argument("--dp", type=int, default=0,
                     help="data-parallel mesh axis size (0 = all devices)")
    par.add_argument("--mp", type=int, default=0,
                     help="model-parallel axis (class-dim sharding of wide "
                          "heads; ring-attention seq sharding for ViT; "
                          "pipeline stages with --pp_microbatches)")
    par.add_argument("--pp_microbatches", type=int, default=0,
                     help="enable GPipe pipelining of the ViT block stack "
                          "over the model axis with N microbatches")
    par.add_argument("--pp_stages", type=int, default=0,
                     help="give the pipeline its OWN mesh axis with N "
                          "stages (3-axis dp×tp×pp mesh), composing with "
                          "--mp class-dim TP; devices = dp×mp×N")
    par.add_argument("--dcn_slices", type=int, default=0,
                     help="multi-slice pods: two-tier mesh with DP across "
                          "N DCN-connected slices, model axis on ICI")
    par.add_argument("--moe_experts", type=int, default=0,
                     help="ViT: dropless split-FFN mixture-of-experts with "
                          "N experts per block; with --mp > 1 the experts "
                          "shard over the model axis (expert parallelism)")
    par.add_argument("--moe_top_k", type=int, default=2,
                     help="router top-k for --moe_experts")
    par.add_argument("--moe_aux_weight", type=float, default=None,
                     help="router load-balance penalty weight "
                          "(default 0.01; 0 disables)")
    par.add_argument("--sharded_ce", action="store_true",
                     help="arcface: partial-FC loss — class-sharded "
                          "softmax-CE over the model axis, no (B, C) "
                          "logits (needs --mp > 1, classes divisible)")
    par.add_argument("--zero_opt", default="",
                     choices=["", "auto", "on", "off"],
                     help="ZeRO-1: partition optimizer state over the data "
                          "axis (reduce-scatter grads, shard-local update, "
                          "all-gather params); 'auto' (the default) enables "
                          "it whenever the data axis spans >1 device")
    par.add_argument("--grad_reduce_dtype", default="",
                     choices=["", "float32", "bfloat16"],
                     help="wire dtype of the cross-replica gradient "
                          "reduction; bfloat16 halves the payload, master "
                          "params/momentum stay f32 (torch-AMP-style)")
    par.add_argument("--multihost", action="store_true",
                     help="call jax.distributed.initialize() (TPU pods)")

    compat = p.add_argument_group("reference-CLI compatibility (ignored)")
    compat.add_argument("--world_size", type=int, default=None,
                        help="ignored: TPU meshes derive their size from the "
                        "hardware; parallelism is --dp/--mp")
    compat.add_argument("--local_rank", type=int, default=None,
                        help="ignored: no per-device processes on TPU; one "
                        "process per host sees all local chips")
    compat.add_argument("--gpu", default=None,
                        help="ignored: device selection is the backend's "
                        "(CDR/main.py:51, NESTED/train.py:473 pass it; "
                        "scripted reference invocations must not break)")
    return p


def config_from_args(args: argparse.Namespace) -> Config:
    cfg = get_preset(args.workload)

    if args.folder:
        cfg.data.train_dir = f"{args.folder}/train"
        cfg.data.val_dir = f"{args.folder}/val"
    if args.train_dir:
        cfg.data.train_dir = args.train_dir
    if args.val_dir:
        cfg.data.val_dir = args.val_dir
    if args.dataset:
        cfg.data.dataset = args.dataset
        if args.dataset in ("cifar10", "cifar100"):
            # CIFAR facts override the preset's ImageNet-ish defaults unless
            # the user explicitly passes the flags
            if not args.num_classes:
                cfg.data.num_classes = 10 if args.dataset == "cifar10" else 100
            if not args.image_size:
                cfg.data.image_size = 32
            if not args.variant:
                cfg.model.variant = "cifar"
    if args.synthetic_size:
        cfg.data.synthetic_size = args.synthetic_size
    if args.batchsize:
        cfg.data.batch_size = args.batchsize
    if args.num_classes:
        cfg.data.num_classes = args.num_classes
    if args.imgs_per_class:
        cfg.data.imgs_per_class = args.imgs_per_class
    if args.num_workers:
        cfg.data.num_workers = args.num_workers
    if args.device_prefetch >= 0:
        cfg.data.device_prefetch = args.device_prefetch
    if args.h2d_overlap:
        cfg.data.h2d_overlap = True
    if args.image_size:
        cfg.data.image_size = args.image_size
    if args.crop_size:
        cfg.data.train_crop_size = args.crop_size
    if args.transform:
        cfg.data.transform = args.transform
    if args.input_dtype:
        cfg.data.input_dtype = args.input_dtype

    if args.model:
        cfg.model.arch = args.model
    if args.flash_attention:
        cfg.model.flash_attention = True
    if args.ln_bf16:
        cfg.model.ln_bf16 = True
    if args.flash_min_tokens >= 0:
        cfg.model.flash_min_tokens = args.flash_min_tokens
    if args.variant:
        cfg.model.variant = args.variant
    if args.pretrained:
        cfg.model.pretrained = True
    if args.pretrained_path:
        cfg.model.pretrained = True
        cfg.model.pretrained_path = args.pretrained_path
    if args.dtype:
        cfg.model.dtype = args.dtype
    if args.dropout >= 0:
        cfg.model.dropout = args.dropout
    if args.remat:
        cfg.model.remat = True
    if args.arc_s >= 0:
        cfg.model.arc_s = args.arc_s
    if args.arc_m >= 0:
        cfg.model.arc_m = args.arc_m
    if args.easy_margin is not None:
        cfg.model.arc_easy_margin = args.easy_margin
    if args.nested >= 0:
        cfg.model.nested_std = args.nested
    if args.freeze_bn is not None:
        cfg.model.freeze_bn = args.freeze_bn

    if args.optimizer:
        cfg.optim.optimizer = args.optimizer
    if args.lr:
        cfg.optim.lr = args.lr
    if args.momentum >= 0:
        cfg.optim.momentum = args.momentum
    if args.weight_decay >= 0:
        cfg.optim.weight_decay = args.weight_decay
    if args.head_lr >= 0:
        cfg.optim.head_lr = args.head_lr
    if args.head_weight_decay >= 0:
        cfg.optim.head_weight_decay = args.head_weight_decay
    if args.lrSchedule is not None:
        cfg.optim.schedule = "multistep"
        cfg.optim.milestones = tuple(args.lrSchedule)
    if args.warmUpIter >= 0:
        cfg.optim.warmup_iters = args.warmUpIter
    if args.noise_rate >= 0:
        cfg.optim.noise_rate = args.noise_rate
    if args.num_gradual >= 0:
        cfg.optim.num_gradual = args.num_gradual
    if args.live_clip_schedule:
        cfg.optim.cdr_dead_schedule = False

    if args.epochs:
        cfg.run.epochs = args.epochs
    if args.seed >= 0:
        cfg.run.seed = args.seed
    if args.out:
        cfg.run.out_dir = args.out
    if args.resume or args.resumePth:
        cfg.run.resume = args.resume or args.resumePth
    if args.auto_resume:
        cfg.run.auto_resume = True
    if args.tensorboard:
        cfg.run.tensorboard = True
    if args.log_every:
        cfg.run.log_every = args.log_every
    if args.save_best_only:
        cfg.run.save_best_only = True
    if args.keep_checkpoints:
        cfg.run.keep_checkpoints = args.keep_checkpoints
    if args.profile_steps:
        cfg.run.profile_steps = args.profile_steps
    if args.debug_nans:
        cfg.run.debug_nans = True
    if args.hang_timeout_s:
        cfg.run.hang_timeout_s = args.hang_timeout_s
    if args.max_bad_steps >= 0:
        cfg.run.max_bad_steps = args.max_bad_steps
    if args.strict_compile:
        cfg.run.strict_compile = True
    if args.fault_spec:
        cfg.run.fault_spec = args.fault_spec
    if args.grad_accum:
        cfg.parallel.grad_accum = args.grad_accum

    if args.correction:
        cfg.plc.correction = args.correction
    if args.delta >= 0:
        cfg.plc.current_delta = args.delta
    if args.delta_increment >= 0:
        cfg.plc.delta_increment = args.delta_increment
    if args.thd >= 0:
        cfg.plc.thd = args.thd
    if args.plc_warmup_epochs >= 0:
        cfg.plc.warmup_epochs = args.plc_warmup_epochs
    if args.plc_max_flip_frac >= 0:
        cfg.plc.max_flip_frac = args.plc_max_flip_frac
    if args.plc_batch_stat_predictions:
        cfg.plc.batch_stat_predictions = True

    if args.dp:
        cfg.parallel.data_axis = args.dp
    if args.mp:
        cfg.parallel.model_axis = args.mp
    if args.pp_microbatches:
        cfg.parallel.pipeline_microbatches = args.pp_microbatches
    if args.pp_stages:
        if not args.pp_microbatches:
            raise ValueError("--pp_stages requires --pp_microbatches")
        cfg.parallel.pipeline_stages = args.pp_stages
    if args.dcn_slices:
        cfg.parallel.dcn_slices = args.dcn_slices
    if args.sharded_ce:
        cfg.parallel.arcface_sharded_ce = True
    if args.zero_opt:
        cfg.parallel.zero_opt = args.zero_opt
    if args.grad_reduce_dtype:
        cfg.parallel.grad_reduce_dtype = args.grad_reduce_dtype
    if args.moe_aux_weight is not None and args.moe_aux_weight < 0:
        raise ValueError(
            f"--moe_aux_weight must be >= 0, got {args.moe_aux_weight}")
    if args.moe_experts:
        cfg.model.moe_experts = args.moe_experts
        cfg.model.moe_top_k = args.moe_top_k
        if args.moe_aux_weight is not None:
            cfg.model.moe_aux_weight = args.moe_aux_weight
    return cfg


def main(argv: Optional[Sequence[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    try:
        # cheap config errors surface before any probe, and exit 2 — the same
        # code argparse uses for usage errors — so supervisors can tell a
        # deterministic config failure (rc 2: restarting replays the bug)
        # from an unhandled runtime exception (bare rc 1: transient
        # XlaRuntimeError through the tunnel, OOM, dataloader IO — retryable
        # with backoff under supervise.sh)
        cfg = config_from_args(args)
    except ValueError as e:
        import sys

        print(f"[trainer] config error: {e}", file=sys.stderr)
        raise SystemExit(2) from None
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    else:
        # honor JAX_PLATFORMS even under a sitecustomize that pins the TPU
        # plugin (env alone does not switch the platform there — observed:
        # JAX_PLATFORMS=cpu still initialized the tunneled TPU backend and
        # hung in its lease poll)
        from ..utils.backend_probe import pin_platform_from_env

        pin_platform_from_env()
    backend_up = None
    if (args.platform or os.environ.get("JAX_PLATFORMS", "")) != "cpu" and (
            os.environ.get("PALLAS_AXON_POOL_IPS")
            or "axon" in os.environ.get("JAX_PLATFORMS", "")):
        # a wedged TPU tunnel blocks jax.devices() indefinitely (observed: a
        # trainer sat 20+ min in the lease poll with 4s of CPU time) — probe
        # in a killable subprocess and fail loudly instead; the watchdog
        # covers the probe-passes-then-lease-churns window during init
        from ..utils.backend_probe import backend_watchdog, require_backend

        try:
            require_backend(attempts=2, probe_timeout=120)
        except RuntimeError as e:
            import sys

            # exit 3 = "backend unreachable", matching bench.py's code for
            # the same condition — distinct from config errors (SystemExit
            # messages → rc 1) so supervisors (window_catcher.sh) can tell
            # an outage-shaped failure from a deterministic one
            print(f"[trainer] TPU backend unreachable: {e} "
                  "(pass --platform cpu to train on the host)",
                  file=sys.stderr)
            raise SystemExit(3)
        backend_up = backend_watchdog(600)
    if args.multihost:
        # bounded-retry rendezvous (parallel/fleet.py): restarted hosts
        # miss each other's window under uncoordinated supervise.sh
        # backoffs, so initialize retries with a deterministic schedule
        # keyed off the shared $OUT/generation file; terminal failure is
        # rc 6 (outage-shaped — supervise.sh backs off OUTAGE_BACKOFF_S
        # and tries again instead of giving up fast)
        from ..parallel.fleet import (FleetConfigError, PodInconsistent,
                                      PodUnviable, RendezvousFailed,
                                      initialize_with_retry)
        from ..parallel.mesh import MeshSpec

        # the configured mesh gates elastic viability: a survivor world
        # whose device count cannot cover it is rc 10, not a
        # construction-time crash after rendezvous
        spec = MeshSpec(cfg.parallel.data_axis, cfg.parallel.model_axis,
                        max(cfg.parallel.pipeline_stages, 1))
        try:
            initialize_with_retry(out_dir=cfg.run.out_dir, mesh_spec=spec)
        except FleetConfigError as e:
            import sys

            # malformed FLEET_* launch env: deterministic, so the same
            # rc 2 as every other config error — supervise.sh must stop,
            # not replay the bad env MAX_RESTARTS times
            print(f"[trainer] config error: {e}", file=sys.stderr)
            raise SystemExit(FleetConfigError.exit_code) from None
        except PodUnviable as e:
            import sys

            # rc 10 = "pod-unviable": the survivor set is too small (or
            # does not divide into the mesh) — outage-shaped for the
            # supervisor, since dead peers may come back
            print(f"[trainer] pod-unviable: {e}", file=sys.stderr)
            raise SystemExit(PodUnviable.exit_code) from None
        except RendezvousFailed as e:
            import sys

            print(f"[trainer] {e}", file=sys.stderr)
            raise SystemExit(RendezvousFailed.exit_code) from None
        except PodInconsistent as e:
            import sys

            # the post-rendezvous membership digest agreement failed:
            # split-brain world views — same rc 9 as a split-brain resume
            print(f"[trainer] pod-inconsistent: {e}", file=sys.stderr)
            raise SystemExit(PodInconsistent.exit_code) from None
    if (args.world_size is not None or args.local_rank is not None
            or args.gpu is not None):
        print("[compat] --world_size/--local_rank/--gpu are ignored on TPU: "
              "one process per host, batch shards over the device mesh")
    from ..utils.cache import enable_persistent_cache

    enable_persistent_cache()

    from ..train.loop import Trainer
    from ..train.plc_loop import PLCTrainer
    from ..utils.seeding import set_seed

    set_seed(cfg.run.seed)
    if cfg.run.debug_nans:
        jax.config.update("jax_debug_nans", True)
    if backend_up is not None:
        jax.devices()  # first real backend touch, bounded by the watchdog
        backend_up()   # disarm BEFORE trainer construction: dataset scans /
        # pretrained-checkpoint conversion are host work that can legitimately
        # exceed the watchdog on reference-scale data, and the backend is
        # already initialized at this point
    from ..parallel.fleet import PodAbort, PodInconsistent, PodReform

    trainer_cls = PLCTrainer if cfg.workload == "plc" else Trainer
    try:
        trainer = trainer_cls(cfg)
    except PodInconsistent as e:
        import sys

        # rc 9 = "pod-inconsistent": the resume digest agreement failed —
        # at least one host restored different bytes than host 0's
        # broadcast choice. Loud and immediate instead of a silent
        # split-brain resume; usually shared-filesystem staleness, so
        # supervise.sh retries it with a runtime backoff.
        print(f"[trainer] pod-inconsistent: {e}", file=sys.stderr)
        raise SystemExit(PodInconsistent.exit_code) from None
    except ValueError as e:
        import sys
        import traceback

        # construction-time ValueErrors are config-shaped and deterministic
        # (MeshSpec.resolve "mesh does not cover N devices" when an axis
        # doesn't divide the device count, build_model's pipeline arch/head
        # rejections, make_hybrid_mesh's dcn+pp rejection, a bad dataset or
        # checkpoint path) — map them to the same rc 2 as config_from_args
        # so supervise.sh doesn't replay the bug MAX_RESTARTS times with
        # backoff (ADVICE r4). Keep the traceback: unlike the pre-parse
        # errors above, construction spans mesh/model/data code and the
        # message alone may not locate the source.
        traceback.print_exc(file=sys.stderr)
        print(f"[trainer] config error: {e}", file=sys.stderr)
        raise SystemExit(2) from None
    from ..analysis.compile_sentinel import SteadyStateRecompile
    from ..train.sentinel import SentinelDiverged

    try:
        trainer.run()
    except SteadyStateRecompile as e:
        import sys

        # --strict_compile tripped: a steady-state XLA compile means some
        # aval/signature drifted mid-run — deterministic (the same run
        # replays the same cache miss), so rc 2: supervisors must not
        # restart it. The sentinel already logged the offending signature.
        print(f"[trainer] steady-state recompile: {e}", file=sys.stderr)
        raise SystemExit(SteadyStateRecompile.exit_code) from None
    except SentinelDiverged as e:
        import sys

        # rc 8 = "diverged": max_bad_steps consecutive non-finite steps.
        # Deterministic — the same weights replay the same divergence — so
        # supervise.sh stops instead of burning the retry budget on it.
        print(f"[trainer] diverged: {e}", file=sys.stderr)
        raise SystemExit(SentinelDiverged.exit_code) from None
    except PodAbort as e:
        import sys

        # coordinated pod stop: some host's abort intent (sentinel rc 8,
        # deferred SIGTERM 143, …) propagated through the epoch-boundary
        # exchange — every host exits with the SAME code, so the
        # supervisors classify one failure, not N different ones
        print(f"[trainer] {e}", file=sys.stderr)
        raise SystemExit(e.code) from None
    except PodReform as e:
        import sys

        # rc 11 = "pod-reform": the epoch-boundary exchange observed a
        # membership change (lost member's lease expired, or a recovered
        # host's fresh lease) — every host exits together and the
        # supervisors respawn them into the re-formed world fast
        print(f"[trainer] pod-reform: {e}", file=sys.stderr)
        raise SystemExit(PodReform.exit_code) from None


if __name__ == "__main__":
    main()
