"""`serve` entrypoint — stand up the micro-batching inference engine over a
trained checkpoint (serve/engine.py; runbook: docs/serving.md).

    python -m ddp_classification_pytorch_tpu.cli.serve baseline \
        --model resnet50 --num_classes 2173 --watch runs/baseline \
        --port 8000 --buckets 2,4,16 --batch_timeout_ms 5

Discipline shared with `cli/train.py`:

- deterministic config errors (bad buckets, topk > classes, a corrupt
  `--ckpt`, construction-time ValueErrors) exit **rc 2** before/without
  burning backend retries — supervisors must not replay them;
- an unreachable TPU backend exits **rc 3** after the killable probe;
- **SIGTERM/SIGINT drain gracefully**: intake stops, every already-queued
  request is answered, metrics print one final line, exit **rc 0** — the
  preemption-safe shutdown a supervisor can always send.

`--selfcheck N` serves N synthetic requests through the full engine path
(warmup → batcher thread → drain) and exits — the socket-free smoke the
tier-1 tests and fresh deployments use.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading
from typing import Optional, Sequence

from ..config import Config, get_preset


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ddp_classification_pytorch_tpu.cli.serve",
        description="micro-batched inference serving over a trained checkpoint",
    )
    p.add_argument("workload", choices=["baseline", "arcface", "cdr", "nested", "plc"],
                   help="preset whose model/head the checkpoint was trained "
                        "with (same presets as cli.train)")

    m = p.add_argument_group("model")
    m.add_argument("--model", "--arch", dest="model", default="",
                   help="resnet18/34/50/101/152 | vgg19_bn | tresnet_m | "
                        "vit_t16/s16/b16 (must match the checkpoint)")
    m.add_argument("--variant", default="", help="imagenet | cifar stem")
    m.add_argument("--dtype", default="", help="bfloat16 | float32 compute dtype")
    m.add_argument("--num_classes", type=int, default=0)
    m.add_argument("--image_size", type=int, default=0)
    m.add_argument("--input_dtype", default="", choices=["", "uint8", "float32"],
                   help="request wire format (default uint8: raw pixels, "
                        "normalization fused into the jitted predict — same "
                        "dataplane as training)")

    s = p.add_argument_group("serving")
    s.add_argument("--ckpt", default="",
                   help="explicit checkpoint to serve (sha256-verified; a "
                        "corrupt file is a deterministic rc 2)")
    s.add_argument("--watch", default="",
                   help="run dir to serve from AND poll for checkpoint "
                        "hot-reload (newest verified checkpoint wins; "
                        "corrupt candidates are quarantined, serving "
                        "continues on the previous params)")
    s.add_argument("--reload_poll_s", type=float, default=-1.0,
                   help="hot-reload poll cadence for --watch (default 5)")
    s.add_argument("--buckets", default="",
                   help="comma list of padded batch shapes, ascending "
                        "(e.g. 2,4,16); compile count == bucket count; every "
                        "bucket must be divisible by the serve mesh's dp "
                        "width (rc 2 otherwise). Default: powers of two up "
                        "to --max_batch, rounded up to the dp width")
    s.add_argument("--max_batch", type=int, default=0,
                   help="largest micro-batch the deadline batcher assembles "
                        "(default 8)")
    s.add_argument("--batch_timeout_ms", type=float, default=-1.0,
                   help="deadline from the first queued request until a "
                        "partial batch flushes (default 5; 0 = never wait)")
    s.add_argument("--queue_depth", type=int, default=0,
                   help="bounded intake queue; submits beyond it are "
                        "rejected (backpressure / HTTP 503; default 64)")
    s.add_argument("--topk", type=int, default=0,
                   help="classes returned per request (default 5)")
    s.add_argument("--port", type=int, default=-1,
                   help=">0: stdlib HTTP front-end (POST /predict, "
                        "GET /healthz|/metrics); default: engine only")
    s.add_argument("--selfcheck", type=int, default=0,
                   help="serve N synthetic requests through the full engine "
                        "path, print metrics, drain, exit 0 (smoke mode)")
    s.add_argument("--serve_devices", "--serve-devices", dest="serve_devices",
                   type=int, default=-1,
                   help="devices on the serve mesh's data axis (0 = all "
                        "visible, the default): padded bucket batches shard "
                        "over them, so throughput scales with the pod; "
                        "buckets must divide evenly (rc 2 otherwise)")
    s.add_argument("--aot_cache", "--aot-cache", dest="aot_cache", default="",
                   help="AOT executable sidecar: 'auto' (default) banks "
                        "compiled bucket programs in <ckpt dir>/aot so the "
                        "next replica boots without compiling, 'off' "
                        "disables, else an explicit sidecar dir")
    s.add_argument("--fleet_dir", "--fleet-dir", dest="fleet_dir", default="",
                   help="shared fleet run dir: replicas heartbeat via "
                        "<dir>/serve_fleet/lease.r<id> and serialize hot "
                        "reloads through one drain token (rolling wave, at "
                        "most one replica draining); default: lone replica")
    s.add_argument("--fleet_replica", "--fleet-replica", dest="fleet_replica",
                   type=int, default=-1,
                   help="this replica's id in the shared --fleet_dir "
                        "(lowest live id is the leader; default 0)")
    s.add_argument("--fleet_ttl_s", "--fleet-ttl-s", dest="fleet_ttl_s",
                   type=float, default=-1.0,
                   help="lease/drain-token freshness horizon: a lease older "
                        "than this is a dead replica, a stale token is "
                        "taken over so a kill mid-wave cannot wedge the "
                        "wave (default 15)")
    s.add_argument("--admission_deadline_ms", "--admission-deadline-ms",
                   dest="admission_deadline_ms", type=float, default=-1.0,
                   help=">0: shed requests when the MEASURED queue wait "
                        "(depth / observed service rate) exceeds this "
                        "deadline — fair-share tenants shed at 1x, any "
                        "tenant at 2x; 503 bodies carry the depth + shed "
                        "tenant (default 0 = engine queue bound only)")
    s.add_argument("--admission_tenants", "--admission-tenants",
                   dest="admission_tenants", default="",
                   help="per-tenant weighted fair shares for admission, "
                        "'name:weight,name:weight' (requests pick a tenant "
                        "via the X-Tenant header; default: one 'default' "
                        "tenant at weight 1)")
    s.add_argument("--strict_compile", action="store_true",
                   help="make a steady-state recompile fatal (rc 2): warmup "
                        "prepays exactly len(buckets) programs and arms a "
                        "compile sentinel; default logs + counts it in "
                        "metrics (analysis/compile_sentinel.py)")

    r = p.add_argument_group("run")
    r.add_argument("--out", default="", help="metrics/records output dir")
    r.add_argument("--tensorboard", action="store_true",
                   help="write serve/* scalar curves to <out>/tb")
    r.add_argument("--log_every_s", type=float, default=-1.0,
                   help="metrics console line cadence (default 10)")
    r.add_argument("--seed", type=int, default=-1)
    r.add_argument("--platform", default="", choices=["", "tpu", "cpu"],
                   help="force a JAX platform (as cli.train)")
    return p


def config_from_args(args: argparse.Namespace) -> Config:
    cfg = get_preset(args.workload)
    if args.model:
        cfg.model.arch = args.model
    if args.variant:
        cfg.model.variant = args.variant
    if args.dtype:
        cfg.model.dtype = args.dtype
    if args.num_classes:
        cfg.data.num_classes = args.num_classes
    if args.image_size:
        cfg.data.image_size = args.image_size
    if args.input_dtype:
        cfg.data.input_dtype = args.input_dtype
    if args.seed >= 0:
        cfg.run.seed = args.seed
    if args.out:
        cfg.run.out_dir = args.out
    if args.tensorboard:
        cfg.run.tensorboard = True

    sv = cfg.serve
    if args.ckpt:
        sv.checkpoint = args.ckpt
    if args.watch:
        sv.watch_dir = args.watch
    if args.reload_poll_s >= 0:
        sv.reload_poll_s = args.reload_poll_s
    if args.buckets:
        sv.buckets = tuple(int(b) for b in args.buckets.split(",") if b)
    if args.max_batch:
        sv.max_batch = args.max_batch
    if args.batch_timeout_ms >= 0:
        sv.batch_timeout_ms = args.batch_timeout_ms
    if args.queue_depth:
        sv.queue_depth = args.queue_depth
    if args.topk:
        sv.topk = args.topk
    if args.port >= 0:
        sv.port = args.port
    if args.log_every_s >= 0:
        sv.log_every_s = args.log_every_s
    if args.strict_compile:
        sv.strict_compile = True
    if args.serve_devices >= 0:
        sv.serve_devices = args.serve_devices
    if args.aot_cache:
        sv.aot_cache = args.aot_cache
    if args.fleet_dir:
        sv.fleet_dir = args.fleet_dir
    if args.fleet_replica >= 0:
        sv.fleet_replica = args.fleet_replica
    if args.fleet_ttl_s >= 0:
        sv.fleet_ttl_s = args.fleet_ttl_s
    if args.admission_deadline_ms >= 0:
        sv.admission_deadline_ms = args.admission_deadline_ms
    if args.admission_tenants:
        sv.admission_tenants = args.admission_tenants

    # dp divisibility re-resolves against the real mesh width in main()
    # (inside the same rc-2 net); this catches the dp-independent errors
    # before any backend work
    sv.resolve_buckets()  # raises ValueError on bad knob combinations
    sv.validate_fleet()  # fleet/admission knobs are config-shaped too
    if sv.topk > cfg.data.num_classes:
        raise ValueError(
            f"serve.topk={sv.topk} exceeds num_classes={cfg.data.num_classes}")
    if sv.checkpoint and sv.watch_dir:
        raise ValueError("--ckpt and --watch are mutually exclusive: an "
                         "explicit checkpoint pins the params, a watch dir "
                         "hot-reloads them")
    if not (sv.checkpoint or sv.watch_dir or args.selfcheck):
        raise ValueError("serving needs weights: pass --ckpt <file> or "
                         "--watch <run_dir> (or --selfcheck N to smoke the "
                         "engine on fresh params)")
    return cfg


def _resolve_aot_dir(cfg: Config) -> str:
    """Where the AOT executable sidecar lives ("" = disabled). 'auto' puts
    it next to the weights — the one location every replica of a
    deployment shares — and disables itself for a weightless selfcheck
    (fresh params have no durable identity worth keying a cache on)."""
    mode = cfg.serve.aot_cache
    if mode == "off":
        return ""
    if mode and mode != "auto":
        return mode
    if cfg.serve.checkpoint:
        base = os.path.dirname(os.path.abspath(cfg.serve.checkpoint)) or "."
        return os.path.join(base, "aot")
    if cfg.serve.watch_dir:
        return os.path.join(cfg.serve.watch_dir, "aot")
    return ""


def _install_signal_handlers(stop: threading.Event):
    """SIGTERM/SIGINT → set the drain event (the serve loop does the actual
    drain: stop intake, flush queue, exit rc 0). Returns the previous
    handlers so tests can restore them."""
    prev = {}
    for sig in (signal.SIGTERM, signal.SIGINT):
        prev[sig] = signal.signal(sig, lambda *_: stop.set())
    return prev


def main(argv: Optional[Sequence[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    try:
        # same pre-backend rc-2 discipline as cli.train: a bad knob combo
        # surfaces in milliseconds with the deterministic code supervisors
        # must not retry
        cfg = config_from_args(args)
    except ValueError as e:
        import sys

        print(f"[serve] config error: {e}", file=sys.stderr)
        raise SystemExit(2) from None
    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    else:
        from ..utils.backend_probe import pin_platform_from_env

        pin_platform_from_env()
    if (args.platform or os.environ.get("JAX_PLATFORMS", "")) != "cpu" and (
            os.environ.get("PALLAS_AXON_POOL_IPS")
            or "axon" in os.environ.get("JAX_PLATFORMS", "")):
        # same killable probe as cli.train: never hang on a dead TPU
        from ..utils.backend_probe import require_backend

        try:
            require_backend(attempts=2, probe_timeout=120)
        except RuntimeError as e:
            import sys

            print(f"[serve] TPU backend unreachable: {e} "
                  "(pass --platform cpu to serve on the host)",
                  file=sys.stderr)
            raise SystemExit(3)
    from ..utils.cache import enable_persistent_cache

    enable_persistent_cache()

    import numpy as np

    from ..data.transforms import build_transform, preset_for_dataset
    from ..parallel import mesh as meshlib
    from ..serve.engine import ServingEngine
    from ..serve.metrics import ServeMetrics
    from ..serve.reload import CheckpointWatcher
    from ..train.checkpoint import CheckpointManager
    from ..train.state import create_train_state
    from ..train.steps import make_topk_predict_step
    from ..utils.logging import host0_print

    try:
        # serving is pure DP: --serve_devices devices (0 = all) on 'data'.
        # Built inside the rc-2 net: an over-wide request or a dp-indivisible
        # explicit bucket is config-shaped, not a crash
        mesh = meshlib.serve_mesh(cfg.serve.serve_devices)
        dp = int(mesh.shape[meshlib.DATA_AXIS])
        cfg.serve.resolve_buckets(dp)
        model, _, state = create_train_state(cfg, mesh, steps_per_epoch=1)
        if cfg.serve.checkpoint:
            # explicit checkpoint: verification failure raises ValueError —
            # deterministic, so it maps to rc 2 like --resume in cli.train
            mgr = CheckpointManager(
                os.path.dirname(os.path.abspath(cfg.serve.checkpoint)) or ".",
                save_every_epoch=False, async_save=False)
            state = mgr.restore(state, cfg.serve.checkpoint)
            host0_print(f"[serve] serving {cfg.serve.checkpoint}")
    except ValueError as e:
        import sys
        import traceback

        # construction-time ValueErrors (unknown arch/head, corrupt --ckpt,
        # shape mismatches) are config-shaped → rc 2, same as cli.train
        traceback.print_exc(file=sys.stderr)
        print(f"[serve] config error: {e}", file=sys.stderr)
        raise SystemExit(2) from None

    predict = make_topk_predict_step(cfg, model, cfg.serve.topk, mesh=mesh)
    metrics = ServeMetrics()
    preset = preset_for_dataset(cfg.data.dataset, cfg.data.transform)
    transform = (build_transform(preset, train=False,
                                 image_size=cfg.data.image_size,
                                 crop_size=cfg.data.train_crop_size,
                                 out_dtype=cfg.data.input_dtype)
                 if preset is not None else None)
    aot_dir = _resolve_aot_dir(cfg)
    engine = ServingEngine.from_config(cfg, state, predict, metrics=metrics,
                                       transform=transform,
                                       mesh=mesh, aot_dir=aot_dir)

    fleet = None
    if cfg.serve.fleet_dir:
        from ..serve.fleet import FleetMember

        # shares the engine registry so fleet_* gauges ride /metrics; the
        # lease heartbeat itself piggybacks on the watcher poll tick
        fleet = FleetMember(cfg.serve.fleet_dir, cfg.serve.fleet_replica,
                            ttl_s=cfg.serve.fleet_ttl_s,
                            registry=metrics.registry)
    admission = None
    if cfg.serve.admission_deadline_ms > 0:
        from ..serve.fleet import AdmissionController

        admission = AdmissionController(
            engine, tenants=cfg.serve.admission_tenants,
            deadline_ms=cfg.serve.admission_deadline_ms,
            registry=metrics.registry)

    watcher = None
    if cfg.serve.watch_dir:
        from ..utils import chaos as chaoslib

        # watcher_io drills aim CHAOS_FAULT_SPEC at a replica; one-shot
        # markers live under this replica's own out dir, not the shared
        # watch dir (each replica owns its poll counter)
        plan = chaoslib.plan_for_run("", cfg.run.out_dir or ".", 0)
        watcher = CheckpointWatcher(cfg.serve.watch_dir, engine, state,
                                    poll_s=cfg.serve.reload_poll_s,
                                    metrics=metrics,
                                    chaos=plan if plan else None,
                                    fleet=fleet)
        loaded = watcher.restore_initial()
        host0_print(f"[serve] watching {cfg.serve.watch_dir} "
                    + (f"(serving epoch {loaded})" if loaded >= 0 else
                       "(no verified checkpoint yet; serving fresh params "
                       "until one lands)"))

    host0_print(f"[serve] arch={cfg.model.arch} classes={cfg.data.num_classes} "
                f"wire={cfg.data.input_dtype} buckets={list(engine.buckets)} "
                f"max_batch={cfg.serve.max_batch} "
                f"timeout={cfg.serve.batch_timeout_ms}ms "
                f"topk={cfg.serve.topk} serve_devices={engine.serve_devices} "
                f"dp={engine.dp} aot={aot_dir or 'off'}")
    engine.warmup()  # ready every bucket executable before traffic
    host0_print(
        f"[serve] warm boot: {len(engine.buckets)} bucket executables "
        "AOT-deserialized, zero compiles" if engine.aot_hit else
        f"[serve] cold boot: {len(engine.buckets)} bucket programs compiled"
        + (" (banked to AOT sidecar)" if aot_dir else ""))

    tb = None
    if cfg.run.tensorboard:
        from ..utils.tensorboard import SummaryWriter

        tb = SummaryWriter(os.path.join(cfg.run.out_dir, "tb"), "serve")

    if args.selfcheck:
        engine.start()
        rng = np.random.default_rng(cfg.run.seed)
        h = cfg.data.image_size
        imgs = (rng.integers(0, 256, (args.selfcheck, h, h, 3)).astype(np.uint8)
                if cfg.data.input_dtype == "uint8"
                else rng.normal(size=(args.selfcheck, h, h, 3)).astype(np.float32))
        futures = [engine.submit(img) for img in imgs]
        for f in futures:
            f.result(timeout=120)
        engine.drain()
        if watcher is not None:
            watcher.stop()
        if fleet is not None:
            fleet.leave()
        host0_print(metrics.log_line(engine.queue_depth))
        if tb is not None:
            metrics.to_tensorboard(tb, 0)
            tb.close()
        if engine.fatal_error is not None:
            import sys

            # strict_compile tripped: deterministic (the same traffic
            # replays the same cache miss) → rc 2, do not restart
            print(f"[serve] {engine.fatal_error}", file=sys.stderr)
            raise SystemExit(2)
        host0_print(f"[serve] selfcheck ok: {args.selfcheck} requests, "
                    f"buckets used {sorted(engine.seen_buckets)}")
        return

    stop = threading.Event()
    _install_signal_handlers(stop)
    engine.start()
    if watcher is not None:
        watcher.start()
    server = None
    if cfg.serve.port:
        from ..serve.http import start_server

        server = start_server(engine, cfg.serve.port, watcher=watcher,
                              fleet=fleet, admission=admission)
        host0_print(f"[serve] http on :{cfg.serve.port} "
                    "(POST /predict, GET /healthz, GET /metrics)")
    if fleet is not None and watcher is None:
        # --ckpt pins the params (no watcher poll to ride): announce the
        # pinned digest once so the registry sees this replica at all
        fleet.heartbeat(digest=engine.params_digest,
                        generation=engine.params_generation)
    from ..obs.events import emit

    emit("serve_ready", port=cfg.serve.port,
         epoch=(watcher.loaded_epoch if watcher is not None else -1))

    step = 0
    while not stop.wait(cfg.serve.log_every_s):
        if engine.fatal_error is not None:
            break  # strict_compile tripped: intake already stopped
        host0_print(metrics.log_line(engine.queue_depth))
        if tb is not None:
            metrics.to_tensorboard(tb, step)
            tb.flush()
        step += 1

    # graceful drain: intake stops first (HTTP answers 503), then every
    # already-accepted request is served, then exit 0
    host0_print("[serve] SIGTERM/SIGINT: draining — intake stopped, "
                f"{engine.queue_depth} request(s) queued")
    emit("drain_begin", queued=engine.queue_depth)
    if server is not None:
        server.shutdown()
    if watcher is not None:
        watcher.stop()
    engine.drain()
    if fleet is not None:
        fleet.leave()  # drop the lease now, not after the TTL
    emit("drain_end")
    host0_print(metrics.log_line(engine.queue_depth))
    if tb is not None:
        metrics.to_tensorboard(tb, step)
        tb.close()
    if engine.fatal_error is not None:
        import sys

        print(f"[serve] {engine.fatal_error}", file=sys.stderr)
        raise SystemExit(2)
    host0_print("[serve] drained clean")


if __name__ == "__main__":
    main()
