"""`analyze` entrypoint — run the program-invariant analyzer over the repo.

    python -m ddp_classification_pytorch_tpu.cli.analyze            # all passes
    python -m ddp_classification_pytorch_tpu.cli.analyze --passes lint
    python -m ddp_classification_pytorch_tpu.cli.analyze --diff-baseline
    python -m ddp_classification_pytorch_tpu.cli.analyze --update-baseline
    python -m ddp_classification_pytorch_tpu.cli.analyze --list     # inventory

Exit discipline (same taxonomy as cli.train / cli.serve, docs/operations.md):

- **rc 0** — every invariant holds (donation aliasing, callback-free hot
  paths, uint8 epilogue, collective-free eval/serve programs, host-sync-free
  step factories, catalogued CLI exit codes, sharding/comms policies, the
  dtype pass's numerics contracts D1–D6, and — under `--diff-baseline` —
  no drift beyond the committed baseline's tolerances);
- **rc 1** — findings: each printed as `[check] where: message`, machine
  copies via `--json`;
- **rc 2** — usage/config error (unknown pass name, argparse errors, a
  backend that cannot host the composed audit meshes).

The jaxpr/sharding passes lower real step factories on a tiny synthetic
config, so they run in seconds on CPU; analysis never needs (or touches) an
accelerator — the backend is pinned to CPU unless `--platform` overrides
it, and a multi-device CPU topology is self-forced (XLA_FLAGS
`--xla_force_host_platform_device_count=8`) so the composed 2×1/2×2 audit
meshes exist on any host — a standalone `--diff-baseline` run matches the
environment the committed baseline was generated in. CI wrapper:
`scripts/lint.sh`; runbook for a red finding: docs/analysis.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

PASSES = ("jaxpr", "lint", "sharding", "dtype")

# the composed audit meshes (dp2, dp2tp2) need ≥4 devices; on CPU we force
# a virtual topology BEFORE backend init so baselines are host-independent
_FORCED_CPU_DEVICES = 8


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ddp_classification_pytorch_tpu.cli.analyze",
        description="program-invariant analyzer: jaxpr/HLO donation audit, "
                    "host-sync & rc-catalogue linting",
    )
    p.add_argument("--passes", default=",".join(PASSES),
                   help="comma list of passes to run: jaxpr (trace/compile "
                        "the step registry), lint (AST passes), sharding "
                        "(compile the program×mesh matrix: collective "
                        "inventory, sharding table, memory budget), dtype "
                        "(numerics contracts D1-D6 over every cell); "
                        "default: all")
    p.add_argument("--dtype", action="store_true",
                   help="shorthand: add the dtype pass to --passes")
    p.add_argument("--arch", default="resnet18",
                   help="backbone for the audit's tiny traced config "
                        "(invariants are program-structure properties, "
                        "independent of scale)")
    p.add_argument("--image_size", type=int, default=32)
    p.add_argument("--num_classes", type=int, default=8)
    p.add_argument("--batchsize", "-b", type=int, default=8,
                   help="synthetic batch aval (must divide the device count "
                        "for the shard_map entry)")
    p.add_argument("--json", default="",
                   help="also write findings + registry evidence as JSON")
    p.add_argument("--list", action="store_true",
                   help="print the registry + invariant inventory and exit 0")
    p.add_argument("--rc-paths", nargs="*", default=None,
                   help="explicit files for the rc-catalogue lint "
                        "(default: the cli/ package)")
    p.add_argument("--platform", default="", choices=["", "cpu", "tpu"],
                   help="JAX platform for the jaxpr pass (default cpu: "
                        "analysis must never burn — or hang on — an "
                        "accelerator lease)")
    p.add_argument("--baseline", default="",
                   help="program-baseline JSON path (default: the "
                        "checked-in analysis/baselines.json)")
    p.add_argument("--diff-baseline", "--diff_baseline",
                   dest="diff_baseline", action="store_true",
                   help="diff the sharding pass's records against the "
                        "committed baseline; drift beyond tolerances "
                        "(new collective kind, payload/peak-HBM growth, "
                        "sharding downgrade, donation regression) is rc 1")
    p.add_argument("--update-baseline", "--update_baseline",
                   dest="update_baseline", action="store_true",
                   help="regenerate the baseline file from this run (with "
                        "a provenance header) instead of diffing — commit "
                        "the result; runbook in docs/analysis.md")
    return p


def main(argv: Optional[Sequence[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    passes = tuple(s.strip() for s in args.passes.split(",") if s.strip())
    unknown = [s for s in passes if s not in PASSES]
    if unknown or not passes:
        # deterministic config error → rc 2, the code supervisors never retry
        print(f"[analyze] config error: unknown pass(es) {unknown or passes}; "
              f"choose from {list(PASSES)}", file=sys.stderr)
        raise SystemExit(2)
    if args.dtype and "dtype" not in passes:
        passes = passes + ("dtype",)
    if args.diff_baseline or args.update_baseline:
        # the baseline file is the sharding + dtype passes' joint artifact
        passes += tuple(p for p in ("sharding", "dtype") if p not in passes)

    if ("jaxpr" in passes or "sharding" in passes or "dtype" in passes) and (
            args.platform or "cpu") == "cpu":
        # the registry's dp×tp entries and the sharded matrix need the
        # composed 2×1/2×2 meshes: force a virtual multi-device CPU
        # topology before the backend initializes (a no-op if the caller
        # already forced one, e.g. the test suite's conftest), so a
        # standalone `--diff-baseline` reproduces the committed baseline's
        # environment on any host
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                f"{_FORCED_CPU_DEVICES}").strip()

    from ..analysis.jaxpr_audit import build_registry

    if args.list:
        print("registered step programs (jaxpr pass):")
        for spec in build_registry():
            props = []
            if spec.donate:
                props.append(f"donates args {list(spec.donate)} (must alias)")
            else:
                props.append("no-donate (documented)")
            if spec.hot_path:
                props.append("callback-free")
            if not spec.allow_collectives:
                props.append("collective-free")
            if spec.uint8_input:
                props.append("uint8→epilogue")
            print(f"  {spec.name:22s} {spec.factory}")
            print(f"  {'':22s} invariants: {', '.join(props)}")
        print("lint pass: host-sync idioms in the factories above; "
              "jit-registration guard over train/steps.py; "
              "rc catalogue over cli/ exits (docs/operations.md matrix)")
        from ..analysis.sharding_audit import sharded_registry

        print("sharding pass (program × composed mesh matrix):")
        for case in sharded_registry():
            print(f"  {case.key:24s} policy: "
                  f"allowed={list(case.policy.allowed_kinds)}"
                  + (" + gradient all-reduce floor"
                     if case.policy.require_grad_allreduce else
                     f", per-op ≤ {case.policy.small_bytes}B")
                  + f", wire≥{case.wire_dtype}")
        from ..analysis.dtype_audit import dtype_registry

        print("dtype pass (program × precision-config cells, contracts "
              "D1-D6):")
        for dcase in dtype_registry():
            waived = ",".join(sorted(dcase.waivers)) or "none"
            print(f"  {dcase.name:34s} "
                  f"{'train (D2 master-weights)' if dcase.train else 'eval'}"
                  f", waivers: {waived}")
        return

    findings = []
    evidence = {}

    if "lint" in passes:
        from ..analysis.lint import (lint_jit_sites, lint_rc_sites,
                                     lint_step_factories)

        findings += lint_step_factories()
        findings += lint_jit_sites()
        findings += lint_rc_sites(paths=args.rc_paths)

    ctx = None
    if "jaxpr" in passes or "sharding" in passes or "dtype" in passes:
        import jax

        # analysis is host-side program inspection: pin CPU so a wedged TPU
        # tunnel can never hang the linter (cf. backend probing in cli.train)
        jax.config.update("jax_platforms", args.platform or "cpu")
        from ..analysis.jaxpr_audit import AuditContext

        ctx = AuditContext(arch=args.arch, image_size=args.image_size,
                           num_classes=args.num_classes, batch=args.batchsize)
        if ("sharding" in passes or "dtype" in passes) \
                and jax.device_count() < 4:
            print(f"[analyze] config error: the sharding/dtype passes need "
                  f"≥4 devices for the composed audit meshes, have "
                  f"{jax.device_count()} (force more via XLA_FLAGS="
                  "--xla_force_host_platform_device_count=8)",
                  file=sys.stderr)
            raise SystemExit(2)

    if "jaxpr" in passes:
        from ..analysis.jaxpr_audit import audit_registry

        jx_findings, specs = audit_registry(ctx)
        findings += jx_findings
        for spec in specs:
            evidence[spec.name] = spec.evidence
            don = spec.evidence.get("donation")
            if don:
                print(f"[analyze] {spec.name}: donated={don['donated_bytes']}B "
                      f"aliased={don['aliased_bytes']}B "
                      f"coverage={don['donation_coverage']}")

    records = None
    if "sharding" in passes:
        from ..analysis.sharding_audit import audit_sharded_registry

        sh_findings, records = audit_sharded_registry(ctx)
        findings += sh_findings
        evidence["sharded"] = records
        for key, rec in records.items():
            print(f"[analyze] {key}: "
                  f"collectives={rec['collective_bytes_per_step']}B/step "
                  f"({'+'.join(sorted(rec['collectives'])) or 'none'}) "
                  f"peak_hbm={rec['peak_hbm_bytes']}B"
                  + (f" coverage={rec['donation_coverage']}"
                     if rec["donation_coverage"] is not None else ""))

    dtype_records = None
    if "dtype" in passes:
        from ..analysis.dtype_audit import audit_dtype_registry

        dt_findings, dtype_records = audit_dtype_registry(ctx)
        findings += dt_findings
        evidence["dtype"] = dtype_records
        for key, rec in dtype_records.items():
            print(f"[analyze] {key}: bf16_ops={rec['bf16_op_fraction']} "
                  f"casts={sum(rec['casts'].values())} "
                  f"wire={'+'.join(rec['collective_dtypes']) or 'none'} "
                  f"waivers={','.join(rec['waivers']) or 'none'}")

    if args.update_baseline:
        from ..analysis import baseline as baselib

        path = baselib.write_baseline(
            records or {}, args.baseline or None,
            context={"arch": args.arch, "image_size": args.image_size,
                     "num_classes": args.num_classes,
                     "batch": args.batchsize},
            dtype_records=dtype_records)
        print(f"[analyze] baseline written: {path} "
              f"({len(records or {})} sharded + "
              f"{len(dtype_records or {})} dtype cells) — review + commit "
              "the diff")
    elif args.diff_baseline:
        from ..analysis import baseline as baselib
        from ..analysis.dtype_audit import diff_dtype_baseline

        try:
            base = baselib.load_baseline(args.baseline or None)
        except FileNotFoundError as e:
            print(f"[analyze] config error: {e}", file=sys.stderr)
            raise SystemExit(2)
        findings += baselib.diff_baseline(records or {}, base)
        findings += diff_dtype_baseline(dtype_records or {}, base)

    if args.json:
        with open(args.json, "w") as f:
            json.dump({"findings": [vars(fd) for fd in findings],
                       "evidence": evidence}, f, indent=2, default=str)

    for fd in findings:
        print(str(fd), file=sys.stderr)
    if findings:
        print(f"[analyze] {len(findings)} finding(s) — see docs/analysis.md "
              "for the runbook", file=sys.stderr)
        raise SystemExit(1)
    ran = "+".join(passes)
    print(f"[analyze] clean: {ran} pass(es), "
          f"{len(evidence) or 'no'} programs audited")


if __name__ == "__main__":
    main()
