"""`analyze` entrypoint — run the program-invariant analyzer over the repo.

    python -m ddp_classification_pytorch_tpu.cli.analyze            # all passes
    python -m ddp_classification_pytorch_tpu.cli.analyze --passes lint
    python -m ddp_classification_pytorch_tpu.cli.analyze --list     # inventory

Exit discipline (same taxonomy as cli.train / cli.serve, docs/operations.md):

- **rc 0** — every invariant holds (donation aliasing, callback-free hot
  paths, uint8 epilogue, collective-free eval/serve programs, host-sync-free
  step factories, catalogued CLI exit codes);
- **rc 1** — findings: each printed as `[check] where: message`, machine
  copies via `--json`;
- **rc 2** — usage/config error (unknown pass name, argparse errors).

The jaxpr pass lowers real step factories on a tiny synthetic config, so it
runs in seconds on CPU; analysis never needs (or touches) an accelerator —
the backend is pinned to CPU unless `--platform` overrides it. CI wrapper:
`scripts/lint.sh`; runbook for a red finding: docs/analysis.md.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

PASSES = ("jaxpr", "lint")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ddp_classification_pytorch_tpu.cli.analyze",
        description="program-invariant analyzer: jaxpr/HLO donation audit, "
                    "host-sync & rc-catalogue linting",
    )
    p.add_argument("--passes", default=",".join(PASSES),
                   help="comma list of passes to run: jaxpr (trace/compile "
                        "the step registry) and/or lint (AST passes); "
                        "default: all")
    p.add_argument("--arch", default="resnet18",
                   help="backbone for the audit's tiny traced config "
                        "(invariants are program-structure properties, "
                        "independent of scale)")
    p.add_argument("--image_size", type=int, default=32)
    p.add_argument("--num_classes", type=int, default=8)
    p.add_argument("--batchsize", "-b", type=int, default=8,
                   help="synthetic batch aval (must divide the device count "
                        "for the shard_map entry)")
    p.add_argument("--json", default="",
                   help="also write findings + registry evidence as JSON")
    p.add_argument("--list", action="store_true",
                   help="print the registry + invariant inventory and exit 0")
    p.add_argument("--rc-paths", nargs="*", default=None,
                   help="explicit files for the rc-catalogue lint "
                        "(default: the cli/ package)")
    p.add_argument("--platform", default="", choices=["", "cpu", "tpu"],
                   help="JAX platform for the jaxpr pass (default cpu: "
                        "analysis must never burn — or hang on — an "
                        "accelerator lease)")
    return p


def main(argv: Optional[Sequence[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    passes = tuple(s.strip() for s in args.passes.split(",") if s.strip())
    unknown = [s for s in passes if s not in PASSES]
    if unknown or not passes:
        # deterministic config error → rc 2, the code supervisors never retry
        print(f"[analyze] config error: unknown pass(es) {unknown or passes}; "
              f"choose from {list(PASSES)}", file=sys.stderr)
        raise SystemExit(2)

    from ..analysis.jaxpr_audit import build_registry

    if args.list:
        print("registered step programs (jaxpr pass):")
        for spec in build_registry():
            props = []
            if spec.donate:
                props.append(f"donates args {list(spec.donate)} (must alias)")
            else:
                props.append("no-donate (documented)")
            if spec.hot_path:
                props.append("callback-free")
            if not spec.allow_collectives:
                props.append("collective-free")
            if spec.uint8_input:
                props.append("uint8→epilogue")
            print(f"  {spec.name:22s} {spec.factory}")
            print(f"  {'':22s} invariants: {', '.join(props)}")
        print("lint pass: host-sync idioms in the factories above; "
              "rc catalogue over cli/ exits (docs/operations.md matrix)")
        return

    findings = []
    evidence = {}

    if "lint" in passes:
        from ..analysis.lint import lint_rc_sites, lint_step_factories

        findings += lint_step_factories()
        findings += lint_rc_sites(paths=args.rc_paths)

    if "jaxpr" in passes:
        import jax

        # analysis is host-side program inspection: pin CPU so a wedged TPU
        # tunnel can never hang the linter (cf. backend probing in cli.train)
        jax.config.update("jax_platforms", args.platform or "cpu")
        from ..analysis.jaxpr_audit import AuditContext, audit_registry

        ctx = AuditContext(arch=args.arch, image_size=args.image_size,
                           num_classes=args.num_classes, batch=args.batchsize)
        jx_findings, specs = audit_registry(ctx)
        findings += jx_findings
        for spec in specs:
            evidence[spec.name] = spec.evidence
            don = spec.evidence.get("donation")
            if don:
                print(f"[analyze] {spec.name}: donated={don['donated_bytes']}B "
                      f"aliased={don['aliased_bytes']}B "
                      f"coverage={don['donation_coverage']}")

    if args.json:
        with open(args.json, "w") as f:
            json.dump({"findings": [vars(fd) for fd in findings],
                       "evidence": evidence}, f, indent=2, default=str)

    for fd in findings:
        print(str(fd), file=sys.stderr)
    if findings:
        print(f"[analyze] {len(findings)} finding(s) — see docs/analysis.md "
              "for the runbook", file=sys.stderr)
        raise SystemExit(1)
    ran = "+".join(passes)
    print(f"[analyze] clean: {ran} pass(es), "
          f"{len(evidence) or 'no'} programs audited")


if __name__ == "__main__":
    main()
