"""Verify a REAL torch checkpoint imports exactly (VERDICT r3 #8).

The randomized-oracle parity tests (tests/test_torch_oracle_parity.py)
prove the converter mapping offline; the residual risk is the real
artifact — a torchvision/timm `.pth` downloaded outside this sandbox
could still carry keys or dtypes the randomized proxy never produced.
This command closes that gap the moment such a file exists on disk:

    python -m ddp_classification_pytorch_tpu.cli.verify_import \
        /path/to/resnet50-0676ba61.pth --arch resnet50

It (1) loads the state_dict, (2) loads it into the matching torch oracle
(models/torch_oracle.py — upstream parameter naming, so strict loading
validates key coverage), (3) converts it into the flax model via the
same `import_torch` path `--pretrained_path` uses, and (4) compares
full-model forward outputs on random inputs in f32 eval mode. Exit 0 =
PASS (max |Δ| within tolerance), 1 = numerical FAIL, 2 = usage/shape
errors (missing file, unknown arch, state_dict/oracle key mismatch).

What the verdict certifies: the CONVERTER against this artifact — the
oracle and the converter read the same bytes, so a PASS means the flax
model computes exactly what torch computes from those weights.
Truncation/rename damage surfaces as the strict-load exit 2 (with key
lists); value-level corruption that both sides read identically is
invisible here by construction and shows up as bad task accuracy, like
it would in torch itself.

Everything runs on CPU — no TPU needed to certify an import.
"""

from __future__ import annotations

import argparse
import sys


def _build_pair(arch: str, num_classes: int):
    """(torch oracle, flax model ctor, converter, image size) per arch."""
    from ..models import import_torch as it
    from ..models import torch_oracle as to

    import jax.numpy as jnp

    if arch in to._DEPTHS:  # every oracle ResNet depth (single source)
        from ..models import resnet as R

        return (to.make_torch_resnet(arch, num_classes),
                lambda: getattr(R, arch)(num_classes=num_classes,
                                         dtype=jnp.float32),
                it.convert_resnet_state_dict, 64)
    if arch == "vgg19_bn":
        from ..models.vgg import vgg19_bn

        return (to.make_torch_vgg19_bn(num_classes),
                lambda: vgg19_bn(num_classes=num_classes, dtype=jnp.float32),
                it.convert_vgg_state_dict, 224)
    if arch in ("tresnet_m", "timm"):
        from ..models.tresnet import tresnet_m

        return (to.make_torch_tresnet_m(num_classes),
                lambda: tresnet_m(num_classes=num_classes, dtype=jnp.float32),
                it.convert_tresnet_state_dict, 224)
    raise SystemExit(2)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="verify a real torch .pth imports exactly")
    ap.add_argument("checkpoint", help="path to the .pth / .pt state_dict")
    ap.add_argument("--arch", default="resnet50",
                    help="resnet18|34|50|101|152|vgg19_bn|tresnet_m")
    ap.add_argument("--tol", type=float, default=2e-4,
                    help="forward-parity tolerance (f32; the randomized "
                         "oracle suite passes at 2e-4)")
    ap.add_argument("--batch", type=int, default=2)
    args = ap.parse_args(argv)

    import numpy as np

    try:
        import torch
    except ImportError:
        print("FAIL: torch unavailable — the oracle comparison needs it",
              file=sys.stderr)
        raise SystemExit(2)

    import jax

    jax.config.update("jax_platforms", "cpu")  # certification is host work

    from ..models.import_torch import (
        load_torch_checkpoint,
        merge_into_variables,
    )

    try:
        sd = load_torch_checkpoint(args.checkpoint)
    except SystemExit:
        raise
    except Exception as e:  # torch.load raises pickle/zip/Runtime errors
        # on truncated or non-checkpoint files — all usage-class here
        print(f"FAIL: cannot load {args.checkpoint}: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        raise SystemExit(2)

    # infer num_classes from the head so ImageNet-1k and finetuned heads
    # both verify
    head_key = {"vgg19_bn": "classifier.6.weight",
                "tresnet_m": "head.fc.weight",
                "timm": "head.fc.weight"}.get(args.arch, "fc.weight")
    if head_key not in sd:
        print(f"FAIL: {head_key!r} missing — not a full {args.arch} "
              f"state_dict (keys sample: {sorted(sd)[:5]})", file=sys.stderr)
        raise SystemExit(2)
    num_classes = int(np.asarray(sd[head_key]).shape[0])

    try:
        tmodel, make_flax, converter, size = _build_pair(args.arch, num_classes)
    except SystemExit:
        print(f"FAIL: unknown --arch {args.arch!r}", file=sys.stderr)
        raise SystemExit(2)

    # strict load into the oracle: a real checkpoint with renamed/missing
    # keys fails HERE with the exact key lists, before any numerics
    try:
        tmodel.load_state_dict(
            {k: torch.as_tensor(np.asarray(v)) for k, v in sd.items()},
            strict=True)
    except RuntimeError as e:
        print(f"FAIL: oracle strict load rejected the state_dict:\n{e}",
              file=sys.stderr)
        raise SystemExit(2)
    tmodel.eval()

    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    x = rng.normal(size=(args.batch, 3, size, size)).astype(np.float32)
    with torch.no_grad():
        ref = tmodel(torch.from_numpy(x)).numpy()

    fmodel = make_flax()
    variables = fmodel.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        jnp.zeros((1, size, size, 3)), train=False)
    merged = merge_into_variables(variables, converter(sd))
    got = np.asarray(fmodel.apply(merged, jnp.asarray(x.transpose(0, 2, 3, 1)),
                                  train=False))

    max_abs = float(np.max(np.abs(got - ref)))
    denom = np.maximum(np.abs(ref), 1.0)
    max_rel = float(np.max(np.abs(got - ref) / denom))
    ok = max_abs <= args.tol or max_rel <= args.tol
    verdict = "PASS" if ok else "FAIL"
    print(f"{verdict}: {args.arch} ({num_classes} classes) "
          f"max|Δ|={max_abs:.3e} max_rel={max_rel:.3e} tol={args.tol:.0e} "
          f"over batch {args.batch} @ {size}px "
          f"(logit std {float(np.std(ref)):.3f})")
    raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
