"""`scenario` entrypoint — the supervised train→serve chaos drill
(scenario/; runbook: docs/operations.md "Scenario drill").

    python -m ddp_classification_pytorch_tpu.cli.scenario \
        --scenario_spec scenario.json --out runs/scenario

Launches an elastic trainer pod publishing checkpoints into a shared run
dir while serve replicas (fleet members sharing leases and the rolling
drain token) sustain offered load, drives the chaos timeline from the spec
(including `spike_load` offered-load steps and autoscaling when the spec
arms `serve.max_replicas`), then replays the recorded `events.jsonl`
through the S1–S5 invariant checkers. `--check_only` skips the run and re-checks an existing
events file (post-mortem of a red run, and how the synthetic-timeline tests
prove each checker fires).

rc discipline (registered in analysis/lint.py's 0–11 catalogue):

- **0** — run converged AND every invariant held;
- **1** — an invariant was violated, or a supervised process failed
  (trainer rc != 0 through its restart budget, replica drain broke,
  analyzer gate red);
- **2** — malformed `--scenario_spec`, or (under `--check_only`) an
  events file with unknown event kinds / missing required fields
  (deterministic; never retried). A fuzzer replaying corrupt forensics
  must fail loudly, not pass vacuously.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ddp_classification_pytorch_tpu.cli.scenario",
        description="supervised train→serve chaos scenario with "
                    "machine-checked safety/availability invariants",
    )
    p.add_argument("--scenario_spec", required=True,
                   help="path to a scenario JSON file, or an inline JSON "
                        "object (docs/operations.md has the grammar); "
                        "malformed specs exit rc 2")
    p.add_argument("--out", default="runs/scenario",
                   help="run dir shared by the trainer pod and the serve "
                        "replicas (checkpoints, logs, events.jsonl)")
    p.add_argument("--events", default="",
                   help="events.jsonl path (default <out>/events.jsonl); "
                        "with --check_only, the timeline to re-check")
    p.add_argument("--check_only", action="store_true",
                   help="skip the run: replay an existing events file "
                        "through the invariant checkers only; the file "
                        "is schema-validated (unknown kinds / missing "
                        "fields exit rc 2)")
    p.add_argument("--skip_lint", action="store_true",
                   help="skip the end-of-run analyzer gate (lint.sh) and "
                        "the S4 check — for quick iteration, not CI")
    return p


def main(argv: Optional[Sequence[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    from ..scenario.spec import SpecError, load_spec

    try:
        spec = load_spec(args.scenario_spec)
    except SpecError as e:
        print(f"[scenario] spec error: {e}", file=sys.stderr)
        raise SystemExit(2) from None

    events_path = args.events or os.path.join(args.out, "events.jsonl")
    run_rc = 0
    if not args.check_only:
        from ..scenario.supervisor import ScenarioSupervisor

        sup = ScenarioSupervisor(spec, args.out, events_path,
                                 skip_lint=args.skip_lint)
        print(f"[scenario] drill: {spec.trainer.hosts} trainer host(s), "
              f"{spec.serve.replicas} serve replica(s), "
              f"{spec.load.rps} rps offered → {args.out}")
        run_rc = sup.run()
        for f in sup.failures:
            print(f"[scenario] FAIL: {f}", file=sys.stderr)

    from ..obs.events import read_events, validate_events
    from ..scenario.invariants import check_invariants

    events = read_events(events_path)
    if not events:
        print(f"[scenario] no events at {events_path} — nothing to check",
              file=sys.stderr)
        raise SystemExit(1)
    if args.check_only:
        # a replayed timeline is committed forensics: unknown kinds or
        # missing fields mean the checkers would run on half-evidence
        # and pass vacuously — deterministic rc 2, same as a bad spec
        schema_errors = validate_events(events)
        if schema_errors:
            for err in schema_errors[:10]:
                print(f"[scenario] events error: {err}", file=sys.stderr)
            print(f"[scenario] events error: {len(schema_errors)} schema "
                  f"error(s) in {events_path}", file=sys.stderr)
            raise SystemExit(2)
    restarts = os.path.join(args.out, "restarts.log")
    violations = check_invariants(
        events, spec,
        restarts_logs=[restarts] if os.path.exists(restarts) else None,
        require_lint=not args.skip_lint)
    by_kind: dict = {}
    for e in events:
        by_kind[e["kind"]] = by_kind.get(e["kind"], 0) + 1
    print(f"[scenario] {len(events)} events: "
          + ", ".join(f"{k}={v}" for k, v in sorted(by_kind.items())))
    for v in violations:
        print(f"[scenario] VIOLATION {v}", file=sys.stderr)
    if violations or run_rc != 0:
        print(f"[scenario] RED: {len(violations)} violation(s), "
              f"run rc={run_rc}", file=sys.stderr)
        raise SystemExit(1)
    print("[scenario] GREEN: S1 verified-serve, S2 availability floor, "
          "S3 bounded adoption"
          + ("" if args.skip_lint else ", S4 analyzer gate")
          + ", S5 fleet all held")


if __name__ == "__main__":
    main()
