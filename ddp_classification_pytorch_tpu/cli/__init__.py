from .train import main, build_parser  # noqa: F401
