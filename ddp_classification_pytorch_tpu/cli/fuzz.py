"""`fuzz` entrypoint — coverage-steered property-based search over the
scenario fault space (scenario/fuzz.py; runbook: docs/operations.md
"Fuzzing runbook").

    python -m ddp_classification_pytorch_tpu.cli.fuzz \
        --seed 0 --budget 20 --out runs/fuzz

A seeded sampler draws valid `ScenarioSpec`s from the grammar (fault
kinds enumerated from utils/chaos.py's FAULT_GRAMMAR), steered by the
persistent coverage ledger (``<out>/fuzz_ledger.json``) toward uncovered
(fault kind × subsystem) pairs. Each spec runs through the chosen runner:

- ``--runner sim`` (default) — a deterministic correct-behavior event
  simulation replayed through the S1–S5 checkers: milliseconds per spec,
  finds checker-vs-model disagreements (checker bugs);
- ``--runner drill`` — the real `ScenarioSupervisor` with subprocesses:
  minutes per spec, finds process bugs. Use a small ``--budget``.

On any violation the failing spec is delta-minimized (drop fault → drop
timeline item → shrink timing → shrink topology, re-running after each
cut) and the smallest failing spec + its forensics land under
``<out>/minimized/`` ready for promotion into tests/data/scenarios/.

rc discipline (registered in analysis/lint.py's 0–11 catalogue):

- **0** — budget exhausted, every sampled scenario green;
- **1** — a violation was found; the minimized spec was written;
- **2** — bad arguments (non-positive budget/candidates, unknown
  runner; deterministic, never retried).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ddp_classification_pytorch_tpu.cli.fuzz",
        description="coverage-steered scenario fuzzing with a "
                    "delta-minimizing shrinker",
    )
    p.add_argument("--seed", type=int, default=0,
                   help="sampler seed; same seed → byte-identical spec "
                        "sequence (a failure reproduces from seed alone)")
    p.add_argument("--budget", type=int, default=20,
                   help="number of scenarios to sample and run (< 1 exits "
                        "rc 2)")
    p.add_argument("--out", default="runs/fuzz",
                   help="artifact dir: fuzz_ledger.json, minimized/ on a "
                        "red, drill run dirs under --runner drill")
    p.add_argument("--ledger", default="",
                   help="coverage ledger path (default <out>/fuzz_ledger"
                        ".json); persists across runs so the next budget "
                        "steers toward still-uncovered pairs")
    p.add_argument("--runner", choices=("sim", "drill"), default="sim",
                   help="sim: deterministic event simulation through the "
                        "checkers (ms/spec); drill: the real supervisor "
                        "(minutes/spec)")
    p.add_argument("--candidates", type=int, default=4,
                   help="specs drawn per sample; the one covering the most "
                        "uncovered ledger pairs runs (1 = no steering)")
    p.add_argument("--max_shrink_runs", type=int, default=200,
                   help="re-run cap for the delta-minimizer")
    return p


def main(argv: Optional[Sequence[str]] = None) -> None:
    args = build_parser().parse_args(argv)
    if args.budget < 1:
        print(f"[fuzz] --budget must be >= 1, got {args.budget}",
              file=sys.stderr)
        raise SystemExit(2)
    if args.candidates < 1:
        print(f"[fuzz] --candidates must be >= 1, got {args.candidates}",
              file=sys.stderr)
        raise SystemExit(2)
    if args.max_shrink_runs < 0:
        print(f"[fuzz] --max_shrink_runs must be >= 0, got "
              f"{args.max_shrink_runs}", file=sys.stderr)
        raise SystemExit(2)

    from ..scenario import fuzz as fuzzlib

    ledger_path = args.ledger or os.path.join(args.out, "fuzz_ledger.json")
    ledger = fuzzlib.CoverageLedger.load(ledger_path)
    if args.runner == "drill":
        runner = fuzzlib.DrillRunner(os.path.join(args.out, "drills"))
    else:
        runner = fuzzlib.sim_runner
    fuzzer = fuzzlib.Fuzzer(runner, seed=args.seed,
                            candidates=args.candidates, ledger=ledger,
                            max_shrink_runs=args.max_shrink_runs,
                            log=lambda s: print(f"[fuzz] {s}"))
    result = fuzzer.run(args.budget)
    ledger.save()
    uncovered = ledger.uncovered()
    print(f"[fuzz] coverage: {ledger.distinct()} distinct "
          f"(kind x subsystem) pair(s) over {ledger.specs_run} spec(s) "
          f"({len(uncovered)} still uncovered) → {ledger_path}")

    if not result.found:
        print(f"[fuzz] GREEN: {result.specs_run} scenario(s), every "
              "invariant held")
        return

    mini_dir = os.path.join(args.out, "minimized")
    os.makedirs(mini_dir, exist_ok=True)
    spec_path = os.path.join(mini_dir, "spec.json")
    with open(spec_path, "w") as f:
        f.write(result.minimized.to_json())
    with open(os.path.join(mini_dir, "seed_spec.json"), "w") as f:
        f.write(result.seed_spec.to_json())
    if args.runner == "sim":
        # the minimized forensics, replayable via cli.scenario --check_only
        events = fuzzlib.simulate_events(result.minimized)
        with open(os.path.join(mini_dir, "events.jsonl"), "w") as f:
            for rec in events:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
    with open(os.path.join(mini_dir, "report.json"), "w") as f:
        json.dump({"seed": args.seed, "specs_run": result.specs_run,
                   "shrink_runs": result.shrink_runs,
                   "violations": [str(v) for v in result.violations]},
                  f, indent=2, sort_keys=True)
        f.write("\n")
    for v in result.violations:
        print(f"[fuzz] VIOLATION {v}", file=sys.stderr)
    print(f"[fuzz] RED: failure found at spec {result.specs_run}/"
          f"{args.budget}, minimized in {result.shrink_runs} run(s) → "
          f"{spec_path}", file=sys.stderr)
    raise SystemExit(1)


if __name__ == "__main__":
    main()
