"""Config tree for all workloads.

The reference scatters configuration across four argparse blocks and hardcoded
constants (BASELINE/main.py:25-32,84-87; ARCFACE/arc_main.py:34-43;
CDR/main.py:32-57; NESTED/train.py:458-486). Here every knob is a typed field
on one dataclass tree, with per-workload presets that reproduce the reference
defaults exactly.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence


@dataclass
class DataConfig:
    """Dataset + input-pipeline options.

    Reference semantics carried over: per-class image caps (500 for BASELINE
    BASELINE/main.py:98,107; 400 for ARCFACE arc_main.py:190; CDR additionally
    keeps only the first 100 class dirs, CDR/main.py:73-81), ImageNet
    normalization constants, and epoch-seeded reshuffle equal to
    `DistributedSampler.set_epoch` (BASELINE/main.py:269).
    """

    train_dir: str = ""
    val_dir: str = ""
    dataset: str = "imagefolder"  # imagefolder | synthetic | plc
    image_size: int = 224
    train_crop_size: int = 256  # reference RandomResizedCrop(256), BASELINE/main.py:61
    num_classes: int = 2173  # BASELINE/main.py:85
    imgs_per_class: int = 500  # BASELINE/main.py:98
    max_classes: int = 0  # 0 = all; CDR uses 100 (CDR/main.py:73)
    batch_size: int = 16  # per-process global batch is batch_size * num_hosts
    num_workers: int = 4  # BASELINE/main.py:130-131
    prefetch: int = 2
    # device-side prefetch depth (data/device_prefetch.py): a background
    # stager thread keeps this many fully-formed, globally-sharded device
    # batches staged ahead of the step loop, so batch assembly + H2D
    # transfer overlap device compute instead of serializing with it. Each
    # staged batch holds device memory (~depth extra batches of HBM).
    # 0 = synchronous assembly inside the step loop (the pre-prefetch path).
    device_prefetch: int = 2
    # double-buffered H2D dispatch (data/device_prefetch.py overlap mode):
    # host-batch fetch and the make_global_array H2D transfer pipeline on
    # two threads, so batch N+1's fetch overlaps batch N's in-flight
    # transfer (one-slot in-flight budget). Ignored at device_prefetch=0,
    # which stays bit-for-bit synchronous.
    h2d_overlap: bool = False
    synthetic_size: int = 0  # for dataset == "synthetic"
    # H2D wire format (data/transforms.py, train/steps.py). "uint8"
    # (default): transforms emit raw uint8 HWC pixels — ¼ the host→device
    # bytes of normalized float32 — and the jitted step normalizes
    # `(x/255−μ)/σ` (plus the train-time horizontal flip, rng threaded from
    # the step key) as a device-side epilogue XLA fuses into the first
    # conv's input read. "float32": the legacy host-normalize path,
    # numerically exact to the pre-uint8 framework — the fallback when
    # bitwise reproduction of an old run matters. The two match to float
    # tolerance on identical crops (quantization is pre-normalize in both).
    input_dtype: str = "uint8"
    # transform preset: baseline | cdr | cifar | clothing1m (SURVEY C15)
    transform: str = "baseline"
    # use the native C++ dataplane (libjpeg decode + fused transform) for
    # supported presets; auto-falls back to the Python/PIL path
    native_loader: bool = True


@dataclass
class ModelConfig:
    """Backbone + head selection.

    arch covers the reference zoo: torchvision-style ImageNet ResNets
    (NESTED/model/imagenet_resnet.py), CIFAR ResNets
    (NESTED/model/cifar_resnet.py), VGG19-BN (NESTED/model/vgg.py) — plus the
    framework's transformer extension (vit_t16/vit_s16/vit_b16, models/vit.py)
    whose token axis ring-shards over the mesh 'model' axis (long-context
    sequence parallelism; the reference has no attention, SURVEY §2.2).
    """

    arch: str = "resnet50"
    variant: str = "imagenet"  # imagenet | cifar
    pretrained: bool = False  # load converted torchvision weights at init
    # path to a torch .pth/.pt checkpoint (torchvision state_dict, a
    # {'state_dict': ...} wrapper, or the reference's NESTED {'feat','cls'}
    # format). Zero-egress environments supply the file; no URL download.
    pretrained_path: str = ""
    feat_dim: int = 0  # 0 = arch default (512 r18/34, 2048 r50+)
    head: str = "fc"  # fc | arcface | nested
    # ArcFace (ARCFACE/arc_main.py:234: s=30, m=0.5, easy_margin=True)
    arc_s: float = 30.0
    arc_m: float = 0.5
    arc_easy_margin: bool = True
    arc_embed_dim: int = 256  # arc_main.py:223-231: 2048->512->256 embedding
    # reference quirk: arc_main.py:230 appends LogSoftmax to the EMBEDDING
    # (almost certainly a bug — features are re-normalized in the margin
    # product); off by default, flag preserves bug-compat training
    arc_log_softmax_quirk: bool = False
    # Nested dropout (NESTED/train.py:512-530: nested=100 i.e. sigma of the
    # Gaussian over feature dims; freeze_bn=True)
    nested_std: float = 100.0
    freeze_bn: bool = False
    dropout: float = 0.0
    dtype: str = "bfloat16"  # compute dtype; params and BN stats stay f32
    remat: bool = False  # per-block rematerialization (activation-memory lever)
    # ViT family: dropless split-FFN mixture-of-experts in every block
    # (ops/moe.py); >0 enables it. Experts shard over the mesh `model` axis
    # (expert parallelism) — the axis serves one role per config, so this
    # excludes ring-SP/PP for the same run.
    moe_experts: int = 0
    moe_top_k: int = 2
    # Switch-style router load-balance penalty weight (ops/moe.py::
    # load_balance_loss, sown per block, summed into the training loss)
    moe_aux_weight: float = 0.01
    # ViT family: use the Pallas streaming flash-attention kernel for the
    # unsharded attention path (ops/flash_attention.py); the ring-sharded
    # path consumes each visiting KV shard with it too
    flash_attention: bool = False
    # Auto-pick floor for the unsharded path: below this token count,
    # --flash_attention routes to XLA's fused dense attention instead of the
    # kernel (measured on v5e: flash wins from ~2048 tokens, dense is
    # equal-or-better in the hundreds — docs/performance.md knob #4).
    # 0 = always use the kernel. The ring path ignores this floor: there the
    # kernel's job is keeping the per-shard score tile unmaterialized, which
    # matters at any length.
    flash_min_tokens: int = 1024
    # ViT only: run the LayerNorms in the compute dtype (bf16) instead of
    # f32 — a bandwidth experiment for the HBM-bound ViT step (VERDICT r3
    # #5; A/B harness scripts/ab_vit_perf.py). Off = the standard
    # f32-LN recipe every convergence record uses.
    ln_bf16: bool = False


@dataclass
class OptimConfig:
    """Optimizer + LR schedule.

    Reference recipes: SGD(momentum=0.9) lr 1e-3 + StepLR(10, 0.1)
    (BASELINE/main.py:86,153-154); Adam-or-SGD switch (arc_main.py:248-253);
    MultiStepLR([10,20]) (CDR/main.py:340) / ([20,30,40,120])
    (NESTED/train.py:472); linear iteration warmup (BASELINE/main.py:170-197,
    NESTED/train.py:276-327).
    """

    optimizer: str = "sgd"  # sgd | adam
    lr: float = 1e-3
    momentum: float = 0.9
    weight_decay: float = 0.0
    # Per-group hyperparameters for the head param group (ArcFace margin
    # head — the reference builds ONE optimizer over TWO param groups,
    # arc_main.py:248-253; its recipes use identical hyperparams per group,
    # so None = inherit lr/weight_decay and the optimizer reduces to a
    # single transform over the joint tree). Set to diverge the groups.
    head_lr: Optional[float] = None
    head_weight_decay: Optional[float] = None
    schedule: str = "step"  # step | multistep | constant
    step_size: int = 10
    gamma: float = 0.1
    milestones: Sequence[int] = field(default_factory=lambda: (10, 20))
    warmup_iters: int = 0
    warmup_start_lr: float = 1e-6  # BASELINE/main.py:175
    grad_transform: str = "none"  # none | cdr
    # CDR (CDR/main.py:37,54): keep top (1-noise_rate) of grad mass
    noise_rate: float = 0.2
    num_gradual: int = 10
    # Reference quirk (CDR/main.py:222-227): the gradual clip schedule is dead
    # code, overwritten with the constant. True reproduces the reference.
    cdr_dead_schedule: bool = True


@dataclass
class ParallelConfig:
    """Mesh layout. The reference supports DP only (SURVEY §2.2); we add a
    `model` axis so wide class-dim heads (ArcFace, 2173→10⁶ identities) can be
    tensor-sharded — the vision analogue of sequence parallelism."""

    data_axis: int = 0  # 0 = all devices on data axis
    model_axis: int = 1
    # microbatching / grad accumulation (capability headroom; reference: none)
    grad_accum: int = 1
    # >0 enables GPipe pipeline parallelism for the ViT family: the block
    # stack shards into stages and this many microbatches stream through
    # them (ops/pipeline.py). With pipeline_stages=0 the stages live on the
    # model axis (one role per config: class-TP | ring-attention SP | PP).
    pipeline_microbatches: int = 0
    # >1 gives the pipeline its OWN mesh axis ('pipe', parallel/mesh.py)
    # with this many stages, composing dp×tp×pp in one program: blocks
    # stage-shard over 'pipe' while the model axis keeps class-dim TP
    # (e.g. an arcface head via arcface_sharded_ce). Device count must
    # equal data_axis × model_axis × pipeline_stages.
    pipeline_stages: int = 0
    # multi-slice deployments: number of DCN-connected slices. >0 builds a
    # two-tier mesh (parallel/mesh.py::make_hybrid_mesh) — DP spans slices
    # (one DCN allreduce/step), model axis stays inside a slice on ICI.
    dcn_slices: int = 0
    # partial-FC-style ArcFace loss: compute softmax-CE with the class dim
    # sharded over the model axis (ops/sharded_head.py) — no (B, C) logits
    # anywhere. The scale path for 10⁵-10⁶-identity heads; requires
    # model_axis > 1 and num_classes divisible by it.
    arcface_sharded_ce: bool = False
    # ZeRO-1 optimizer-state partitioning (Rajbhandari et al. 2020): shard
    # each optimizer-state leaf over the data axis so XLA compiles
    # reduce-scatter -> shard-local update -> param all-gather instead of
    # replicated all-reduce + N identical updates. "auto" = on when the
    # data axis spans >1 device, off otherwise; "on"/"off" force it. The
    # update arithmetic is unchanged (each shard computes exactly the
    # slice of the replicated update it owns), so checkpoints and parity
    # pins are bit-compatible with the replicated layout.
    zero_opt: str = "auto"  # auto | on | off
    # Wire dtype for the cross-replica gradient reduction. "bfloat16"
    # casts grads to bf16 before the reduction and back to the param
    # dtype after, halving the all-reduce payload; the optimizer update
    # still accumulates into f32 master params. Rides a shard_map grad
    # section, so it composes with zero_opt but not with pipeline stages
    # or arcface_sharded_ce (rejected at step build).
    grad_reduce_dtype: str = "float32"  # float32 | bfloat16


@dataclass
class PLCConfig:
    """Progressive-label-correction loop (PLC silo — the reference left it
    '// TODO' with no training entry point, SURVEY §1; here it is a first-class
    workload wiring `ops.labelnoise` corrections into the train loop via
    `FolderDataset.update_corrupted_label` semantics, PLC/FolderDataset.py:80-82)."""

    correction: str = "lrt"  # lrt | prob
    current_delta: float = 0.3  # PLC/utils.py:291 θ
    delta_increment: float = 0.1  # β
    thd: float = 0.1  # prob_correction confidence threshold (:321)
    warmup_epochs: int = 2  # epochs of plain training before correction starts
    # collect f(x) with the prediction batch's own BN stats (as the reference
    # harvests softmax during training, utils.py:269-271) vs running averages.
    # Default False: the ordered correction scan is class-sorted, so each
    # prediction batch is nearly single-class and batch statistics skew its
    # normalization — measured 63% vs 99% argmax-vs-truth on a 97%-val model
    # (train/plc_loop.py::_predict_pipeline); True reproduces the reference's
    # harvest-during-training flavor and is only safe on shuffled batches
    batch_stat_predictions: bool = False
    # synthetic-noise injection for experiments (utils.py:149-220); -1 = off
    noise_type: int = -1
    noise_factor: float = 1.2
    # Safety valve over the reference behavior: cap the fraction of labels a
    # single correction pass may flip, keeping the most-confident flips
    # (largest prediction-vs-label disagreement). Correction on an immature
    # model self-confirms: observed live, a warmup-5 run flipped 17% of
    # labels in one pass and collapsed the label set onto 3 classes (noise
    # 19% -> 82%). 1.0 = uncapped reference semantics.
    max_flip_frac: float = 1.0


@dataclass
class RunConfig:
    """Loop + IO. Epochs/ckpt/record semantics per BASELINE/main.py:258-317."""

    epochs: int = 100  # NUM_EPOCH, BASELINE/main.py:87
    seed: int = 999  # set_seed(999), BASELINE/main.py:43-50
    log_every: int = 20  # BASELINE/main.py:284
    eval_every: int = 1
    eval_first: bool = False  # initial Test before training (NESTED:413-414)
    out_dir: str = "./runs/default"
    save_every_epoch: bool = True  # BASELINE/main.py:308-310
    save_best_only: bool = False  # NESTED netBest.pth policy, train.py:154-161
    async_checkpoint: bool = True  # background serialize+write (SURVEY §5)
    keep_checkpoints: int = 0  # prune epoch ckpts beyond N (0 = keep all)
    resume: str = ""  # NESTED --resumePth, train.py:372-378
    # preemption recovery (SURVEY §5 failure-detection row): pick up the
    # latest checkpoint in out_dir automatically — the restart command is
    # then identical to the start command (scripts/supervise.sh relies on it)
    auto_resume: bool = False
    write_records: bool = True  # output.txt / history.json (SURVEY C23)
    # TensorBoard event files at <out_dir>/tb (utils/tensorboard.py, no deps).
    # The reference only ever carried commented-out tensorboardX imports
    # (BASELINE/main.py:41-42,311)
    tensorboard: bool = False
    # observability (SURVEY §5 tracing/race-detection rows — the reference has
    # ad-hoc wall-clock timers only)
    profile_steps: int = 0  # >0: capture a jax.profiler trace of steps [10, 10+N)
    profile_dir: str = ""   # default: <out_dir>/profile
    debug_nans: bool = False  # jax_debug_nans for fail-fast numeric debugging
    # mid-run hang detection (observed live 2026-08-01: a tunnel lease churn
    # froze a training process mid-step FOREVER — zero CPU, no exception; a
    # hang never exits, so supervise.sh alone cannot recover it). >0 arms a
    # heartbeat watchdog: if no host-observed progress (log-line sync,
    # epoch-end sync, eval sync, final-drain start) lands for this many
    # seconds, the process exits
    # loudly (code 7) so supervise.sh + auto_resume can take over. Set WELL
    # above the slowest legitimate gap — first compile on a tunneled TPU can
    # take 10+ min (TResNet); 0 disables.
    hang_timeout_s: float = 0.0
    # Non-finite step sentinel (train/sentinel.py): every jitted train step
    # skips its update (identity) when loss/grad-norm go non-finite; after
    # this many CONSECUTIVE skips the run exits rc 8 ("diverged") — a
    # deterministic failure supervise.sh must NOT hot-loop restart. The
    # streak is evaluated at the log_every sync cadence, so detection lands
    # within one log window of the threshold. 0 = skip forever, never exit.
    max_bad_steps: int = 25
    # Deterministic fault injection (utils/chaos.py), e.g.
    # "nan_loss@step=7,ckpt_io@epoch=1,loader_io@batch=3,sigterm@step=20".
    # CHAOS_FAULT_SPEC env overrides; empty = every hook is inert and the
    # train step compiles to exactly the uninjected program.
    fault_spec: str = ""
    # Compile sentinel (analysis/compile_sentinel.py): the trainer arms a
    # recompile guard once the first eval'd epoch completes (all steady-state
    # programs compiled); any later compile is logged with the offending
    # function + aval signature. False = warn-only; True = deterministic
    # rc 2 at the epoch boundary (a steady-state recompile replays on
    # restart, so supervisors must not retry it).
    strict_compile: bool = False


def dp_round_up_buckets(buckets: Sequence[int], dp: int) -> tuple:
    """Round each bucket UP to the next dp multiple and dedup (ascending):
    the compile-count bound survives data-parallel serving — at most
    len(buckets) padded shapes, each evenly shardable over 'data'. Shared
    by `ServeConfig.resolve_buckets` (auto-buckets) and `bench.py --serve`
    (which must run its default bucket list on whatever mesh exists)."""
    if dp < 1:
        raise ValueError(f"dp must be >= 1, got {dp}")
    return tuple(sorted({((int(b) + dp - 1) // dp) * dp for b in buckets}))


@dataclass
class ServeConfig:
    """Inference serving (serve/ subsystem, cli/serve.py).

    The engine assembles micro-batches from a bounded request queue under a
    deadline and pads them to a small fixed set of bucket sizes, so the
    jitted predict compiles at most len(buckets) programs — the classic
    adaptive-batching trade (Clipper-style): `batch_timeout_ms` bounds the
    latency a lone request pays waiting for company, `max_batch` bounds how
    much throughput a full queue can amortize into one device dispatch.
    """

    max_batch: int = 8  # largest micro-batch the batcher assembles
    # deadline from the FIRST queued request until a partial batch flushes;
    # 0 = never wait (every collect takes whatever is queued right now)
    batch_timeout_ms: float = 5.0
    queue_depth: int = 64  # bounded intake; submits beyond it are rejected
    # padded batch shapes (ascending). () = powers of two up to max_batch.
    # Each bucket is one compiled program; requests pad to the smallest
    # bucket that fits the collected batch. Under a >1-device serve mesh
    # every bucket must be divisible by the data-parallel width (each
    # padded batch shards evenly over 'data'); auto-buckets round up.
    buckets: Sequence[int] = ()
    # devices on the serve mesh's data axis (0 = all visible devices);
    # per-replica throughput scales with it — the predict runs dp-sharded
    # over the mesh, batches arrive as data-sharded global arrays
    serve_devices: int = 0
    # AOT executable sidecar (serve/aot.py): "auto" = <run dir>/aot next
    # to the served checkpoint, "off" = disable, else an explicit dir. A
    # joining replica deserializes the warmed bucket executables instead
    # of compiling them — zero steady-state compiles on a warm boot.
    aot_cache: str = "auto"
    topk: int = 5  # classes returned per request
    checkpoint: str = ""  # explicit checkpoint to serve (verified; rc 2 if corrupt)
    watch_dir: str = ""  # run dir to poll for checkpoint hot-reload
    reload_poll_s: float = 5.0  # hot-reload poll cadence
    port: int = 0  # >0: stdlib http front-end on this port (serve/http.py)
    log_every_s: float = 10.0  # metrics console line cadence
    # Compile sentinel: warmup() arms a recompile guard after prepaying the
    # bucket programs; a steady-state compile (a shape leaking past the
    # bucket padding) is counted + logged. False = warn-only; True = the
    # engine stops intake and cli.serve exits rc 2 (deterministic).
    strict_compile: bool = False
    # --- serve-fleet control plane (serve/fleet.py) ---
    # shared fleet run dir ("" = fleet off, lone-replica mode). Replicas
    # sharing it heartbeat via $FLEET_DIR/serve_fleet/lease.r<id> and
    # serialize hot reloads through the single drain token (rolling wave).
    fleet_dir: str = ""
    fleet_replica: int = 0  # this replica's id in the shared fleet dir
    fleet_ttl_s: float = 15.0  # lease/token freshness horizon (mtime vs now)
    # admission control above the engine queue: 0 = off (engine bound only);
    # >0 = shed when measured wait (depth / observed service rate) exceeds
    # this deadline (fair-share shed at 1x, any-tenant shed at 2x)
    admission_deadline_ms: float = 0.0
    # per-tenant weighted fair shares, "name:weight,name:weight"
    # ("" = single 'default' tenant at weight 1)
    admission_tenants: str = ""

    def validate_fleet(self) -> None:
        """Config-shaped fleet/admission validation (ValueError = rc 2)."""
        if self.fleet_replica < 0:
            raise ValueError(
                f"serve.fleet_replica must be >= 0, got {self.fleet_replica}")
        if self.fleet_ttl_s <= 0:
            raise ValueError(
                f"serve.fleet_ttl_s must be > 0, got {self.fleet_ttl_s}")
        if self.admission_deadline_ms < 0:
            raise ValueError(
                f"serve.admission_deadline_ms must be >= 0, "
                f"got {self.admission_deadline_ms}")
        from .serve.fleet import parse_tenants

        parse_tenants(self.admission_tenants)

    def resolve_buckets(self, dp: int = 1) -> tuple:
        """Validated ascending bucket tuple (ValueError = config-shaped,
        the serve CLI maps it to the deterministic rc 2).

        `dp` is the serve mesh's data-parallel width: every padded batch
        shards its leading axis over 'data', so each bucket must be a
        dp multiple or the global array cannot be assembled. Explicit
        buckets that violate this are rejected (the operator asked for
        shapes that cannot run); auto-buckets round UP to the next dp
        multiple — padding overhead, never a dropped request."""
        if self.max_batch < 1:
            raise ValueError(f"serve.max_batch must be >= 1, got {self.max_batch}")
        if self.batch_timeout_ms < 0:
            raise ValueError(
                f"serve.batch_timeout_ms must be >= 0, got {self.batch_timeout_ms}")
        if self.queue_depth < 1:
            raise ValueError(f"serve.queue_depth must be >= 1, got {self.queue_depth}")
        if self.topk < 1:
            raise ValueError(f"serve.topk must be >= 1, got {self.topk}")
        if dp < 1:
            raise ValueError(f"serve data-parallel width must be >= 1, got {dp}")
        if self.buckets:
            buckets = tuple(int(b) for b in self.buckets)
            bad = [b for b in buckets if b % dp]
            if bad:
                raise ValueError(
                    f"serve.buckets {bad} not divisible by the serve mesh's "
                    f"data-parallel width dp={dp} — every padded batch shards "
                    "its leading axis over 'data', so each bucket must be a "
                    f"multiple of {dp} (error: serve-bucket-dp-indivisible)")
        else:
            buckets, b = [], 1
            while b < self.max_batch:
                buckets.append(b)
                b *= 2
            buckets.append(self.max_batch)
            buckets = dp_round_up_buckets(buckets, dp)
        if any(b < 1 for b in buckets) or list(buckets) != sorted(set(buckets)):
            raise ValueError(
                f"serve.buckets must be positive and strictly ascending, "
                f"got {buckets}")
        if self.max_batch > buckets[-1]:
            raise ValueError(
                f"serve.max_batch={self.max_batch} exceeds the largest bucket "
                f"{buckets[-1]} — a full batch would have no padded shape to "
                "run at")
        return buckets


@dataclass
class Config:
    workload: str = "baseline"  # baseline | arcface | cdr | nested | plc
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    run: RunConfig = field(default_factory=RunConfig)
    plc: PLCConfig = field(default_factory=PLCConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)

    def replace(self, **kw: Any) -> "Config":
        return dataclasses.replace(self, **kw)


def baseline_preset() -> Config:
    """BASELINE/main.py defaults: ResNet-50, CE, batch 16/proc, SGD 1e-3,
    StepLR(10,0.1), 100 epochs, 2173 classes, ≤500 imgs/class."""
    return Config(workload="baseline")


def arcface_preset() -> Config:
    """ARCFACE/arc_main.py: ResNet-50 → 256-d embedding + ArcMarginProduct
    (s=30, m=0.5, easy_margin=True at :234), batch 32, Adam 1e-3."""
    cfg = Config(workload="arcface")
    cfg.data.batch_size = 32
    cfg.data.imgs_per_class = 400  # arc_main.py:190
    cfg.model.head = "arcface"
    cfg.optim.optimizer = "adam"
    return cfg


def cdr_preset() -> Config:
    """CDR/main.py: ResNet-50, batch 128, SGD 0.1, MultiStepLR([10,20]),
    selective-gradient step, first 100 classes."""
    cfg = Config(workload="cdr")
    cfg.data.batch_size = 128
    cfg.data.max_classes = 100
    cfg.data.num_classes = 100
    cfg.data.transform = "cdr"
    cfg.optim.lr = 0.1
    cfg.optim.schedule = "multistep"
    cfg.optim.milestones = (10, 20)
    cfg.optim.grad_transform = "cdr"
    cfg.run.epochs = 30  # CDR/main.py:54 n_epoch default
    return cfg


def nested_preset() -> Config:
    """NESTED/train.py: ResNet-50 feat + bias-free linear cls, batch 128,
    10k-iter warmup → lr 1e-2, MultiStepLR([20,30,40,120]), nested σ=100,
    freeze-BN (main() hardcodes nested=100, freeze_bn=True at :527,529)."""
    cfg = Config(workload="nested")
    cfg.data.batch_size = 128
    cfg.model.head = "nested"
    cfg.model.nested_std = 100.0
    cfg.model.freeze_bn = True
    cfg.optim.lr = 1e-2
    cfg.optim.schedule = "multistep"
    cfg.optim.milestones = (20, 30, 40, 120)
    cfg.optim.warmup_iters = 10000
    cfg.run.epochs = 50
    cfg.run.save_best_only = True
    cfg.run.eval_first = True  # initial Test before training (train.py:413-414)
    return cfg


def plc_preset() -> Config:
    """PLC correction training on Clothing1M-scale data: ResNet-50, batch 128,
    LRT correction after 2 warmup epochs. The reference shipped the dataset +
    algorithms but no trainer (README.md:12 'PLC // TODO'); recipe constants
    follow the PLC paper defaults encoded in utils.py:291-360."""
    cfg = Config(workload="plc")
    cfg.data.batch_size = 128
    cfg.data.num_classes = 14  # Clothing1M
    cfg.optim.lr = 0.01
    cfg.optim.schedule = "multistep"
    cfg.optim.milestones = (10, 20)
    cfg.run.epochs = 30
    return cfg


PRESETS = {
    "baseline": baseline_preset,
    "arcface": arcface_preset,
    "cdr": cdr_preset,
    "nested": nested_preset,
    "plc": plc_preset,
}


def get_preset(name: str) -> Config:
    try:
        return PRESETS[name]()
    except KeyError:
        raise ValueError(f"unknown workload {name!r}; one of {sorted(PRESETS)}")
