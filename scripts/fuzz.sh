#!/usr/bin/env bash
# Scenario fuzzing — a bounded, seeded property-based search over the
# chaos fault space (scenario/fuzz.py; runbook: docs/operations.md
# "Fuzzing runbook").
#
# Samples random valid scenario specs (fault kinds x timing x topology x
# timeline actions), steered by the persistent coverage ledger toward
# uncovered (fault kind x subsystem) pairs, runs each through the fast
# correct-behavior simulator + S1-S5 checkers, and on any violation
# delta-minimizes the spec to its smallest failing form under
# $OUT/minimized/ — ready to promote into tests/data/scenarios/.
# Exits with cli.fuzz's code: 0 green, 1 minimized failure found,
# 2 bad args.
#
#   bash scripts/fuzz.sh                       # seeded default budget
#   FUZZ_SEED=7 FUZZ_BUDGET=200 bash scripts/fuzz.sh runs/fuzz-nightly
#   FUZZ_RUNNER=drill FUZZ_BUDGET=3 bash scripts/fuzz.sh   # real drills
#
# Flags used here are locked against the cli.fuzz parser by
# tests/test_scripts_meta.py.
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
OUT=${1:-"$REPO/runs/fuzz"}
SEED=${FUZZ_SEED:-0}
BUDGET=${FUZZ_BUDGET:-50}
RUNNER=${FUZZ_RUNNER:-sim}

cd "$REPO"
exec env JAX_PLATFORMS=cpu python -m ddp_classification_pytorch_tpu.cli.fuzz \
    --seed "$SEED" --budget "$BUDGET" --runner "$RUNNER" --out "$OUT"
