#!/usr/bin/env bash
# PLC workload — progressive label correction on Clothing1M-format
# annotations. The reference shipped only the dataset + algorithms (its
# README marks PLC "// TODO"); this trainer completes the capability.
set -euo pipefail
FOLDER=${1:-/data/clothing1m}
python -m ddp_classification_pytorch_tpu.cli.train plc \
  --dataset plc --train_dir "$FOLDER" --batchsize 128 --model resnet50 \
  --correction lrt --delta 0.3 --out ./runs/plc "${@:2}"
