"""Torch ground-truth arm of the digits head-to-head (VERDICT r4 next #3).

Trains a CIFAR-stem ResNet-18 in PLAIN PYTORCH on the exported digits
imagefolder with the reference BASELINE recipe — CE loss, SGD(momentum 0.9),
per-iteration linear warmup then MultiStep decay (BASELINE/main.py:153-154,
:170-197; CIFAR stem per the reference's CIFAR zoo NESTED/model/resnet.py:
3x3/1 stem, no maxpool, stride-1 conv2_x) — and the SAME hyperparameters,
split, and transform semantics as the framework's committed
`runs/digits_rn18` run (docs/convergence.md):

    pad-4 random crop 32 + horizontal flip + ImageNet normalize (train),
    plain normalize (val); batch 128, lr 0.1, wd 5e-4, warmup 36 iters,
    milestones (20, 32) gamma 0.1, 40 epochs, seed 999.

The two arms share the dataset and recipe but NOT the rng streams — this is
the north star's "match top-1 within 0.1%" (BASELINE.json) scaled to the
one real dataset the sandbox allows: a statistical accuracy comparison, not
a bitwise one (tests/test_torch_dynamics_parity.py pins the bitwise step
dynamics separately).

Usage:
    python scripts/export_digits.py --root /tmp/digits
    python scripts/torch_digits_baseline.py --folder /tmp/digits \
        --out runs/digits_rn18_torch_oracle
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


def build_cifar_resnet18(num_classes: int):
    """CIFAR-stem ResNet-18, written for this script (reference semantics:
    NESTED/model/resnet.py BasicBlock zoo; torchvision naming unnecessary —
    nothing is converted from this model)."""
    import torch.nn as nn

    class Block(nn.Module):
        def __init__(self, c_in, c_out, stride):
            super().__init__()
            self.conv1 = nn.Conv2d(c_in, c_out, 3, stride, 1, bias=False)
            self.bn1 = nn.BatchNorm2d(c_out)
            self.conv2 = nn.Conv2d(c_out, c_out, 3, 1, 1, bias=False)
            self.bn2 = nn.BatchNorm2d(c_out)
            self.relu = nn.ReLU(inplace=True)
            self.down = None
            if stride != 1 or c_in != c_out:
                self.down = nn.Sequential(
                    nn.Conv2d(c_in, c_out, 1, stride, bias=False),
                    nn.BatchNorm2d(c_out))

        def forward(self, x):
            r = x if self.down is None else self.down(x)
            y = self.relu(self.bn1(self.conv1(x)))
            y = self.bn2(self.conv2(y))
            return self.relu(y + r)

    class CifarResNet18(nn.Module):
        def __init__(self, classes):
            super().__init__()
            self.stem = nn.Sequential(
                nn.Conv2d(3, 64, 3, 1, 1, bias=False),
                nn.BatchNorm2d(64), nn.ReLU(inplace=True))
            layers = []
            c_in = 64
            for c_out, stride in ((64, 1), (64, 1), (128, 2), (128, 1),
                                  (256, 2), (256, 1), (512, 2), (512, 1)):
                layers.append(Block(c_in, c_out, stride))
                c_in = c_out
            self.layers = nn.Sequential(*layers)
            self.fc = nn.Linear(512, classes)

        def forward(self, x):
            h = self.layers(self.stem(x))
            return self.fc(h.mean(dim=(2, 3)))

    return CifarResNet18(num_classes)


def load_folder(root: str):
    """Deterministic sorted scan (same contract as data/imagefolder.py) →
    in-memory uint8 arrays; the whole dataset is 1,797 32x32 images."""
    from PIL import Image

    out = {}
    for split in ("train", "val"):
        xs, ys = [], []
        classes = sorted(os.listdir(os.path.join(root, split)))
        for ci, cls in enumerate(classes):
            d = os.path.join(root, split, cls)
            for name in sorted(os.listdir(d)):
                img = Image.open(os.path.join(d, name)).convert("RGB")
                xs.append(np.asarray(img, np.uint8))
                ys.append(ci)
        out[split] = (np.stack(xs), np.array(ys, np.int64))
    return out


MEAN = np.array([0.485, 0.456, 0.406], np.float32)
STD = np.array([0.229, 0.224, 0.225], np.float32)


def normalize(batch_u8: np.ndarray) -> np.ndarray:
    x = (batch_u8.astype(np.float32) / 255.0 - MEAN) / STD
    return np.ascontiguousarray(x.transpose(0, 3, 1, 2))


def augment(batch_u8: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    """pad-4 random crop + flip — the 'cifar' train preset
    (data/transforms.py; NESTED/train.py:40-44 semantics)."""
    n, h, w, _ = batch_u8.shape
    padded = np.pad(batch_u8, ((0, 0), (4, 4), (4, 4), (0, 0)))
    out = np.empty_like(batch_u8)
    ys = rng.integers(0, 9, n)
    xs = rng.integers(0, 9, n)
    flips = rng.uniform(size=n) < 0.5
    for i in range(n):
        crop = padded[i, ys[i]:ys[i] + h, xs[i]:xs[i] + w]
        out[i] = crop[:, ::-1] if flips[i] else crop
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--folder", default="/tmp/digits")
    ap.add_argument("--out", default="runs/digits_rn18_torch_oracle")
    ap.add_argument("--epochs", type=int, default=40)
    ap.add_argument("--batchsize", type=int, default=128)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--weight_decay", type=float, default=5e-4)
    ap.add_argument("--warmup_iters", type=int, default=36)
    ap.add_argument("--milestones", type=int, nargs="+", default=[20, 32])
    ap.add_argument("--gamma", type=float, default=0.1)
    ap.add_argument("--seed", type=int, default=999)
    ap.add_argument("--threads", type=int, default=0,
                    help=">0: cap torch intra-op threads (leave CPU headroom "
                         "for the TPU window catcher's probes)")
    args = ap.parse_args()

    import torch

    if args.threads > 0:
        torch.set_num_threads(args.threads)
    torch.manual_seed(args.seed)
    data = load_folder(args.folder)
    (xtr, ytr), (xva, yva) = data["train"], data["val"]
    n_train = len(ytr)
    steps_per_epoch = (n_train + args.batchsize - 1) // args.batchsize

    model = build_cifar_resnet18(10)
    opt = torch.optim.SGD(model.parameters(), lr=args.lr, momentum=0.9,
                          weight_decay=args.weight_decay)
    lossf = torch.nn.CrossEntropyLoss()
    rng = np.random.default_rng(args.seed)

    def lr_at(global_it: int, epoch: int) -> float:
        if global_it < args.warmup_iters:  # BASELINE/main.py:179
            return 1e-6 + global_it * (args.lr - 1e-6) / args.warmup_iters
        return args.lr * args.gamma ** sum(epoch >= m for m in args.milestones)

    os.makedirs(args.out, exist_ok=True)
    rec = open(os.path.join(args.out, "output.txt"), "a", buffering=1)
    best = {"val_top1": -1.0, "epoch": -1}
    git = 0
    for epoch in range(args.epochs):
        t0 = time.time()
        model.train()
        order = rng.permutation(n_train)
        tloss = tcorr = tn = 0.0
        for s in range(steps_per_epoch):
            idx = order[s * args.batchsize:(s + 1) * args.batchsize]
            xb = normalize(augment(xtr[idx], rng))
            yb = torch.from_numpy(ytr[idx])
            opt.param_groups[0]["lr"] = lr_at(git, epoch)
            git += 1
            opt.zero_grad()
            logits = model(torch.from_numpy(xb))
            loss = lossf(logits, yb)
            loss.backward()
            opt.step()
            tloss += float(loss.detach()) * len(idx)
            tcorr += float((logits.argmax(1) == yb).sum())
            tn += len(idx)

        model.eval()
        vcorr1 = vcorr3 = vloss = 0.0
        with torch.no_grad():
            for s in range(0, len(yva), args.batchsize):
                xb = torch.from_numpy(normalize(xva[s:s + args.batchsize]))
                yb = torch.from_numpy(yva[s:s + args.batchsize])
                logits = model(xb)
                vloss += float(lossf(logits, yb)) * len(yb)
                top3 = logits.topk(3, dim=1).indices
                vcorr1 += float((top3[:, 0] == yb).sum())
                vcorr3 += float((top3 == yb[:, None]).any(1).sum())
        val_top1 = vcorr1 / len(yva)
        line = (f"epoch:{epoch}\tloss:{tloss / tn:.6f}\ttop1:{tcorr / tn:.6f}"
                f"\tval_loss:{vloss / len(yva):.6f}\tval_top1:{val_top1:.6f}"
                f"\tval_top3:{vcorr3 / len(yva):.6f}"
                f"\tepoch_time:{time.time() - t0:.2f}")
        print(line)
        rec.write(line + "\n")
        if val_top1 > best["val_top1"]:
            best = {"val_top1": val_top1, "epoch": epoch}
    summary = {"arm": "torch_oracle_rn18_cifar_stem", "seed": args.seed,
               "epochs": args.epochs, "final_val_top1": val_top1,
               "best_val_top1": best["val_top1"], "best_epoch": best["epoch"],
               "n_val": int(len(yva))}
    with open(os.path.join(args.out, "summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
