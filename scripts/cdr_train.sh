#!/usr/bin/env bash
# CDR workload (reference CDR/train.sh:1-4): noisy-label robust training with
# the selective-gradient step; first 100 classes, batch 128, SGD 0.1.
set -euo pipefail
FOLDER=${1:-/data/food}
python -m ddp_classification_pytorch_tpu.cli.train cdr \
  --folder "$FOLDER" --batchsize 128 --model resnet50 \
  --lr 0.1 --noise_rate 0.2 --out ./runs/cdr "${@:2}"
