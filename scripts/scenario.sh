#!/usr/bin/env bash
# Continuous train→serve chaos drill — the flagship robustness scenario
# (scenario/; runbook: docs/operations.md "Scenario drill").
#
# Launches an elastic trainer pod under supervise.sh publishing verified
# checkpoints into a shared run dir, serve replicas (fleet members: shared
# leases + rolling-wave drain token) hot-reloading from it under offered
# HTTP load, injects the spec's chaos timeline (NaN burst, torn +
# corrupt-published checkpoints, host SIGKILL, watcher fs flake,
# reload-during-drain; specs may also step the offered load with
# spike_load and SIGKILL the wave's token holder), then machine-checks
# the S1–S5 invariants from the recorded events.jsonl. Exits with
# cli.scenario's code: 0 green, 1 invariant violated / process failed,
# 2 malformed spec. The fleet drill with autoscaling is chaos_drill.sh
# phase 9, which passes its own spec here.
#
#   bash scripts/scenario.sh                         # default drill
#   bash scripts/scenario.sh runs/s my_spec.json     # custom out + spec
#
# Flags used here are locked against the cli.scenario parser by
# tests/test_scripts_meta.py.
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
OUT=${1:-"$REPO/runs/scenario"}
SPEC=${2:-""}

if [ -z "$SPEC" ]; then
  SPEC="$OUT/spec.json"
  mkdir -p "$OUT"
  # the default drill: every fault family at once — torn epoch-0 ckpt,
  # NaN burst absorbed by the sentinel, host 1 SIGKILLed mid-run (elastic
  # re-form + rejoin), a corrupt PUBLISHED candidate, a watcher poll
  # flake, and a deliberate replica drain while reloads are in flight
  cat > "$SPEC" <<'JSON'
{
  "trainer": {
    "hosts": 2, "elastic": true, "min_processes": 1, "epochs": 4,
    "fault_specs": {
      "0": "ckpt_io@epoch=0,publish_corrupt@epoch=2",
      "1": "nan_loss@step=2..3,host_lost@step=10"
    }
  },
  "serve": {
    "replicas": 2, "poll_s": 1.0,
    "fault_specs": {"0": "watcher_io@poll=3"}
  },
  "load": {"rps": 4.0, "timeout_s": 20.0},
  "availability": {"floor": 0.5, "window_s": 10.0, "min_samples": 3},
  "adopt_deadline_s": 180.0,
  "deadline_s": 900.0,
  "timeline": [{"at": "publish:1", "action": "drain_replica", "replica": 1}]
}
JSON
fi

cd "$REPO"
exec env JAX_PLATFORMS=cpu python -m ddp_classification_pytorch_tpu.cli.scenario \
    --scenario_spec "$SPEC" --out "$OUT"
