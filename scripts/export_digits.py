"""Export the scikit-learn digits dataset as an image folder.

This zero-egress environment cannot download CIFAR-10/ImageNet, so the one
REAL image-classification dataset available on disk is sklearn's bundled
copy of the UCI handwritten digits (1,797 8x8 grayscale images, 10 classes).
This script writes them as a <root>/{train,val}/<class>/*.png folder — a
stratified deterministic 80/20 split, upscaled x4 to 32x32 so the CIFAR-stem
ResNets apply — giving the framework a genuine generalization task through
its real imagefolder + transform + loader path (docs/convergence.md).

Usage: python scripts/export_digits.py [--root /tmp/digits] [--scale 4]
"""

from __future__ import annotations

import argparse
import os

import numpy as np
from PIL import Image


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default="/tmp/digits")
    ap.add_argument("--scale", type=int, default=4)
    ap.add_argument("--val_frac", type=float, default=0.2)
    ap.add_argument("--noise_rate", type=float, default=0.0,
                    help="symmetric label noise on the TRAIN split only "
                         "(image written under a uniformly-wrong class dir; "
                         "val stays clean) — the CDR/PLC robust-learning "
                         "demo input (CDR/main.py:37 semantics)")
    args = ap.parse_args()

    from sklearn.datasets import load_digits

    X, y = load_digits(return_X_y=True)
    imgs = (X.reshape(-1, 8, 8) * (255.0 / 16.0)).round().astype(np.uint8)

    rng = np.random.default_rng(0)
    # separate stream for label corruption: the train/val SPLIT must be
    # identical for every noise_rate, so clean-vs-noisy comparisons share
    # one val set (noise draws must not advance the split rng)
    noise_rng = np.random.default_rng(1)
    counts = {"train": 0, "val": 0}
    for cls in range(10):
        idx = np.nonzero(y == cls)[0]
        rng.shuffle(idx)
        n_val = int(len(idx) * args.val_frac)
        for split, members in (("val", idx[:n_val]), ("train", idx[n_val:])):
            for i in members:
                label = cls
                if split == "train" and args.noise_rate > 0 and (
                        noise_rng.uniform() < args.noise_rate):
                    label = int(noise_rng.choice(
                        [c for c in range(10) if c != cls]))
                d = os.path.join(args.root, split, f"digit{label}")
                os.makedirs(d, exist_ok=True)
                im = Image.fromarray(imgs[i], "L").resize(
                    (8 * args.scale, 8 * args.scale), Image.NEAREST)
                im.convert("RGB").save(os.path.join(d, f"img{i:04d}.png"))
            counts[split] += len(members)
    print(f"wrote {counts['train']} train / {counts['val']} val images "
          f"({8 * args.scale}px) under {args.root}")


if __name__ == "__main__":
    main()
