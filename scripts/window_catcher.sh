#!/usr/bin/env bash
# Poll the axon backend through a multi-hour outage; on each answering
# probe, run the owed TPU work unattended: the tunnel-up worklist first
# (bench — fresh-window numbers are the representative ones; the owed
# list lives ONLY in scripts/tpu_up_worklist.sh), then the queued VGG
# record (scripts/vgg_record.sh — supervised, auto-resuming, so a window
# that dies mid-run continues from its checkpoint in the NEXT window
# instead of being wasted). Serializes TPU access: nothing else may
# touch the chip while this runs (docs/operations.md).
#
# rc discipline: outage-shaped failures (probe timeout/unreachable;
# worklist rc 3/4/5; a supervised run that lost its backend) are retried
# on later windows, bounded by WINDOWS_MAX; deterministic failures stop
# the catcher loudly — an unattended retry loop must not relabel a real
# bug as a transient outage. That includes the PROBE itself: a timeout or
# "backend unreachable" is an outage, but an ImportError / missing
# python / broken venv (rc 126/127 or a non-outage traceback) would
# otherwise loop every 10 min forever, so those stop loudly too.
#
# Each banked window is committed IMMEDIATELY (git add -f + commit) so an
# unattended window can't be lost to a workspace reset.
#
# Usage: nohup bash scripts/window_catcher.sh & — progress in
# runs/tpu_window_auto/catcher.log; exits 0 after the owed work lands.
set -u
cd "$(dirname "$0")/.." || exit 1
out=${CATCHER_OUT:-runs/tpu_window_auto}
mkdir -p "$out"
log="$out/catcher.log"
attempts=0

bank() {
  # commit whatever this window banked right away; runs/ is gitignored so
  # artifacts need add -f, and catcher.log is excluded (it churns every
  # poll and is not evidence). Commit ONLY the window paths — an
  # operator's pre-staged WIP must not be swept into an evidence commit.
  if ! git add -f -- "$out" ':!**/catcher.log' >> "$log" 2>&1; then
    echo "WARNING: git add failed for $out — window NOT banked; commit" \
         "the artifacts by hand before any workspace reset" >> "$log"
    return 1
  fi
  git reset -q -- "$log" >> "$log" 2>&1 || true
  if ! git diff --cached --quiet -- "$out"; then
    git commit -m "$1" -- "$out" ':!**/catcher.log' >> "$log" 2>&1 \
      && echo "banked commit: $1" >> "$log" \
      || echo "WARNING: commit failed — artifacts staged but unbanked" >> "$log"
  fi
}

while true; do
  # probe diagnostics go to the log too: a broken import / dead venv must
  # read differently from a real outage (review r3 finding) — capture the
  # chunk separately so it can be classified before appending
  chunk=$(mktemp)
  timeout 150 python - > "$chunk" 2>&1 <<'EOF'
from ddp_classification_pytorch_tpu.utils.backend_probe import require_backend
require_backend(attempts=1, probe_timeout=120)
EOF
  prc=$?
  cat "$chunk" >> "$log"
  if [ "$prc" -eq 0 ]; then
    rm -f "$chunk"
    stamp=$(date +%m%d_%H%M)
    echo "=== backend UP at $stamp ===" >> "$log"
    bash scripts/tpu_up_worklist.sh "$out/window_$stamp" >> "$log" 2>&1
    wrc=$?
    progressed=0
    if [ "$wrc" -eq 0 ]; then
      bank "Bank unattended TPU window $stamp: bench artifacts"
      # forward-progress marker: output.txt gains a line per epoch, so a
      # window that advanced the run must not count against WINDOWS_MAX
      # (a 40-epoch record may legitimately span many interrupted windows)
      marker="$out/digits_vgg19bn_native_tpu/output.txt"
      before=$(stat -c %Y "$marker" 2>/dev/null || echo 0)
      bash scripts/vgg_record.sh "$out" > "$out/vgg_train_$stamp.log" 2>&1
      vrc=$?
      after=$(stat -c %Y "$marker" 2>/dev/null || echo 0)
      [ "$after" -gt "$before" ] && progressed=1
      echo "vgg_record rc=$vrc at $(date -u +%H:%M:%S)" >> "$log"
      bank "Bank unattended TPU window $stamp: VGG record progress (rc=$vrc)"
      [ "$vrc" -eq 0 ] && exit 0
      case "$vrc" in
        # outage-shaped trainer exits only: 3 backend unreachable at
        # launch, 4 init watchdog, 7 mid-run hang, 137/143 killed
        # (docs/operations.md table) — checkpoints survive and the next
        # window's vgg_record auto-resumes from them. rc 1 is a runtime
        # exception that supervise.sh already retried MAX_RESTARTS times
        # with backoff — persistent, not outage-shaped.
        3|4|7|137|143) ;;
        *) echo "vgg_record rc=$vrc is not outage-shaped (rc 6 = dataset" \
                "export, 2 = config/usage error, 1 = runtime exception" \
                "persisting through supervised retries); stopping" >> "$log"
           exit "$vrc" ;;
      esac
    else
      case "$wrc" in
        # 3 unreachable, 4 init-watchdog lease churn, 5 mid-run hang
        # deadline, 137/143 killed — all outage-shaped
        3|4|5|137|143)
          echo "worklist rc=$wrc (backend outage/hang mid-window)" \
               >> "$log"
          bank "Bank unattended TPU window $stamp: partial (worklist rc=$wrc)" ;;
        *) echo "worklist rc=$wrc is not outage-shaped (bench bug or" \
                "config error); stopping" >> "$log"
           exit "$wrc" ;;
      esac
    fi
    if [ "$progressed" -eq 1 ]; then
      attempts=0
    else
      attempts=$((attempts + 1))
    fi
    if [ "$attempts" -ge "${WINDOWS_MAX:-8}" ]; then
      echo "giving up after $attempts half-banked windows" >> "$log"
      exit 1
    fi
    sleep "${INTER_WINDOW_S:-300}"
    continue
  fi
  # classify the DOWN probe: timeout (124 from `timeout`, or the probe's
  # own in-process TimeoutExpired → RuntimeError "backend unreachable")
  # is outage-shaped; anything else (127 missing python, 126 not
  # executable, ImportError/ModuleNotFoundError traceback) is a broken
  # harness and must stop loudly, not retry forever. Broken-harness
  # patterns take PRECEDENCE: require_backend wraps the probe
  # subprocess's stderr tail into its "backend unreachable" message, so
  # a venv whose `import jax` dies reads as BOTH — and must stop.
  if grep -qE "ImportError|ModuleNotFoundError|command not found" "$chunk"; then
    rm -f "$chunk"
    echo "probe failure contains a broken-harness signature (ImportError/" \
         "missing command); stopping — see the traceback above" >> "$log"
    exit "${prc:-1}"
  fi
  if [ "$prc" -eq 124 ] || grep -qE "backend unreachable|TimeoutExpired|ConnectionError|DEADLINE_EXCEEDED|UNAVAILABLE" "$chunk"; then
    rm -f "$chunk"
    echo "down at $(date -u +%H:%M:%S)" >> "$log"
    sleep "${DOWN_POLL_S:-600}"
  else
    rm -f "$chunk"
    echo "probe failed rc=$prc and the output is not outage-shaped" \
         "(broken venv/import?); stopping — see the traceback above" >> "$log"
    exit "$prc"
  fi
done
