#!/bin/bash
# Poll the axon backend; on the first answering probe, run the owed TPU
# work in priority order (bench FIRST — fresh-window numbers), then the
# optional VGG full run. Serializes: this is the only TPU toucher.
cd /root/repo
out=runs/tpu_window_auto
mkdir -p "$out"
while true; do
  if timeout 150 python - <<'EOF'
from ddp_classification_pytorch_tpu.utils.backend_probe import require_backend
require_backend(attempts=1, probe_timeout=120)
EOF
  then
    echo "=== backend UP at $(date -u +%H:%M:%S) ===" >> "$out/catcher.log"
    stamp=$(date +%H%M)
    python bench.py > "$out/bench_$stamp.json" 2> "$out/bench_$stamp.log"
    rc=$?
    echo "bench rc=$rc" >> "$out/catcher.log"
    if [ $rc -ne 0 ]; then sleep 300; continue; fi
    python scripts/export_digits.py --root /tmp/digits >> "$out/catcher.log" 2>&1
    python -m ddp_classification_pytorch_tpu.cli.train baseline \
      --folder /tmp/digits --transform baseline --image_size 64 --crop_size 64 \
      --model vgg19_bn --num_classes 10 --batchsize 128 \
      --lr 0.005 --weight_decay 0.0005 --warmUpIter 60 --epochs 40 \
      --lrSchedule 20 32 --out "$out/digits_vgg19bn_native_tpu" --seed 999 \
      --save_best_only --auto_resume --hang_timeout_s 1200 \
      > "$out/vgg_train.log" 2>&1
    echo "vgg rc=$? done at $(date -u +%H:%M:%S)" >> "$out/catcher.log"
    exit 0
  fi
  echo "down at $(date -u +%H:%M:%S)" >> "$out/catcher.log"
  sleep 600
done
