#!/usr/bin/env bash
# Poll the axon backend through a multi-hour outage; on each answering
# probe, run the owed TPU work unattended: the tunnel-up worklist first
# (bench — fresh-window numbers are the representative ones; the owed
# list lives ONLY in scripts/tpu_up_worklist.sh), then the queued VGG
# record (scripts/vgg_record.sh — supervised, auto-resuming, so a window
# that dies mid-run continues from its checkpoint in the NEXT window
# instead of being wasted). Serializes TPU access: nothing else may
# touch the chip while this runs (docs/operations.md).
#
# rc discipline: outage-shaped failures (probe down; worklist rc 3/5;
# a supervised run that lost its backend) are retried on later windows,
# bounded by WINDOWS_MAX; deterministic failures (any other worklist rc,
# dataset-export rc 6) stop the catcher loudly — an unattended retry
# loop must not relabel a real bug as a transient outage.
#
# Usage: nohup bash scripts/window_catcher.sh & — progress in
# runs/tpu_window_auto/catcher.log; exits 0 after the owed work lands.
set -u
cd "$(dirname "$0")/.." || exit 1
out=runs/tpu_window_auto
mkdir -p "$out"
log="$out/catcher.log"
attempts=0

while true; do
  # probe diagnostics go to the log too: a broken import / dead venv must
  # read differently from a real outage (review r3 finding)
  if timeout 150 python - >> "$log" 2>&1 <<'EOF'
from ddp_classification_pytorch_tpu.utils.backend_probe import require_backend
require_backend(attempts=1, probe_timeout=120)
EOF
  then
    stamp=$(date +%m%d_%H%M)
    echo "=== backend UP at $stamp ===" >> "$log"
    bash scripts/tpu_up_worklist.sh "$out/window_$stamp" >> "$log" 2>&1
    wrc=$?
    if [ "$wrc" -eq 0 ]; then
      # forward-progress marker: output.txt gains a line per epoch, so a
      # window that advanced the run must not count against WINDOWS_MAX
      # (a 40-epoch record may legitimately span many interrupted windows)
      marker="$out/digits_vgg19bn_native_tpu/output.txt"
      before=$(stat -c %Y "$marker" 2>/dev/null || echo 0)
      bash scripts/vgg_record.sh "$out" > "$out/vgg_train_$stamp.log" 2>&1
      vrc=$?
      after=$(stat -c %Y "$marker" 2>/dev/null || echo 0)
      [ "$after" -gt "$before" ] && attempts=0
      echo "vgg_record rc=$vrc at $(date -u +%H:%M:%S)" >> "$log"
      [ "$vrc" -eq 0 ] && exit 0
      case "$vrc" in
        # outage-shaped trainer exits only: 3 backend unreachable at
        # launch, 4 init watchdog, 7 mid-run hang, 137/143 killed
        # (docs/operations.md table) — checkpoints survive and the next
        # window's vgg_record auto-resumes from them
        3|4|7|137|143) ;;
        *) echo "vgg_record rc=$vrc is not outage-shaped (rc 6 = dataset" \
                "export, 1/2 = config/usage error); stopping" >> "$log"
           exit "$vrc" ;;
      esac
    else
      case "$wrc" in
        # 3 unreachable, 4 init-watchdog lease churn, 5 mid-run hang
        # deadline, 137/143 killed — all outage-shaped
        3|4|5|137|143)
          echo "worklist rc=$wrc (backend outage/hang mid-window)" \
               >> "$log" ;;
        *) echo "worklist rc=$wrc is not outage-shaped (bench bug or" \
                "config error); stopping" >> "$log"
           exit "$wrc" ;;
      esac
    fi
    attempts=$((attempts + 1))
    if [ "$attempts" -ge "${WINDOWS_MAX:-8}" ]; then
      echo "giving up after $attempts half-banked windows" >> "$log"
      exit 1
    fi
    sleep 300
    continue
  fi
  echo "down at $(date -u +%H:%M:%S)" >> "$log"
  sleep 600
done
