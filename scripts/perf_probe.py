"""Step-time breakdown probe for the flagship train step (VERDICT r1 #5).

The tunneled TPU plugin wedges `jax.profiler`, so this probe decomposes the
step the way a trace would, by timing nested subgraphs of the SAME jitted
computation:

  fwd        model.apply only (loss, no grad)
  fwd+bwd    value_and_grad, discard updates
  full step  value_and_grad + optimizer update (the bench's step)

and audits the compiled HLO for dtype leaks (f32 convolutions/dots that
should be bf16) plus reports XLA's per-execution FLOPs and peak HBM usage.

Usage: python scripts/perf_probe.py [--batch 256] [--image-size 224]
       [--arch resnet50] [--steps 30] [--remat] [--sweep 64,128,256,512]
"""

from __future__ import annotations

import argparse
import re
import sys
import time


def _time_compiled(compiled, args, steps: int, sync) -> float:
    out = None
    for _ in range(3):  # warmup
        out = compiled(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = compiled(*args)
    sync(out)
    return (time.perf_counter() - t0) / steps


def _time_full_step(compiled, state, images, labels, steps: int) -> float:
    """Steady-state seconds/step for the donated train step: the output state
    feeds back in, so donation is satisfied on every iteration; a metric
    device-get closes each timing window (block_until_ready does not reliably
    fence tunneled execution)."""
    out_state = state
    for _ in range(3):
        out_state, m = compiled(out_state, images, labels)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        out_state, m = compiled(out_state, images, labels)
    float(m["loss"])
    return (time.perf_counter() - t0) / steps


def _hlo_dtype_audit(compiled) -> dict:
    """Count convolution/dot ops by result dtype in the optimized HLO."""
    try:
        hlo = compiled.as_text()
    except Exception:
        return {}
    counts: dict = {}
    # optimized-HLO form: `%name = bf16[256,56,56,256]{layout} convolution(...)`
    for m in re.finditer(r"= (\w+)\[[^\]]*\](?:\{[^}]*\})? (convolution|dot)\(", hlo):
        key = f"{m.group(2)}_{m.group(1)}"
        counts[key] = counts.get(key, 0) + 1
    return counts


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arch", default="resnet50")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--sweep", default="",
                    help="comma batch list: time the FULL step at each")
    args = ap.parse_args()

    from ddp_classification_pytorch_tpu.utils.backend_probe import require_backend
    from ddp_classification_pytorch_tpu.utils.cache import enable_persistent_cache

    enable_persistent_cache()
    try:
        require_backend(attempts=2, probe_timeout=120)
    except RuntimeError as e:
        print(f"# {e}", file=sys.stderr)
        sys.exit(3)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    devices = jax.devices()
    on_accel = devices[0].platform in ("tpu", "gpu")
    if not on_accel:
        # a TPU-lease outage can answer the probe with the CPU backend; the
        # 224px/batch-256 defaults would then grind for hours — downsize to
        # a smoke-scale run instead (the numbers are only meaningful on TPU)
        print("# non-accelerator backend: downsizing to smoke scale",
              file=sys.stderr)
        args.batch, args.image_size = min(args.batch, 16), min(args.image_size, 64)
        args.steps, args.sweep = min(args.steps, 3), ""
    mesh = meshlib.make_mesh(devices=devices)

    def build(batch):
        cfg = get_preset("baseline")
        cfg.model.arch = args.arch
        cfg.model.dtype = "bfloat16" if on_accel else "float32"
        cfg.model.remat = args.remat
        cfg.data.num_classes = 1000
        cfg.data.image_size = args.image_size
        cfg.data.batch_size = batch
        with mesh:
            model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=100)
        rng = np.random.default_rng(0)
        h = cfg.data.image_size
        images = jax.device_put(
            rng.normal(size=(batch, h, h, 3)).astype(np.float32),
            meshlib.batch_sharding(mesh))
        labels = jax.device_put(
            rng.integers(0, 1000, batch).astype(np.int32),
            meshlib.batch_sharding(mesh))
        return cfg, model, tx, state, images, labels

    def sync_tree(out):
        leaf = jax.tree_util.tree_leaves(out)[0]
        np.asarray(jax.device_get(leaf.ravel()[0] if leaf.ndim else leaf))

    cfg, model, tx, state, images, labels = build(args.batch)

    def loss_only(params, batch_stats, images, labels):
        variables = {"params": params, "batch_stats": batch_stats}
        logits, _ = model.apply(variables, images, train=True,
                                mutable=["batch_stats"],
                                rngs={"dropout": jax.random.PRNGKey(0)})
        import optax
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels).mean()

    def grad_only(params, batch_stats, images, labels):
        g = jax.grad(loss_only)(params, batch_stats, images, labels)
        return jax.tree_util.tree_reduce(
            lambda a, x: a + x.astype(jnp.float32).sum(), g, 0.0)

    with mesh:
        print(f"# probe: {args.arch} batch {args.batch} {args.image_size}px "
              f"remat={args.remat} on {devices[0].device_kind} x{len(devices)}",
              file=sys.stderr)

        fwd = jax.jit(loss_only).lower(
            state.params, state.batch_stats, images, labels).compile()
        t_fwd = _time_compiled(
            fwd, (state.params, state.batch_stats, images, labels),
            args.steps, sync_tree)

        bwd = jax.jit(grad_only).lower(
            state.params, state.batch_stats, images, labels).compile()
        t_bwd = _time_compiled(
            bwd, (state.params, state.batch_stats, images, labels),
            args.steps, sync_tree)

        step = make_train_step(cfg, model, tx, mesh=mesh)
        full = step.lower(state, images, labels).compile()
        audit = _hlo_dtype_audit(full)
        try:
            mem = full.memory_analysis()
            peak = getattr(mem, "peak_memory_in_bytes", None)
            if isinstance(mem, (list, tuple)):
                peak = getattr(mem[0], "peak_memory_in_bytes", None)
        except Exception:
            peak = None
        t_full = _time_full_step(full, state, images, labels, args.steps)

    b = args.batch
    print(f"fwd_only_ms        {t_fwd * 1e3:8.2f}   ({b / t_fwd:8.0f} img/s)")
    print(f"fwd_bwd_ms         {t_bwd * 1e3:8.2f}   ({b / t_bwd:8.0f} img/s)")
    print(f"full_step_ms       {t_full * 1e3:8.2f}   ({b / t_full:8.0f} img/s)")
    print(f"optimizer_overhead {max(t_full - t_bwd, 0.0) * 1e3:8.2f} ms")
    # t_bwd times the whole value_and_grad (forward AND backward); subtract
    # the forward so the ratio is backward/forward, not (f+b)/f
    print(f"bwd_over_fwd       {max(t_bwd - t_fwd, 0.0) / t_fwd:8.2f}x")
    if peak:
        print(f"peak_hbm_bytes     {peak:>12,}  ({peak / 2**30:.2f} GiB)")
    if audit:
        print("hlo_matmul_conv_dtypes:")
        for k, v in sorted(audit.items()):
            print(f"  {k:24s} {v}")

    for bs in [int(x) for x in args.sweep.split(",") if x]:
        if bs == args.batch:  # already measured above; compiles cost minutes
            print(f"sweep batch {bs:5d}: {t_full * 1e3:8.2f} ms/step  "
                  f"{bs / t_full:8.0f} img/s")
            continue
        try:
            cfg, model, tx, state, images, labels = build(bs)
            with mesh:
                step = make_train_step(cfg, model, tx, mesh=mesh)
                compiled = step.lower(state, images, labels).compile()
                t = _time_full_step(compiled, state, images, labels, args.steps)
            print(f"sweep batch {bs:5d}: {t * 1e3:8.2f} ms/step  "
                  f"{bs / t:8.0f} img/s")
        except Exception as e:
            print(f"sweep batch {bs:5d}: FAILED {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
