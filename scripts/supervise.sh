#!/usr/bin/env bash
# Failure-detection / preemption-recovery supervisor (SURVEY §5: the reference
# has none — a crashed torch.distributed.launch rank hangs the others at the
# next collective, BASELINE/train.sh:1). This wrapper restarts the trainer
# with --auto_resume until it exits cleanly or retries are exhausted; the
# restart command is identical to the start command because auto-resume picks
# up the latest checkpoint in --out.
#
# Every non-zero exit is appended to $OUT/restarts.log (timestamp, host,
# process index, rc, backoff, attempt, action) when an --out dir is present
# in the args — the post-mortem record of what the recovery chain actually
# did. On pods every host's supervisor appends to the SAME shared log; the
# host=/proc= fields keep the interleaved lines attributable.
#
# Pod runs additionally max-write the supervisor attempt number into the
# shared $OUT/generation file before each restart: all hosts of a restart
# wave converge on the same generation (two hosts observing G both write
# G+1), and the trainer's rendezvous retry (parallel/fleet.py) logs/paces
# against it so per-host backoff drift cannot make hosts miss each other's
# rendezvous window.
#
# Elastic pods (FLEET_ELASTIC=1): the trainer caches the lease-derived
# membership in $OUT/fleet/membership (one line: gen=G world=0,1). Before
# each restart this supervisor re-reads it and re-exports
# FLEET_PROCESS_ID/FLEET_NUM_PROCESSES as this host's rank/size in the
# re-formed world — respawning into the CURRENT membership instead of the
# frozen launch env. Every restarts.log line also records gen=/world= so
# the re-formation history (2 -> 1 -> 2 after a rejoin) reads off one
# shared log.
#
# Usage: MAX_RESTARTS=5 bash scripts/supervise.sh <workload> --out runs/x [flags...]
set -u
max=${MAX_RESTARTS:-5}
n=0

# find the --out value so restart events can be logged next to the run's
# checkpoints/records; no --out, no log (nowhere durable to put it)
out=""
prev=""
for a in "$@"; do
  [ "$prev" = "--out" ] && out="$a"
  prev="$a"
done

# process identity for shared (pod) restart logs: FLEET_HOST_ID is stable
# across elastic re-formations (ranks are not), falling back to
# FLEET_PROCESS_ID; single-host runs show proc=-
host=$(hostname 2>/dev/null || echo "?")
proc=${FLEET_HOST_ID:-${FLEET_PROCESS_ID:--}}

mem_fields() { # -> "gen=G world=0,1" from $OUT/fleet/membership, "-" absent
  g="-"; w="-"
  if [ -n "$out" ] && [ -f "$out/fleet/membership" ]; then
    line=$(head -n 1 "$out/fleet/membership" 2>/dev/null || echo "")
    case "$line" in gen=*)
      g=${line#gen=}; g=${g%% *}
      w=${line##*world=}; w=${w%% *}
    ;; esac
  fi
  echo "gen=$g world=$w"
}

log_event() { # $1=rc $2=backoff $3=action
  [ -n "$out" ] || return 0
  mkdir -p "$out" 2>/dev/null || return 0
  echo "$(date -Is) host=$host proc=$proc rc=$1 backoff=${2}s attempt=$n/$max $(mem_fields) action=$3" \
    >> "$out/restarts.log"
}

reexport_membership() { # respawn into the re-formed world (elastic pods)
  [ -n "${FLEET_ELASTIC:-}" ] && [ "${FLEET_ELASTIC:-0}" != "0" ] || return 0
  [ -n "$out" ] && [ -f "$out/fleet/membership" ] || return 0
  line=$(head -n 1 "$out/fleet/membership" 2>/dev/null || echo "")
  w=${line##*world=}; w=${w%% *}
  [ -n "$w" ] && [ "$w" != "$line" ] || return 0
  me=${FLEET_HOST_ID:-${FLEET_PROCESS_ID:-}}
  [ -n "$me" ] || return 0
  rank=0; size=0; found=""
  oldIFS=$IFS; IFS=','
  for h in $w; do
    [ "$h" = "$me" ] && { found=1; rank=$size; }
    size=$((size + 1))
  done
  IFS=$oldIFS
  # only members re-export: a recovered host NOT yet in the cached world
  # keeps its launch env and rejoins when the survivors re-form around it
  if [ -n "$found" ] && [ "$size" -gt 0 ]; then
    export FLEET_PROCESS_ID="$rank" FLEET_NUM_PROCESSES="$size"
  fi
  return 0
}

bump_generation() { # max-write our attempt number into $OUT/generation
  [ -n "$out" ] || return 0
  gf="$out/generation"
  cur=$(cat "$gf" 2>/dev/null || echo 0)
  case "$cur" in (''|*[!0-9]*) cur=0;; esac
  if [ "$n" -gt "$cur" ]; then
    tmp="$gf.tmp.$$"
    echo "$n" > "$tmp" 2>/dev/null && mv "$tmp" "$gf" 2>/dev/null
  fi
  return 0
}

while true; do
  python -m ddp_classification_pytorch_tpu.cli.train "$@" --auto_resume
  rc=$?
  # a clean exit is logged too: on elastic pods the world transitions
  # (2 -> 1 -> 2) are reconstructed from restarts.log, and the final
  # converged state must appear there, not just the failures
  [ "$rc" -eq 0 ] && { log_event 0 0 exit; exit 0; }
  # rc classification lives HERE, one level below any window scheduler:
  # 2 is deterministic (config/usage — the trainer maps its own config
  # validation to SystemExit(2), same code argparse uses) — restarting
  # replays the same failure; 8 is deterministic too (the non-finite step
  # sentinel: training diverged, every restart resumes the same weights
  # into the same divergence) — a hot-loop restart would burn the whole
  # retry budget replaying it; bare 1 is an UNHANDLED runtime exception
  # (transient XlaRuntimeError via the tunnel, in-process OOM, dataloader
  # IO) — retryable, but with a backoff so a crash loop doesn't spin;
  # 3 is "backend unreachable" (trainer and bench share the code), where
  # an immediate restart just burns the probe budget — back off long
  # enough for a tunnel blip to pass; 6 is "rendezvous failed"
  # (parallel/fleet.py: jax.distributed.initialize never completed within
  # its retry budget) — outage-shaped, the peers may simply not have
  # restarted yet, so it takes the SAME long backoff as rc 3; 9 is
  # "pod-inconsistent" (the resume digest agreement failed — usually
  # shared-filesystem staleness) — retryable with the runtime backoff,
  # the next consensus pass normally agrees. Everything else (4 init
  # watchdog, 7 mid-run hang, kill signals) restarts fast and
  # auto-resumes from the newest checkpoint.
  case "$rc" in
    2)
      echo "[supervise] rc=$rc is deterministic (config/usage error);" \
           "not restarting" >&2
      log_event "$rc" 0 stop
      exit "$rc" ;;
    8)
      echo "[supervise] rc=$rc is deterministic (training diverged:" \
           "sentinel hit max_bad_steps consecutive non-finite steps);" \
           "not restarting" >&2
      log_event "$rc" 0 stop
      exit "$rc" ;;
    1) backoff=${RUNTIME_BACKOFF_S:-30} ;;
    3) backoff=${OUTAGE_BACKOFF_S:-300} ;;
    6) backoff=${OUTAGE_BACKOFF_S:-300} ;;
    9) backoff=${RUNTIME_BACKOFF_S:-30} ;;
    10) backoff=${OUTAGE_BACKOFF_S:-300} ;;
    11) backoff=${REFORM_BACKOFF_S:-2} ;;
    *) backoff=2 ;;
  esac
  # 10 is "pod-unviable" (parallel/fleet.py: the survivor set is below
  # FLEET_MIN_PROCESSES or cannot cover the mesh) — outage-shaped like
  # rc 3/6, the dead peers may come back, so the long backoff; 11 is
  # "pod-reform" (membership changed at the epoch boundary) — every host
  # exits together ON PURPOSE, so restart fast into the re-formed world.
  n=$((n + 1))
  if [ "$n" -gt "$max" ]; then
    echo "[supervise] giving up after $n failures (last rc=$rc)" >&2
    log_event "$rc" "$backoff" give-up
    exit "$rc"
  fi
  echo "[supervise] trainer exited rc=$rc; restart $n/$max (auto-resume," \
       "${backoff}s backoff)" >&2
  log_event "$rc" "$backoff" restart
  bump_generation
  reexport_membership
  sleep "$backoff"
done
