#!/usr/bin/env bash
# Failure-detection / preemption-recovery supervisor (SURVEY §5: the reference
# has none — a crashed torch.distributed.launch rank hangs the others at the
# next collective, BASELINE/train.sh:1). This wrapper restarts the trainer
# with --auto_resume until it exits cleanly or retries are exhausted; the
# restart command is identical to the start command because auto-resume picks
# up the latest checkpoint in --out.
#
# Usage: MAX_RESTARTS=5 bash scripts/supervise.sh <workload> --out runs/x [flags...]
set -u
max=${MAX_RESTARTS:-5}
n=0
while true; do
  python -m ddp_classification_pytorch_tpu.cli.train "$@" --auto_resume
  rc=$?
  [ "$rc" -eq 0 ] && exit 0
  # rc classification lives HERE, one level below any window scheduler:
  # 2 is deterministic (config/usage — the trainer maps its own config
  # validation to SystemExit(2), same code argparse uses) — restarting
  # replays the same failure; bare 1 is an UNHANDLED runtime exception
  # (transient XlaRuntimeError via the tunnel, in-process OOM, dataloader
  # IO) — retryable, but with a backoff so a crash loop doesn't spin;
  # 3 is "backend unreachable" (trainer and bench share the code), where
  # an immediate restart just burns the probe budget — back off long
  # enough for a tunnel blip to pass. Everything else (4 init watchdog,
  # 7 mid-run hang, kill signals) restarts fast and auto-resumes from
  # the newest checkpoint.
  case "$rc" in
    2)
      echo "[supervise] rc=$rc is deterministic (config/usage error);" \
           "not restarting" >&2
      exit "$rc" ;;
    1) backoff=${RUNTIME_BACKOFF_S:-30} ;;
    3) backoff=${OUTAGE_BACKOFF_S:-300} ;;
    *) backoff=2 ;;
  esac
  n=$((n + 1))
  if [ "$n" -gt "$max" ]; then
    echo "[supervise] giving up after $n failures (last rc=$rc)" >&2
    exit "$rc"
  fi
  echo "[supervise] trainer exited rc=$rc; restart $n/$max (auto-resume," \
       "${backoff}s backoff)" >&2
  sleep "$backoff"
done
