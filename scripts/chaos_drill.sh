#!/usr/bin/env bash
# End-to-end chaos drill: prove the recovery chain — supervise.sh restarts,
# --auto_resume with checksum-verified fallback, the non-finite step
# sentinel, rc classification, and (phases 3-5) the POD fault-tolerance
# layer (parallel/fleet.py) — against INJECTED faults instead of trusting
# it (docs/operations.md "Chaos drill").
#
# Phase 1 (must converge to rc 0): a NaN-loss burst (skipped by the
# sentinel), a loader IO failure (rc 1, restarted with backoff), a torn
# epoch-0 checkpoint (quarantined on resume, fallback to fresh start), and
# a mid-epoch SIGTERM (restarted fast). Host-side faults are one-shot
# across restarts (fired markers under $OUT/chaos), so the run converges.
#
# Phase 2 (must stop at rc 8): a sustained NaN from step 2 on — the
# sentinel exits 8 ("diverged") and supervise.sh must NOT restart it.
#
# Phase 3 (pod, must converge to rc 0): TWO supervised hosts (one virtual
# CPU device each, gloo standing in for DCN) and a peer_dead SIGKILL on
# host 1 mid-epoch-1 — the scenario the reference can only hang on. Both
# hosts must restart into the SAME generation, resume-consensus must
# restore the identical verified checkpoint on both (digests agree, no
# rc 9), and the run completes rc 0.
#
# Phase 4 (pod, must converge to rc 0): a corrupt LATEST checkpoint on the
# shared out dir — host 0 alone quarantines it (exactly ONE *.corrupt
# rename pod-wide) and both hosts fall back to the same older verified
# checkpoint via the consensus broadcast.
#
# Phase 5 (pod, must stop at rc 8 on BOTH hosts): a sustained NaN gated to
# host 1 only (CHAOS_HOST=1) — the sentinel's deterministic stop must
# surface as the SAME rc 8 on the peer within one epoch boundary via the
# fleet abort exchange: no indefinite hang, no spurious rc 7, no restart.
#
# Phase 6 (elastic pod, must converge to rc 0): two ELASTIC supervised
# hosts (FLEET_ELASTIC=1, each under setsid so a host loss can take its
# supervisor too) and a host_lost SIGKILL-the-group on host 1 mid-epoch-1.
# Host 0 must re-form as a 1-process pod once host 1's lease expires
# (restarts.log shows the world transition 2 -> 1), keep training from the
# last verified checkpoint, then — when host 1 is relaunched — observe its
# fresh lease at an epoch boundary, exit rc 11, and re-form back to 2
# hosts at a later generation (1 -> 2). Both hosts finish rc 0.
#
# Phase 7 (elastic pod, must stop at rc 10 on the survivor — no hang):
# same host loss, but FLEET_MIN_PROCESSES=2 makes the 1-host survivor set
# unviable: host 0 must exit the deterministic rc 10 ("pod-unviable") on
# every restart and give up within its budget instead of hanging in
# rendezvous backoff forever.
#
# Phase 8 (train→serve scenario, must converge to rc 0): the full
# continuous train→serve drill via scripts/scenario.sh — an elastic
# 2-host pod publishing into a shared run dir, 2 serve replicas under
# offered load, with a NaN burst, a torn epoch-0 checkpoint, host 1
# SIGKILLed mid-run (re-form + rejoin), a corrupt PUBLISHED candidate, a
# watcher poll flake, and a deliberate replica drain during reloads —
# then the S1–S4 invariants (verified-serve, availability floor, bounded
# adoption, analyzer gate) machine-checked from events.jsonl.
#
# Phase 9 (serve-fleet drill, must converge to rc 0): the fleet control
# plane under fire — 2 replicas sharing leases + the rolling drain token
# over the trainer's run dir, admission deadline shedding armed, with a
# torn epoch-0 publish, the drain-token HOLDER SIGKILLed mid-wave (the
# lease-TTL hand-off), and a spike_load step that must drive the
# autoscaler to scale_out within its deadline — then S1–S5 (S5: wave
# exclusivity, survivor digest convergence, spike→scale-out bound)
# machine-checked from events.jsonl.
#
# CPU-only, synthetic data, tiny model: runs anywhere in a few minutes.
# Select phases with CHAOS_PHASES (default "1 2 3 4 5 6 7 8 9"); the pod
# phases skip gracefully when the platform cannot host two CPU processes
# (a forced non-cpu JAX_PLATFORMS means only one host's worth of real
# devices is available).
# Usage: [CHAOS_PHASES="3 4 5"] bash scripts/chaos_drill.sh [out_dir]
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
OUT=${1:-"$REPO/runs/chaos_drill"}
PHASES=${CHAOS_PHASES:-"1 2 3 4 5 6 7 8 9"}
export JAX_PLATFORMS=${JAX_PLATFORMS:-cpu}

COMMON=(baseline --dataset synthetic --platform cpu --model resnet18
        --variant cifar --dtype float32 --image_size 32 --num_classes 4
        --batchsize 64 --num_workers 1 --log_every 2 --epochs 3)

# pod phases run TWO trainer processes on one machine (and restart them
# repeatedly, each restart recompiling), so they take the lightest wire
# that still trains: 16px, 64 samples, per-host batch 8 (global 16,
# 4 steps/epoch) — the mechanisms under test are control-path, not
# compute-path
POD_COMMON=(baseline --dataset synthetic --synthetic_size 64 --platform cpu
            --model resnet18 --variant cifar --dtype float32 --image_size 16
            --num_classes 4 --batchsize 8 --num_workers 1 --log_every 2
            --epochs 3)

fail() { echo "CHAOS DRILL FAIL: $*" >&2; exit 1; }
has_phase() { case " $PHASES " in *" $1 "*) return 0;; *) return 1;; esac; }

# ---------------------------------------------------------------- phase 1 --
if has_phase 1; then
P1="$OUT/converge"
rm -rf "$P1"; mkdir -p "$P1"
SPEC1="nan_loss@step=2..3,loader_io@batch=5,ckpt_io@epoch=0,sigterm@step=12"
echo "[drill] phase 1: $SPEC1"
MAX_RESTARTS=5 RUNTIME_BACKOFF_S=1 \
  bash "$REPO/scripts/supervise.sh" "${COMMON[@]}" \
    --out "$P1" --fault_spec "$SPEC1" 2>&1 | tee "$P1/drill.log"
rc=${PIPESTATUS[0]}

[ "$rc" -eq 0 ] || fail "phase 1 exited rc=$rc, want 0 (see $P1/drill.log)"
grep -q "\[sentinel\] skipped" "$P1/drill.log" \
  || fail "no sentinel skip line — the NaN burst was not absorbed"
grep -q "quarantined corrupt checkpoint" "$P1/drill.log" \
  || fail "no quarantine line — the torn checkpoint was not caught"
ls "$P1"/ckpt_e*.msgpack.corrupt >/dev/null 2>&1 \
  || fail "no *.corrupt file left behind by the quarantine"
[ -s "$P1/restarts.log" ] || fail "restarts.log missing or empty"
grep -q "action=restart" "$P1/restarts.log" \
  || fail "restarts.log has no restart events"
[ -f "$P1/ckpt_e2.msgpack" ] || fail "final epoch checkpoint missing"
echo "[drill] phase 1 OK: converged to rc 0 through" \
     "$(grep -c 'action=restart' "$P1/restarts.log") restarts"
fi

# ---------------------------------------------------------------- phase 2 --
if has_phase 2; then
P2="$OUT/diverge"
rm -rf "$P2"; mkdir -p "$P2"
SPEC2="nan_loss@step=2.."
echo "[drill] phase 2: $SPEC2 (sustained NaN, max_bad_steps=4)"
MAX_RESTARTS=5 RUNTIME_BACKOFF_S=1 \
  bash "$REPO/scripts/supervise.sh" "${COMMON[@]}" \
    --out "$P2" --fault_spec "$SPEC2" --max_bad_steps 4 \
    2>&1 | tee "$P2/drill.log"
rc=${PIPESTATUS[0]}

[ "$rc" -eq 8 ] || fail "phase 2 exited rc=$rc, want 8 (see $P2/drill.log)"
grep -q "diverged" "$P2/drill.log" || fail "no divergence diagnostic"
grep -q "action=restart" "$P2/restarts.log" 2>/dev/null \
  && fail "rc 8 was restarted — deterministic divergence must stop the chain"
grep -q "rc=8" "$P2/restarts.log" || fail "rc=8 stop not logged"
echo "[drill] phase 2 OK: sustained NaN stopped at rc 8 without a restart"
fi

# ------------------------------------------------------------- pod phases --
pod_available() {
  # the pod harness runs on virtual CPU devices; a forced non-cpu platform
  # means only one host's worth of real devices is available — skip
  [ "${JAX_PLATFORMS:-}" = "cpu" ]
}

free_port() {
  python - <<'PY'
import socket
s = socket.socket()
s.bind(("localhost", 0))
print(s.getsockname()[1])
PY
}

run_pod() { # $1=out $2=fault_spec [extra trainer flags...]; logs $out/host{0,1}.log
  local out=$1 spec=$2; shift 2
  local port i rc=0 r
  port=$(free_port)
  local pids=()
  for i in 0 1; do
    env XLA_FLAGS=--xla_force_host_platform_device_count=1 \
        FLEET_COORDINATOR="localhost:$port" \
        FLEET_NUM_PROCESSES=2 FLEET_PROCESS_ID=$i \
        FLEET_RENDEZVOUS_ATTEMPTS=8 FLEET_RENDEZVOUS_BACKOFF_S=2 \
        FLEET_RENDEZVOUS_BACKOFF_CAP_S=10 FLEET_RENDEZVOUS_TIMEOUT_S=60 \
        FLEET_RENDEZVOUS_DEADLINE_S=300 \
        CHAOS_HOST="${CHAOS_HOST:-}" \
        MAX_RESTARTS=6 RUNTIME_BACKOFF_S=1 OUTAGE_BACKOFF_S=2 \
      bash "$REPO/scripts/supervise.sh" "${POD_COMMON[@]}" \
        --multihost --hang_timeout_s 120 \
        --out "$out" --fault_spec "$spec" "$@" \
        > "$out/host$i.log" 2>&1 &
    pids[$i]=$!
  done
  for i in 0 1; do
    wait "${pids[$i]}"; r=$?
    [ "$r" -ne 0 ] && rc=$r
  done
  return "$rc"
}

last_generation() { # $1=log — generation of the last successful rendezvous
  sed -n 's/.*rendezvous ok (generation=\([0-9]*\).*/\1/p' "$1" | tail -1
}

last_consensus_sha() { # $1=log — sha prefix of the last consensus resume
  sed -n 's/.*consensus resume .*sha256=\([0-9a-f]*\).*/\1/p' "$1" | tail -1
}

# ---------------------------------------------------------------- phase 3 --
if has_phase 3; then
if ! pod_available; then
  echo "[drill] phase 3 SKIPPED: JAX_PLATFORMS=${JAX_PLATFORMS:-} — only" \
       "one host's worth of devices available (pod drill needs the CPU" \
       "virtual-device harness)"
else
P3="$OUT/pod_peer_dead"
rm -rf "$P3"; mkdir -p "$P3"
SPEC3="peer_dead@step=6"  # 4 steps/epoch: dies in epoch 1, epoch-0 ckpt exists
echo "[drill] phase 3: $SPEC3 on host 1 (CHAOS_HOST=1), two supervised hosts"
CHAOS_HOST=1 run_pod "$P3" "$SPEC3"
rc=$?
[ "$rc" -eq 0 ] || fail "phase 3 exited rc=$rc, want 0 (see $P3/host*.log)"
grep -q "chaos: host 1 dies (SIGKILL)" "$P3/host1.log" \
  || fail "peer_dead never fired on host 1"
grep -q "proc=0" "$P3/restarts.log" && grep -q "proc=1" "$P3/restarts.log" \
  || fail "restarts.log lacks per-host attribution (proc= fields)"
g0=$(last_generation "$P3/host0.log"); g1=$(last_generation "$P3/host1.log")
[ -n "$g0" ] && [ "$g0" = "$g1" ] \
  || fail "hosts restarted into different generations ('$g0' vs '$g1')"
[ "$g0" -ge 1 ] || fail "no restart generation was ever recorded"
s0=$(last_consensus_sha "$P3/host0.log"); s1=$(last_consensus_sha "$P3/host1.log")
[ -n "$s0" ] && [ "$s0" = "$s1" ] \
  || fail "consensus resume digests differ across hosts ('$s0' vs '$s1')"
grep -q "rc=9" "$P3/restarts.log" \
  && fail "pod went rc 9 (inconsistent resume) — consensus failed"
[ -f "$P3/ckpt_e2.msgpack" ] || fail "final epoch checkpoint missing"
echo "[drill] phase 3 OK: host-1 SIGKILL converged — generation $g0 on" \
     "both hosts, identical consensus digest $s0"
fi
fi

# ---------------------------------------------------------------- phase 4 --
if has_phase 4; then
if ! pod_available; then
  echo "[drill] phase 4 SKIPPED: pod drill needs the CPU virtual-device harness"
else
P4="$OUT/pod_corrupt_ckpt"
rm -rf "$P4"; mkdir -p "$P4"
echo "[drill] phase 4: clean 2-host run, then a corrupt latest checkpoint" \
     "on shared storage"
run_pod "$P4" "" --epochs 2 \
  || fail "phase 4 seed run failed (see $P4/host*.log)"
[ -f "$P4/ckpt_e1.msgpack" ] || fail "seed run left no epoch-1 checkpoint"
python - "$P4/ckpt_e1.msgpack" <<'PY'
import sys
path = sys.argv[1]
data = open(path, "rb").read()
open(path, "wb").write(data[: len(data) // 2])  # tear it; sidecar now disagrees
PY
mv "$P4/host0.log" "$P4/host0.seed.log"; mv "$P4/host1.log" "$P4/host1.seed.log"
run_pod "$P4" "" \
  || fail "phase 4 resume run failed (see $P4/host*.log)"
n_corrupt=$(ls "$P4"/ckpt_e1.msgpack.corrupt 2>/dev/null | wc -l)
[ "$n_corrupt" -eq 1 ] || fail "want exactly one quarantine rename, got $n_corrupt"
grep -q "consensus resume ckpt_e0.msgpack" "$P4/host0.log" \
  || fail "host 0 did not fall back to ckpt_e0 via consensus"
grep -q "consensus resume ckpt_e0.msgpack" "$P4/host1.log" \
  || fail "host 1 did not fall back to ckpt_e0 via consensus"
s0=$(last_consensus_sha "$P4/host0.log"); s1=$(last_consensus_sha "$P4/host1.log")
[ -n "$s0" ] && [ "$s0" = "$s1" ] \
  || fail "fallback digests differ across hosts ('$s0' vs '$s1')"
grep -q "rc=9" "$P4/restarts.log" 2>/dev/null \
  && fail "pod went rc 9 on the fallback — consensus failed"
[ -f "$P4/ckpt_e2.msgpack" ] || fail "resumed run never reached epoch 2"
echo "[drill] phase 4 OK: both hosts fell back to ckpt_e0 (digest $s0)," \
     "exactly one quarantine rename"
fi
fi

# ---------------------------------------------------------------- phase 5 --
if has_phase 5; then
if ! pod_available; then
  echo "[drill] phase 5 SKIPPED: pod drill needs the CPU virtual-device harness"
else
P5="$OUT/pod_abort"
rm -rf "$P5"; mkdir -p "$P5"
SPEC5="nan_loss@step=2.."
echo "[drill] phase 5: $SPEC5 on host 1 only (CHAOS_HOST=1) — rc 8 must" \
     "propagate to the peer within one epoch"
CHAOS_HOST=1 run_pod "$P5" "$SPEC5" --max_bad_steps 3 --epochs 2
rc=$?
[ "$rc" -eq 8 ] || fail "phase 5 exited rc=$rc, want 8 (see $P5/host*.log)"
grep -q "abort intent rc 8" "$P5/host1.log" \
  || fail "host 1 never noted the sentinel abort intent"
grep -q "pod abort rc 8 (from host 1)" "$P5/host0.log" \
  || fail "host 0 never received the propagated rc 8"
n_stops=$(grep -c "rc=8" "$P5/restarts.log")
[ "$n_stops" -eq 2 ] || fail "want both supervisors to log the rc-8 stop, got $n_stops"
grep -q "action=restart" "$P5/restarts.log" \
  && fail "rc 8 was restarted — deterministic divergence must stop the pod"
grep -q "rc=7" "$P5/restarts.log" \
  && fail "spurious rc 7 — the abort exchange should beat the heartbeat"
echo "[drill] phase 5 OK: one-host divergence stopped BOTH hosts at rc 8," \
     "no hang, no rc 7, no restart"
fi
fi

# -------------------------------------------------------- elastic phases --
# Each elastic host runs under setsid: host_lost SIGKILLs its whole process
# group, so trainer AND supervisor die together — nothing local restarts
# the host, which is exactly the scenario re-formation exists for. Short
# lease TTL + rendezvous knobs keep the drill's re-form latency in seconds.
launch_elastic_host() { # $1=out $2=host_id $3=port $4=min_procs $5=spec [extra...]
  local out=$1 hid=$2 port=$3 minp=$4 spec=$5; shift 5
  setsid env XLA_FLAGS=--xla_force_host_platform_device_count=1 \
      FLEET_ELASTIC=1 FLEET_COORDINATOR="localhost:$port" \
      FLEET_NUM_PROCESSES=2 FLEET_PROCESS_ID="$hid" FLEET_HOST_ID="$hid" \
      FLEET_MIN_PROCESSES="$minp" \
      FLEET_LEASE_TTL_S=25 FLEET_LEASE_SETTLE_S=2 \
      FLEET_RENDEZVOUS_ATTEMPTS=8 FLEET_RENDEZVOUS_BACKOFF_S=2 \
      FLEET_RENDEZVOUS_BACKOFF_CAP_S=5 FLEET_RENDEZVOUS_TIMEOUT_S=15 \
      FLEET_RENDEZVOUS_DEADLINE_S=240 \
      CHAOS_HOST="${CHAOS_HOST:-}" \
      MAX_RESTARTS="${ELASTIC_MAX_RESTARTS:-8}" RUNTIME_BACKOFF_S=1 \
      OUTAGE_BACKOFF_S="${ELASTIC_OUTAGE_BACKOFF_S:-2}" REFORM_BACKOFF_S=1 \
    bash "$REPO/scripts/supervise.sh" "${POD_COMMON[@]}" \
      --multihost --hang_timeout_s 120 \
      --out "$out" --fault_spec "$spec" "$@" \
      > "$out/host$hid.log" 2>&1 &
  launched_pid=$!  # global on purpose: $(...) would orphan the pid for wait
}

wait_for_membership() { # $1=out $2=want world $3=liveness pid $4=deadline_s
  local t=0
  while [ "$t" -lt "$4" ]; do
    grep -q "world=$2\$" "$1/fleet/membership" 2>/dev/null && return 0
    kill -0 "$3" 2>/dev/null || return 1
    sleep 2; t=$((t + 2))
  done
  return 1
}

# ---------------------------------------------------------------- phase 6 --
if has_phase 6; then
if ! pod_available; then
  echo "[drill] phase 6 SKIPPED: pod drill needs the CPU virtual-device harness"
else
P6="$OUT/pod_elastic"
rm -rf "$P6"; mkdir -p "$P6"
SPEC6="host_lost@step=6"  # 4 steps/epoch: the host vanishes in epoch 1
echo "[drill] phase 6: $SPEC6 on host 1 (CHAOS_HOST=1), elastic re-formation"
PORT6=$(free_port)
CHAOS_HOST=1 launch_elastic_host "$P6" 0 "$PORT6" 1 "$SPEC6" --epochs 4
pid0=$launched_pid
CHAOS_HOST=1 launch_elastic_host "$P6" 1 "$PORT6" 1 "$SPEC6" --epochs 4
pid1=$launched_pid
wait "$pid1"; r1=$?
[ "$r1" -eq 137 ] || fail "phase 6: host 1 group exited rc=$r1, want 137 (SIGKILL)"
grep -q "chaos: host 1 lost (SIGKILL group)" "$P6/host1.log" \
  || fail "host_lost never fired on host 1"
# survivors re-form once the dead host's lease expires (TTL 25s)
wait_for_membership "$P6" 0 "$pid0" 240 \
  || fail "host 0 never re-formed as a 1-host pod (see $P6/host0.log)"
echo "[drill] phase 6: world shrank to [0]; relaunching host 1 (rejoin)"
mv "$P6/host1.log" "$P6/host1.lost.log"
CHAOS_HOST=1 launch_elastic_host "$P6" 1 "$PORT6" 1 "$SPEC6" --epochs 4
pid1=$launched_pid
wait "$pid1"; r1=$?
wait "$pid0"; r0=$?
[ "$r0" -eq 0 ] || fail "phase 6: host 0 exited rc=$r0, want 0 (see $P6/host0.log)"
[ "$r1" -eq 0 ] || fail "phase 6: rejoined host 1 exited rc=$r1, want 0 (see $P6/host1.log)"
grep -q "re-formed pod" "$P6/host0.log" \
  || fail "host 0 never logged the re-formation"
grep -q "rc=11" "$P6/restarts.log" \
  || fail "no rc 11 (pod-reform) event — the rejoin was never observed"
grep -q "world=0 action" "$P6/restarts.log" \
  || fail "restarts.log never recorded the shrunken world (2 -> 1)"
grep -q "world=0,1 action" "$P6/restarts.log" \
  || fail "restarts.log never recorded the re-grown world (1 -> 2)"
g6=$(sed -n 's/^gen=\([0-9]*\).*/\1/p' "$P6/fleet/membership")
[ -n "$g6" ] && [ "$g6" -ge 2 ] \
  || fail "membership generation '$g6' never advanced through two re-formations"
[ -f "$P6/ckpt_e3.msgpack" ] || fail "final epoch checkpoint missing"
echo "[drill] phase 6 OK: pod re-formed 2 -> 1 -> 2 (generation $g6)," \
     "converged rc 0 on both hosts"
fi
fi

# ---------------------------------------------------------------- phase 7 --
if has_phase 7; then
if ! pod_available; then
  echo "[drill] phase 7 SKIPPED: pod drill needs the CPU virtual-device harness"
else
P7="$OUT/pod_unviable"
rm -rf "$P7"; mkdir -p "$P7"
SPEC7="host_lost@step=6"
echo "[drill] phase 7: $SPEC7 on host 1, min_processes=2 — survivor must" \
     "exit rc 10, not hang"
PORT7=$(free_port)
CHAOS_HOST=1 ELASTIC_MAX_RESTARTS=3 ELASTIC_OUTAGE_BACKOFF_S=1 \
  launch_elastic_host "$P7" 0 "$PORT7" 2 "$SPEC7" --epochs 3
pid0=$launched_pid
CHAOS_HOST=1 ELASTIC_MAX_RESTARTS=3 ELASTIC_OUTAGE_BACKOFF_S=1 \
  launch_elastic_host "$P7" 1 "$PORT7" 2 "$SPEC7" --epochs 3
pid1=$launched_pid
wait "$pid1"; r1=$?
[ "$r1" -eq 137 ] || fail "phase 7: host 1 group exited rc=$r1, want 137 (SIGKILL)"
wait "$pid0"; r0=$?
[ "$r0" -eq 10 ] || fail "phase 7: host 0 exited rc=$r0, want 10 (see $P7/host0.log)"
grep -q "pod-unviable" "$P7/host0.log" \
  || fail "host 0 never named the unviable survivor set"
grep -q "rc=10" "$P7/restarts.log" \
  || fail "restarts.log never classified the rc-10 give-up"
echo "[drill] phase 7 OK: unviable survivor set exited deterministic" \
     "rc 10 within its restart budget — no hang"
fi
fi

# ---------------------------------------------------------------- phase 8 --
if has_phase 8; then
if ! pod_available; then
  echo "[drill] phase 8 SKIPPED: the scenario drill needs the CPU" \
       "virtual-device harness"
else
P8="$OUT/scenario"
rm -rf "$P8"; mkdir -p "$P8"
echo "[drill] phase 8: continuous train→serve scenario (scripts/scenario.sh)"
bash "$REPO/scripts/scenario.sh" "$P8" 2>&1 | tee "$P8/drill.log"
rc=${PIPESTATUS[0]}
[ "$rc" -eq 0 ] || fail "phase 8 exited rc=$rc, want 0 (see $P8/drill.log)"
grep -q "GREEN: S1 verified-serve" "$P8/drill.log" \
  || fail "the invariant checker never declared the run green"
[ -s "$P8/events.jsonl" ] || fail "events.jsonl missing or empty"
grep -q '"kind": "publish_torn"' "$P8/events.jsonl" \
  || fail "no publish_torn event — the corrupt-candidate faults never fired"
grep -q '"kind": "quarantine"' "$P8/events.jsonl" \
  || fail "no quarantine event — the torn candidate was never caught"
grep -q '"kind": "watcher_error"' "$P8/events.jsonl" \
  || fail "no watcher_error event — the watcher_io flake never fired"
grep -q '"kind": "reform"' "$P8/events.jsonl" \
  || fail "no reform event — the host loss never re-formed the pod"
grep -q '"kind": "drain_begin"' "$P8/events.jsonl" \
  || fail "no drain_begin event — the reload-during-drain window never opened"
grep -q "rc=11" "$P8/restarts.log" \
  || fail "no rc 11 (pod-reform) in restarts.log — the rejoin never happened"
echo "[drill] phase 8 OK: train→serve scenario green —" \
     "$(grep -c '"kind": "request"' "$P8/events.jsonl") requests under chaos," \
     "all four invariants held"
fi
fi

# ---------------------------------------------------------------- phase 9 --
if has_phase 9; then
if ! pod_available; then
  echo "[drill] phase 9 SKIPPED: the fleet drill needs the CPU" \
       "virtual-device harness"
else
P9="$OUT/fleet_scenario"
rm -rf "$P9"; mkdir -p "$P9"
SPEC9="$P9/spec.json"
# the fleet drill: rolling waves + admission + autoscaling under fire —
# a torn epoch-0 publish, the drain-token holder SIGKILLed once a wave
# is in flight (TTL hand-off), and an offered-load spike the autoscaler
# must answer with a scale_out inside its deadline
cat > "$SPEC9" <<'JSON'
{
  "trainer": {
    "hosts": 2, "elastic": true, "min_processes": 1, "epochs": 4,
    "fault_specs": {"0": "ckpt_io@epoch=0,publish_corrupt@epoch=2"}
  },
  "serve": {
    "replicas": 2, "poll_s": 1.0, "max_replicas": 3, "fleet_ttl_s": 6.0,
    "admission_deadline_ms": 250.0, "scale_out_deadline_s": 60.0
  },
  "load": {"rps": 3.0, "timeout_s": 20.0},
  "availability": {"floor": 0.5, "window_s": 10.0, "min_samples": 3},
  "adopt_deadline_s": 180.0,
  "deadline_s": 900.0,
  "timeline": [{"at": "t:5", "action": "kill_replica_during_wave"},
               {"at": "t:25", "action": "spike_load", "rps": 10.0}]
}
JSON
echo "[drill] phase 9: serve-fleet scenario (rolling wave + admission +" \
     "autoscaler) via scripts/scenario.sh"
bash "$REPO/scripts/scenario.sh" "$P9" "$SPEC9" 2>&1 | tee "$P9/drill.log"
rc=${PIPESTATUS[0]}
[ "$rc" -eq 0 ] || fail "phase 9 exited rc=$rc, want 0 (see $P9/drill.log)"
grep -q "GREEN: S1 verified-serve" "$P9/drill.log" \
  || fail "the invariant checker never declared the fleet run green"
grep -q "S5 fleet" "$P9/drill.log" \
  || fail "the green line never named the S5 fleet invariant"
[ -s "$P9/events.jsonl" ] || fail "events.jsonl missing or empty"
grep -q '"kind": "publish_torn"' "$P9/events.jsonl" \
  || fail "no publish_torn event — the torn-publish fault never fired"
grep -q '"kind": "drain_token_acquire"' "$P9/events.jsonl" \
  || fail "no drain_token_acquire — the replicas never ran a rolling wave"
grep -q '"kind": "spike_load"' "$P9/events.jsonl" \
  || fail "no spike_load event — the offered-load step never fired"
grep -q '"kind": "scale_out"' "$P9/events.jsonl" \
  || fail "no scale_out event — the autoscaler never answered the spike"
grep -q 'kill_replica_during_wave@' "$P9/events.jsonl" \
  || fail "the mid-wave kill never fired (no armed timeline hit)"
echo "[drill] phase 9 OK: serve-fleet scenario green —" \
     "$(grep -c '"kind": "drain_token_acquire"' "$P9/events.jsonl") wave slots," \
     "$(grep -c '"kind": "scale_out"' "$P9/events.jsonl") scale-out(s)," \
     "all five invariants held"
fi
fi

echo "CHAOS DRILL PASS"
