#!/usr/bin/env bash
# End-to-end chaos drill: prove the recovery chain — supervise.sh restarts,
# --auto_resume with checksum-verified fallback, the non-finite step
# sentinel, and rc classification — against INJECTED faults instead of
# trusting it (docs/operations.md "Chaos drill").
#
# Phase 1 (must converge to rc 0): a NaN-loss burst (skipped by the
# sentinel), a loader IO failure (rc 1, restarted with backoff), a torn
# epoch-0 checkpoint (quarantined on resume, fallback to fresh start), and
# a mid-epoch SIGTERM (restarted fast). Host-side faults are one-shot
# across restarts (fired markers under $OUT/chaos), so the run converges.
#
# Phase 2 (must stop at rc 8): a sustained NaN from step 2 on — the
# sentinel exits 8 ("diverged") and supervise.sh must NOT restart it.
#
# CPU-only, synthetic data, tiny model: runs anywhere in a few minutes.
# Usage: bash scripts/chaos_drill.sh [out_dir]
set -u
REPO=$(cd "$(dirname "$0")/.." && pwd)
OUT=${1:-"$REPO/runs/chaos_drill"}
export JAX_PLATFORMS=cpu

COMMON=(baseline --dataset synthetic --platform cpu --model resnet18
        --variant cifar --dtype float32 --image_size 32 --num_classes 4
        --batchsize 64 --num_workers 1 --log_every 2 --epochs 3)

fail() { echo "CHAOS DRILL FAIL: $*" >&2; exit 1; }

# ---------------------------------------------------------------- phase 1 --
P1="$OUT/converge"
rm -rf "$P1"; mkdir -p "$P1"
SPEC1="nan_loss@step=2..3,loader_io@batch=5,ckpt_io@epoch=0,sigterm@step=12"
echo "[drill] phase 1: $SPEC1"
MAX_RESTARTS=5 RUNTIME_BACKOFF_S=1 \
  bash "$REPO/scripts/supervise.sh" "${COMMON[@]}" \
    --out "$P1" --fault_spec "$SPEC1" 2>&1 | tee "$P1/drill.log"
rc=${PIPESTATUS[0]}

[ "$rc" -eq 0 ] || fail "phase 1 exited rc=$rc, want 0 (see $P1/drill.log)"
grep -q "\[sentinel\] skipped" "$P1/drill.log" \
  || fail "no sentinel skip line — the NaN burst was not absorbed"
grep -q "quarantined corrupt checkpoint" "$P1/drill.log" \
  || fail "no quarantine line — the torn checkpoint was not caught"
ls "$P1"/ckpt_e*.msgpack.corrupt >/dev/null 2>&1 \
  || fail "no *.corrupt file left behind by the quarantine"
[ -s "$P1/restarts.log" ] || fail "restarts.log missing or empty"
grep -q "action=restart" "$P1/restarts.log" \
  || fail "restarts.log has no restart events"
[ -f "$P1/ckpt_e2.msgpack" ] || fail "final epoch checkpoint missing"
echo "[drill] phase 1 OK: converged to rc 0 through" \
     "$(grep -c 'action=restart' "$P1/restarts.log") restarts"

# ---------------------------------------------------------------- phase 2 --
P2="$OUT/diverge"
rm -rf "$P2"; mkdir -p "$P2"
SPEC2="nan_loss@step=2.."
echo "[drill] phase 2: $SPEC2 (sustained NaN, max_bad_steps=4)"
MAX_RESTARTS=5 RUNTIME_BACKOFF_S=1 \
  bash "$REPO/scripts/supervise.sh" "${COMMON[@]}" \
    --out "$P2" --fault_spec "$SPEC2" --max_bad_steps 4 \
    2>&1 | tee "$P2/drill.log"
rc=${PIPESTATUS[0]}

[ "$rc" -eq 8 ] || fail "phase 2 exited rc=$rc, want 8 (see $P2/drill.log)"
grep -q "diverged" "$P2/drill.log" || fail "no divergence diagnostic"
grep -q "action=restart" "$P2/restarts.log" 2>/dev/null \
  && fail "rc 8 was restarted — deterministic divergence must stop the chain"
grep -q "rc=8" "$P2/restarts.log" || fail "rc=8 stop not logged"
echo "[drill] phase 2 OK: sustained NaN stopped at rc 8 without a restart"

echo "CHAOS DRILL PASS"
