#!/usr/bin/env bash
# The owed VGG19-BN on-chip convergence record: a COMPLETE 40-epoch run
# superseding the hang-truncated 0.9803@29 one (docs/convergence.md —
# the epoch-21 checkpoint did not survive the workspace change, so this
# is a fresh run, not a resume). Runs under the supervise.sh recovery
# chain: a mid-run hang exits 7 via --hang_timeout_s and is restarted
# with auto-resume; checkpoints land in the outdir, so a re-invocation
# after an aborted window continues instead of starting over.
#
# Usage: bash scripts/vgg_record.sh [outdir]   (exit 6 = dataset export
# failed before any chip work; otherwise supervise.sh's exit code)
set -u
cd "$(dirname "$0")/.." || exit 1
# stable default outdir: a re-invocation after an aborted window must find
# the earlier checkpoints for auto-resume, so the default must NOT be a
# fresh per-invocation date stamp
out=${1:-runs/tpu_window_manual}
mkdir -p "$out"
python scripts/export_digits.py --root /tmp/digits || exit 6
MAX_RESTARTS=${MAX_RESTARTS:-5} bash scripts/supervise.sh baseline \
  --folder /tmp/digits --transform baseline --image_size 64 --crop_size 64 \
  --model vgg19_bn --num_classes 10 --batchsize 128 \
  --lr 0.005 --weight_decay 0.0005 --warmUpIter 60 --epochs 40 \
  --lrSchedule 20 32 --out "$out/digits_vgg19bn_native_tpu" --seed 999 \
  --save_best_only --hang_timeout_s 1200
