"""ViT perf A/B at bench shapes (VERDICT r3 #5: chase the 0.2832-MFU row).

Measures the vit_s16 train step under one-change-at-a-time variants,
with bench.py's own row machinery (same AOT compile, median-of-chunks
timing, MFU + roofline fields), so numbers are directly comparable to
the committed bench captures:

    baseline    — the bench's auto-pick configuration (dense at 196 tok)
    ln_bf16     — LayerNorms in bf16 instead of f32 (bandwidth lever)
    remat_dots  — per-block checkpoint with the checkpoint_dots policy
                  (memory lever; expected slower — measured to document)
    flash       — force the Pallas kernel below its auto-pick floor
                  (re-check of the dense-vs-flash A/B at 196 tokens)

Run in a FRESH window (contention distorts comparisons less than levels,
but clean numbers decide `ln_bf16`'s default):

    python scripts/ab_vit_perf.py [--steps 30] [--batch 0]

One JSON line per variant; paste the verdict into docs/performance.md
(the ViT section) and flip ModelConfig.ln_bf16's default only on a
measured win + a convergence re-record.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import bench  # noqa: E402  (repo root — reuse probe, rows, peak tables)


def main() -> None:
    t_start = time.monotonic()
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--batch", type=int, default=0, help="0 = 128/chip")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--variants", default="baseline,ln_bf16,remat_dots,flash")
    ap.add_argument("--deadline", type=float, default=900.0,
                    help="wall-clock budget; a mid-run backend hang exits 5 "
                         "(bench.py's deadline watchdog) instead of blocking "
                         "the unattended window chain forever")
    args = ap.parse_args()

    # same watchdog bench.main() arms: the tunneled backend can hang any
    # device sync with no exception — unattended callers
    # (tpu_up_worklist.sh → window_catcher.sh) need an exit, not a hang
    partial_box: dict = {}
    disarm = bench._arm_deadline_watchdog(args.deadline, t_start, partial_box)

    from ddp_classification_pytorch_tpu.utils.backend_probe import (
        backend_watchdog,
        require_backend,
    )
    from ddp_classification_pytorch_tpu.utils.cache import (
        enable_persistent_cache,
    )

    enable_persistent_cache()
    try:
        require_backend(attempts=2, probe_timeout=120)
    except RuntimeError as e:
        print(f"# {e}", file=sys.stderr)
        sys.exit(3)
    backend_up = backend_watchdog(600)

    import jax

    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib

    devices = jax.devices()
    backend_up()
    n_chips = len(devices)
    on_accel = devices[0].platform in ("tpu", "gpu")
    peak = (bench._peak_flops(devices[0].device_kind)
            if devices[0].platform == "tpu" else None)
    peak_bw = (bench._peak_hbm(devices[0].device_kind)
               if devices[0].platform == "tpu" else None)
    mesh = meshlib.make_mesh(devices=devices)

    probe_ms = bench._contention_probe() if on_accel else None
    print(f"# probe: {probe_ms} ms (uncontended ref "
          f"{bench.PROBE_UNCONTENDED_MS or bench.PROBE_EXPECTED_MS_FALLBACK})",
          file=sys.stderr)

    def cfg_for(variant: str):
        c = get_preset("baseline")
        c.model.arch = "vit_s16"
        c.model.dtype = "bfloat16" if on_accel else "float32"
        c.model.flash_attention = True  # bench auto-pick parity
        c.data.num_classes = 1000
        c.data.image_size = args.image_size if on_accel else 64
        c.data.batch_size = args.batch or (128 if on_accel else 8) * n_chips
        if variant == "ln_bf16":
            c.model.ln_bf16 = True
        elif variant == "remat_dots":
            c.model.remat = True
        elif variant == "flash":
            c.model.flash_min_tokens = 0  # kernel even at 196 tokens
        elif variant != "baseline":
            raise SystemExit(f"unknown variant {variant!r}")
        return c

    steps = args.steps if on_accel else 2
    warmup = args.warmup if on_accel else 1
    done_rows = []
    # same guard bench.main() applies to its extra rows: a variant only
    # STARTS while enough budget remains for its compile+measure, so the
    # deadline watchdog firing genuinely means "backend hung", never
    # "list too long on a slow-but-healthy window"
    variant_budget = 240.0
    for variant in [v for v in args.variants.split(",") if v]:
        left = (args.deadline - (time.monotonic() - t_start)
                if args.deadline else float("inf"))
        if left < variant_budget:
            print(f"# skipping variant {variant!r}: {left:.0f}s left < "
                  f"{variant_budget:.0f}s budget", file=sys.stderr)
            continue
        t0 = time.monotonic()
        row = bench._bench_row(
            cfg_for(variant), mesh, steps=steps, warmup=warmup,
            n_chips=n_chips, peak=peak, peak_bw=peak_bw,
            metric=f"vit_s16_{variant}_train_images_per_sec_per_chip")
        row["variant"] = variant
        if probe_ms is not None:
            row["probe_matmul20_ms"] = probe_ms
        print(json.dumps(row), flush=True)
        # measured variants must survive a later variant's hang (the
        # watchdog serializes this box from its own thread)
        done_rows.append(dict(row))
        partial_box["row"] = {"ab_vit_perf_rows": list(done_rows)}
        print(f"# {variant}: {row['value']} img/s/chip, "
              f"step {row['step_ms']}ms, mfu {row.get('mfu', 'n/a')}, "
              f"{time.monotonic() - t0:.0f}s", file=sys.stderr)
    disarm()


if __name__ == "__main__":
    main()
