#!/usr/bin/env bash
# Everything owed to the live chip, in priority order, for the next
# tunnel-up window. Each step is independently committed evidence; a
# window that closes mid-list still leaves the earlier artifacts on disk.
# Serialize TPU access: nothing else may hold the lease while this runs
# (docs/operations.md).
#
# 2026-08-01 window banked: bench rc=0 (flagship 2652.85 fresh / 2319.72
# cold-first-row), T=196/784 attention A/B, and native-dataplane on-chip
# convergence for RN18/RN50/TResNet-M/VGG19-BN.
# 2026-08-02 window banked: two contended bench captures (probe 141.63 →
# 95.04 ms as co-tenant load decayed — variance doc updated) and the ViT
# on-chip convergence record (0.800 best val top-1, equal to the CPU-mesh
# run); the window was followed by a 10+ h outage — check
# runs/tpu_window_auto/ for artifacts window_catcher.sh may have banked
# unattended. Still owed (in order):
#   1. a FRESH-WINDOW bench early in the window — pins
#      PROBE_UNCONTENDED_MS (bench.py) from the emitted probe.matmul20_ms
#      when step_ms lands near 48, gives the vit dense-auto row its
#      first uncontended capture, AND (new in r4) emits the measured
#      roofline fields (bytes_per_step_gb / achieved_gbps /
#      hbm_peak_frac — docs/performance.md "Roofline, measured": record
#      the verdict there either way). Run it with --e2e (new in r5): the
#      e2e row now carries h2d_bytes_per_step + input_dtype on the uint8
#      wire (docs/performance.md "Wire format: uint8 H2D") — its first
#      TPU capture is owed. Run it with --serve too (new in r6): the
#      serve_latency row (p50/p99, req/s, bucket histogram — the serving
#      engine's first on-chip capture, docs/serving.md) is owed as well
#   2. anything this file previously captured, re-run only if its code
#      path changed since the banked artifact
#
# Usage: bash scripts/tpu_up_worklist.sh [outdir]
set -u
out=${1:-runs/tpu_window_$(date +%m%d_%H%M)}
mkdir -p "$out"

echo "== 1/2 bench (run FIRST: fresh-window numbers are the real ones —" >&2
echo "   docs/performance.md 'Measurement variance')" >&2
# --e2e: also capture the uint8-wire input-path row (h2d_bytes_per_step /
# input_dtype evidence — first TPU capture owed)
# --serve: also capture the serving engine's serve_latency row (p50/p99 +
# req/s + bucket histogram — first TPU capture owed; docs/serving.md)
python bench.py --e2e --serve --trace > "$out/bench.json" 2> "$out/bench.log"
rc=$?
tail -1 "$out/bench.json"
if [ $rc -ne 0 ]; then
  case $rc in
    3) echo "bench rc=3 — backend unreachable (probe never answered), stopping" >&2 ;;
    5) echo "bench rc=5 — backend answered but the run hung past its deadline" \
            "(mid-run hang or extreme contention; see the fallback JSON)" >&2 ;;
    *) echo "bench rc=$rc — unexpected failure, stopping" >&2 ;;
  esac
  exit $rc
fi
# the serve row must prove the AOT warm path on-chip: the bench boots one
# cold engine (banks the aot/ sidecar) and one warm engine (deserializes
# it), so a healthy capture has both timings and a cache hit
grep -q '"cold_start_ms"' "$out/bench.json" \
  || echo ">> serve row missing cold_start_ms — AOT cold/warm split not captured" >&2
grep -q '"aot_cache_hit": true' "$out/bench.json" \
  || echo ">> aot_cache_hit not true — warm boot recompiled instead of deserializing" >&2

echo "== 1b/2 grad-accum comms A/B (new in r16): K∈{1,4} × wire" >&2
echo "   {f32,bf16} e2e rows — the ÷K / ÷2K amortization of" >&2
echo "   collective_bytes_per_optimizer_step on real DCN-adjacent" >&2
echo "   hardware, plus the double-buffered H2D overlap's" >&2
echo "   h2d_wait_ms_per_step delta (docs/performance.md 'Gradient" >&2
echo "   accumulation and comms amortization')" >&2
# K=1 f32 is step 1's bench.json; the three remaining corners each get a
# short --e2e-only capture (same batch, so the per-optimizer-step payload
# comparison is like-for-like; --h2d-overlap on the K=4 rows also banks
# the overlap evidence). A failed corner warns and continues — the A/B
# must not cost the queued ViT/VGG work.
for corner in "accum4_f32:--grad-accum 4 --h2d-overlap" \
              "accum1_bf16:--grad-reduce-dtype bfloat16 --zero-opt off" \
              "accum4_bf16:--grad-accum 4 --grad-reduce-dtype bfloat16 --zero-opt off --h2d-overlap"; do
  name=${corner%%:*}; flags=${corner#*:}
  # shellcheck disable=SC2086
  python bench.py --e2e --steps 20 --rows "" $flags \
      > "$out/bench_$name.json" 2> "$out/bench_$name.log"
  crc=$?
  if [ $crc -ne 0 ]; then
    case $crc in
      3|5) echo "bench_$name rc=$crc — backend outage, stopping" >&2; exit $crc ;;
      *) echo "bench_$name rc=$crc (non-outage) — continuing" >&2 ;;
    esac
  else
    tail -1 "$out/bench_$name.json"
  fi
done

echo ">> if step_ms is ~48 and probe.matmul20_ms is fresh, pin" >&2
echo ">> PROBE_UNCONTENDED_MS in bench.py to that probe value (and mirror" >&2
echo ">> the capture into docs/performance.md — tests/test_bench_meta.py" >&2
echo ">> locks the two together)" >&2

echo "== 2/2 ViT perf A/B (VERDICT r4: baseline/ln_bf16/remat_dots/flash" >&2
echo "   at bench shapes — decides ln_bf16's default and the vit row's" >&2
echo "   0.2832-MFU chase; verdict goes into docs/performance.md)" >&2
# one-shot documentation: once ANY window banked the A/B, later windows
# (the catcher retries until the VGG record completes) must not burn
# scarce chip minutes re-measuring identical variants — FORCE_AB=1 to
# re-run after a code change to the measured paths
# find, not a one-level glob: window_catcher.sh banks under
# runs/tpu_window_auto/window_<stamp>/, two levels deep (ADVICE r4)
banked_ab=$(find runs -name ab_vit_perf.jsonl -size +0c 2>/dev/null | head -1)
if [ -n "$banked_ab" ] && [ "${FORCE_AB:-0}" != "1" ]; then
  echo "   already banked: $banked_ab — skipping (FORCE_AB=1 to re-run)" >&2
  abrc=0
else
  # write to a .partial name and rename only on rc=0: a crashed or
  # window-killed A/B must never leave a file the banked check above would
  # match in later windows — only a complete run banks
  python scripts/ab_vit_perf.py > "$out/ab_vit_perf.partial.jsonl" \
                                2> "$out/ab_vit_perf.log"
  abrc=$?
  if [ $abrc -eq 0 ]; then
    mv "$out/ab_vit_perf.partial.jsonl" "$out/ab_vit_perf.jsonl"
    tail -4 "$out/ab_vit_perf.jsonl" >&2
  else
    tail -4 "$out/ab_vit_perf.partial.jsonl" >&2
    case $abrc in
      # outage-shaped (docs/operations.md: 3 unreachable, 4 init-watchdog
      # lease churn, 5 mid-run hang deadline, 137/143 killed): stop the
      # window — the VGG record would fail the same way; anything else is
      # an A/B bug — warn and continue, a broken experiment must not cost
      # the queued convergence record
      3|4|5|137|143) echo "ab_vit_perf rc=$abrc — backend outage, stopping" >&2
                     exit $abrc ;;
      *) echo "ab_vit_perf rc=$abrc (non-outage) — continuing to the" \
              "VGG record; see $out/ab_vit_perf.log" >&2 ;;
    esac
  fi
fi

echo "== (reference) dense-vs-flash A/B already banked:" >&2
echo "   runs/tpu_window_0801_0802/ab_attention.json — re-run" >&2
echo "   scripts/ab_vit_attention.py ONLY if the attention dispatch changed" >&2

# Optional: supersede the hang-truncated VGG record (0.9803 at epoch
# 29/40) with a complete 40-epoch run: `bash scripts/vgg_record.sh "$out"`
# (the single source of truth for that recipe; window_catcher.sh runs it
# automatically after a banked bench).

echo "window work complete — git add -f the $out artifacts" >&2
