#!/usr/bin/env bash
# Everything owed to the live chip, in priority order, for the next
# tunnel-up window (rounds 2-3 were fully eclipsed by outages). Each step
# is independently committed evidence; a window that closes mid-list still
# leaves the earlier artifacts on disk. Serialize TPU access: nothing else
# may hold the lease while this runs (docs/operations.md).
#
# Usage: bash scripts/tpu_up_worklist.sh [outdir]
set -u
out=${1:-runs/tpu_window_$(date +%m%d_%H%M)}
mkdir -p "$out"

echo "== 1/3 bench (the driver-comparable capture)" >&2
python bench.py > "$out/bench.json" 2> "$out/bench.log"
rc=$?
tail -1 "$out/bench.json"
if [ $rc -ne 0 ]; then
  case $rc in
    3) echo "bench rc=3 — backend unreachable (probe never answered), stopping" >&2 ;;
    5) echo "bench rc=5 — backend answered but the run hung past its deadline" \
            "(mid-run hang or extreme contention; see the fallback JSON)" >&2 ;;
    *) echo "bench rc=$rc — unexpected failure, stopping" >&2 ;;
  esac
  exit $rc
fi

echo "== 2/3 dense-vs-flash A/B at bench token counts" >&2
python scripts/ab_vit_attention.py --sizes 224,448 \
  > "$out/ab_attention.json" 2> "$out/ab_attention.log"
cat "$out/ab_attention.json"

echo "== 3/3 native-dataplane digits run on the chip (~5 min)" >&2
python scripts/export_digits.py --root /tmp/digits
python -m ddp_classification_pytorch_tpu.cli.train baseline \
  --folder /tmp/digits --transform baseline --image_size 32 --crop_size 32 \
  --variant cifar --model resnet18 --num_classes 10 --batchsize 128 \
  --lr 0.1 --weight_decay 0.0005 --warmUpIter 36 --epochs 40 \
  --lrSchedule 20 32 --out "$out/digits_rn18_native_tpu" --seed 999 \
  --save_best_only 2>&1 | tail -3
cat "$out/digits_rn18_native_tpu/meta.json" 2>/dev/null

echo "window work complete — commit $out (bench.json, ab_attention.json," >&2
echo "digits record) and fold the A/B crossover into flash_min_tokens" >&2
