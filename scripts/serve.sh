#!/usr/bin/env bash
# Serving launcher — micro-batched inference over a trained run dir with
# checkpoint hot-reload and an HTTP front-end (docs/serving.md).
#
# The watch dir is the SAME --out a trainer writes: new verified
# checkpoints hot-swap between micro-batches; corrupt candidates are
# quarantined (*.corrupt) and serving continues on the previous params.
# SIGTERM drains gracefully (intake stops, queued requests answered,
# exit 0) — safe to stop from a supervisor at any time.
#
# The predict runs dp-sharded over SERVE_DEVICES devices (0 = the whole
# host/pod), and warmed bucket executables are banked in the watch dir's
# aot/ sidecar so the next replica boots without compiling. BUCKETS
# defaults to the CLI's auto-buckets, which round themselves up to the
# mesh's dp width; an explicit BUCKETS list must be dp-divisible (rc 2).
#
# A replica launched with FLEET_DIR joins the serve-fleet control plane
# (docs/serving.md "Fleet"): it heartbeats a lease into
# $FLEET_DIR/serve_fleet on every watcher poll, gates its hot-reload
# swaps on the fleet's single drain token (rolling waves — at most one
# replica draining at a time), and reports fleet_role/wave_state on
# /healthz. ADMISSION_DEADLINE_MS > 0 turns on deadline-based load
# shedding above the batch queue; ADMISSION_TENANTS weights it.
#
# Usage: bash scripts/serve.sh <run_dir> [extra cli.serve flags...]
# Env:   PORT (default 8000), BUCKETS (default auto), MAX_BATCH (16),
#        BATCH_TIMEOUT_MS (5), TOPK (5), SERVE_DEVICES (0 = all),
#        AOT_CACHE (auto | off | dir),
#        FLEET_DIR (off; shared fleet run dir), FLEET_REPLICA (0),
#        FLEET_TTL_S (15), ADMISSION_DEADLINE_MS (0 = off),
#        ADMISSION_TENANTS ("" = single default tenant)
set -euo pipefail
RUN_DIR=${1:?usage: bash scripts/serve.sh <run_dir> [flags...]}
BUCKET_ARGS=()
if [[ -n "${BUCKETS:-}" ]]; then
  BUCKET_ARGS=(--buckets "$BUCKETS")
fi
FLEET_ARGS=()
if [[ -n "${FLEET_DIR:-}" ]]; then
  FLEET_ARGS=(--fleet_dir "$FLEET_DIR"
              --fleet_replica "${FLEET_REPLICA:-0}"
              --fleet_ttl_s "${FLEET_TTL_S:-15}")
fi
if [[ -n "${ADMISSION_DEADLINE_MS:-}" ]]; then
  FLEET_ARGS+=(--admission_deadline_ms "$ADMISSION_DEADLINE_MS")
fi
if [[ -n "${ADMISSION_TENANTS:-}" ]]; then
  FLEET_ARGS+=(--admission_tenants "$ADMISSION_TENANTS")
fi
python -m ddp_classification_pytorch_tpu.cli.serve baseline \
  --watch "$RUN_DIR" \
  --port "${PORT:-8000}" \
  --max_batch "${MAX_BATCH:-16}" \
  --batch_timeout_ms "${BATCH_TIMEOUT_MS:-5}" \
  --topk "${TOPK:-5}" \
  --serve_devices "${SERVE_DEVICES:-0}" \
  --aot_cache "${AOT_CACHE:-auto}" \
  --out "$RUN_DIR/serve" \
  "${BUCKET_ARGS[@]}" \
  ${FLEET_ARGS[@]+"${FLEET_ARGS[@]}"} \
  "${@:2}"
