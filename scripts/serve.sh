#!/usr/bin/env bash
# Serving launcher — micro-batched inference over a trained run dir with
# checkpoint hot-reload and an HTTP front-end (docs/serving.md).
#
# The watch dir is the SAME --out a trainer writes: new verified
# checkpoints hot-swap between micro-batches; corrupt candidates are
# quarantined (*.corrupt) and serving continues on the previous params.
# SIGTERM drains gracefully (intake stops, queued requests answered,
# exit 0) — safe to stop from a supervisor at any time.
#
# Usage: bash scripts/serve.sh <run_dir> [extra cli.serve flags...]
# Env:   PORT (default 8000), BUCKETS (default 1,4,16), MAX_BATCH (16),
#        BATCH_TIMEOUT_MS (5), TOPK (5)
set -euo pipefail
RUN_DIR=${1:?usage: bash scripts/serve.sh <run_dir> [flags...]}
python -m ddp_classification_pytorch_tpu.cli.serve baseline \
  --watch "$RUN_DIR" \
  --port "${PORT:-8000}" \
  --buckets "${BUCKETS:-1,4,16}" \
  --max_batch "${MAX_BATCH:-16}" \
  --batch_timeout_ms "${BATCH_TIMEOUT_MS:-5}" \
  --topk "${TOPK:-5}" \
  --out "$RUN_DIR/serve" \
  "${@:2}"
