#!/usr/bin/env bash
# BASELINE workload (reference BASELINE/train.sh:1):
#   CUDA_VISIBLE_DEVICES=0,1 python -m torch.distributed.launch --nproc_per_node=2 \
#       main.py --world_size=2 --folder=/data/food
# On TPU there is no per-device process launcher: one process per host sees all
# local chips and the batch shards over the mesh automatically. The per-GPU
# batch 16 × 2 GPUs becomes --batchsize 32 (per host).
set -euo pipefail
FOLDER=${1:-/data/food}
python -m ddp_classification_pytorch_tpu.cli.train baseline \
  --folder "$FOLDER" --batchsize 32 --model resnet50 \
  --lr 0.001 --epochs 100 --out ./runs/baseline "${@:2}"
