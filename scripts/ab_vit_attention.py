"""A/B the ViT attention paths at a given token count on the live backend.

Settles VERDICT r2 weak #3 / next-round #4 with a measurement: time the
full vit train step with (a) the Pallas flash kernel forced
(--flash_min_tokens 0) and (b) the XLA fused dense path, at the bench's
token count (224px → 196 tokens) and optionally a sweep, then print one
JSON line per point. The bench's auto-pick floor
(ModelConfig.flash_min_tokens) should sit below the measured crossover.

Usage: python scripts/ab_vit_attention.py [--sizes 224,448,736]
       [--batch 128] [--steps 30] [--arch vit_s16]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--arch", default="vit_s16")
    ap.add_argument("--sizes", default="224,448",
                    help="comma list of image sizes (tokens = (S/16)^2)")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--platform", default="", choices=["", "tpu", "cpu"],
                    help="force a JAX platform (the sitecustomize pins axon; "
                         "env vars alone do not switch — same contract as "
                         "cli/train.py)")
    args = ap.parse_args()

    from ddp_classification_pytorch_tpu.utils.backend_probe import require_backend
    from ddp_classification_pytorch_tpu.utils.cache import enable_persistent_cache

    enable_persistent_cache()
    if args.platform:
        # same contract as cli/train.py: an explicit flag pins the platform
        # regardless of env (the sitecustomize pins axon; JAX_PLATFORMS in
        # the env may pin something else)
        import jax

        jax.config.update("jax_platforms", args.platform)
    if args.platform != "cpu":
        require_backend(attempts=2, probe_timeout=120)

    import jax
    import numpy as np

    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    devices = jax.devices()
    on_accel = devices[0].platform in ("tpu", "gpu")
    if not on_accel and args.platform != "cpu":
        # a lease outage can land the probe on the CPU backend; full-size
        # vit_s16 steps would grind for hours and the numbers would not
        # answer the TPU flash-vs-dense question anyway
        raise SystemExit(
            "backend is CPU but --platform cpu was not requested — refusing "
            "to measure the TPU crossover on the host (pass --platform cpu "
            "with small --sizes/--batch for a smoke run)")
    mesh = meshlib.make_mesh(devices=devices)

    for size in [int(s) for s in args.sizes.split(",") if s]:
        tokens = (size // 16) ** 2
        for mode, floor in (("flash", 0), ("dense", 10 ** 9)):
            cfg = get_preset("baseline")
            cfg.model.arch = args.arch
            cfg.model.flash_attention = True
            cfg.model.flash_min_tokens = floor
            cfg.model.dtype = "bfloat16" if on_accel else "float32"
            cfg.data.num_classes = 1000
            cfg.data.image_size = size
            cfg.data.batch_size = args.batch * len(devices)
            with mesh:
                model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=100)
                step = make_train_step(cfg, model, tx, mesh=mesh)
                rng = np.random.default_rng(0)
                images = jax.device_put(
                    rng.normal(size=(cfg.data.batch_size, size, size, 3))
                    .astype(np.float32), meshlib.batch_sharding(mesh))
                labels = jax.device_put(
                    rng.integers(0, 1000, cfg.data.batch_size).astype(np.int32),
                    meshlib.batch_sharding(mesh))
                compiled = step.lower(state, images, labels).compile()
                for _ in range(args.warmup):
                    state, m = compiled(state, images, labels)
                if args.warmup:
                    float(m["loss"])  # hard sync (block_until_ready
                    # unreliable through the tunnel)
                t0 = time.perf_counter()
                for _ in range(args.steps):
                    state, m = compiled(state, images, labels)
                float(m["loss"])
                dt = (time.perf_counter() - t0) / args.steps
            print(json.dumps({
                "metric": f"{args.arch}_{mode}_step_ms",
                "tokens": tokens,
                "image_size": size,
                "batch_per_chip": args.batch,
                "value": round(dt * 1e3, 2),
                "images_per_sec_per_chip": round(
                    cfg.data.batch_size / dt / len(devices), 1),
            }), flush=True)


if __name__ == "__main__":
    main()
