"""Measure PLC label-correction quality against ground truth.

The digits export (`scripts/export_digits.py`) names every file by its
global scikit-learn index (`img{i:04d}.png`), so the true label of each
training image is recoverable even after noise injection wrote it under a
wrong class directory. This script compares three label sets over the SAME
dataset order the PLC trainer used (the deterministic imagefolder scan):

  folder labels   — what the noisy export claims (what training started from)
  corrected       — `<run>/plc_labels.npy` written by the PLC loop
                    (train/plc_loop.py, FolderDataset.update_corrupted_label
                    semantics — PLC/FolderDataset.py:80-82)
  truth           — sklearn digits labels via the filename index

and reports the noise rate before/after correction plus the fix/break
counts — the quantified version of the reference's label-correction claim
(PLC/utils.py:291-360).

Usage: python scripts/plc_recovery.py --root /tmp/digits_noisy --run runs/digits_plc
"""

from __future__ import annotations

import argparse
import os
import re
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ddp_classification_pytorch_tpu.data.imagefolder import scan_image_folder


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", required=True, help="noisy export root (train/ under it)")
    ap.add_argument("--run", required=True, help="PLC run dir containing plc_labels.npy")
    args = ap.parse_args()

    from sklearn.datasets import load_digits

    _, y = load_digits(return_X_y=True)

    paths, folder_labels, _names = scan_image_folder(
        os.path.join(args.root, "train"), imgs_per_class=0, max_classes=0)
    folder_labels = np.asarray(folder_labels)
    matches = [re.search(r"img(\d+)\.png$", p) for p in paths]
    bad = [p for p, m in zip(paths, matches) if m is None]
    if bad:
        raise SystemExit(
            f"{len(bad)} files do not look like an export_digits.py export "
            f"(first: {bad[0]}) — truth labels are only recoverable from "
            "img{i}.png filenames")
    truth = np.array([y[int(m.group(1))] for m in matches])

    corrected = np.load(os.path.join(args.run, "plc_labels.npy"))
    if corrected.shape != folder_labels.shape:
        raise SystemExit(
            f"label count mismatch: scan {folder_labels.shape} vs "
            f"corrected {corrected.shape} — was the run trained on --root?")

    n = len(truth)
    noisy_before = folder_labels != truth
    noisy_after = corrected != truth
    changed = corrected != folder_labels
    fixed = changed & noisy_before & ~noisy_after
    broken = changed & ~noisy_before & noisy_after

    print(f"samples                {n}")
    print(f"noise before           {noisy_before.sum()}  ({noisy_before.mean():.1%})")
    print(f"noise after            {noisy_after.sum()}  ({noisy_after.mean():.1%})")
    print(f"labels changed         {changed.sum()}")
    print(f"  correctly fixed      {fixed.sum()}")
    print(f"  newly broken         {broken.sum()}")
    print(f"  wrong->other-wrong   {(changed & noisy_before & noisy_after).sum()}")


if __name__ == "__main__":
    main()
