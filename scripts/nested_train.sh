#!/usr/bin/env bash
# NESTED workload (reference NESTED/train.sh:1-7): nested-dropout ordered
# features, 10k-iter warmup, freeze-BN, pretrained backbone, all-K eval.
set -euo pipefail
FOLDER=${1:-/data/clothing1m}
python -m ddp_classification_pytorch_tpu.cli.train nested \
  --folder "$FOLDER" --batchsize 128 --model resnet50 \
  --nested 100 --warmUpIter 10000 --freeze-bn --lr 0.01 \
  --lrSchedule 20 30 40 120 --out ./runs/nested "${@:2}"
