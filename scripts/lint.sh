#!/usr/bin/env bash
# Program-invariant analyzer over the repo itself — the CI gate.
#
# Runs every pass of cli.analyze (jaxpr/HLO donation audit, host-sync /
# jit-registration / rc-catalogue lint, sharding/comms audit of the
# program × composed-mesh matrix, dtype numerics contracts D1-D6) on CPU
# and diffs the sharded + dtype records against the committed
# analysis/baselines.json, exiting with its code: 0 clean, 1 findings
# (each printed as `[check] where: message`; runbook docs/analysis.md),
# 2 usage error. The analyzer self-forces a multi-device CPU topology, so
# this runs identically on any host. Extra flags pass through, e.g.:
#
#   bash scripts/lint.sh                      # all passes + baseline diff
#   bash scripts/lint.sh --passes lint        # AST passes only (fast)
#   bash scripts/lint.sh --json /tmp/a.json   # machine copy of findings
#
# After an INTENTIONAL program change (new sharding rule, optimizer, step
# structure), regenerate the fence and commit the diff:
#
#   python -m ddp_classification_pytorch_tpu.cli.analyze --update-baseline
#
# Flags used here are locked against the cli.analyze parser by
# tests/test_scripts_meta.py.
set -euo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS=cpu exec python -m ddp_classification_pytorch_tpu.cli.analyze \
    --passes jaxpr,lint,sharding,dtype --diff-baseline "$@"
