#!/usr/bin/env bash
# ARCFACE workload (reference ARCFACE/arc_train.sh:1, HPC variant batch 64:
# arc_train_hpc.sh:1-3): ResNet-50 → 256-d embedding → ArcMarginProduct
# (s=30, m=0.5, easy_margin), Adam.
set -euo pipefail
FOLDER=${1:-/data/food}
python -m ddp_classification_pytorch_tpu.cli.train arcface \
  --folder "$FOLDER" --batchsize 64 --model resnet50 --optimizer adam \
  --lr 0.001 --epochs 100 --out ./runs/arcface "${@:2}"
