"""Ops-script consistency guards.

The round-3 review caught `scripts/tpu_up_worklist.sh` drifting from the
work it described (a banked run still listed as owed). Scripts are not
exercised by the unit suite, so give them the cheap static guards: every
shell script must parse, and every repo path a script references must
exist — a renamed helper or run directory breaks the referencing script
at the worst time (inside a scarce tunnel-up window).
"""

import os
import re
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")


def _shell_scripts():
    return sorted(
        os.path.join(SCRIPTS, f) for f in os.listdir(SCRIPTS)
        if f.endswith(".sh")
    )


def test_shell_scripts_parse():
    assert _shell_scripts(), "scripts/*.sh disappeared"
    for path in _shell_scripts():
        p = subprocess.run(["bash", "-n", path], capture_output=True)
        assert p.returncode == 0, (path, p.stderr.decode())


def test_script_repo_references_exist():
    """Repo-relative paths named in shell scripts must exist: `python
    scripts/foo.py`, `python -m package.module`, and committed-evidence
    pointers into `runs/tpu_window_<digits>/`. The digit-stamp convention
    is load-bearing: committed capture windows are date-stamped
    (`tpu_window_0801_0802`), while script OUTPUT dirs are either
    non-digit (`tpu_window_auto`) or built from a `$(date ...)` expansion
    — neither matches the literal-digits regex, so outputs a script
    creates are structurally exempt rather than exempted by accident."""
    missing = []
    for path in _shell_scripts():
        with open(path) as f:
            # comment lines may cite reference-world commands
            # (torch.distributed.launch) that rightly don't exist here
            text = "\n".join(
                ln for ln in f.read().splitlines()
                if not ln.lstrip().startswith("#")
            )
        for m in re.finditer(r"\bscripts/[\w./-]+\.(?:py|sh)\b", text):
            if not os.path.exists(os.path.join(REPO, m.group(0))):
                missing.append((os.path.basename(path), m.group(0)))
        for m in re.finditer(r"\bpython -m ([\w.]+)\b", text):
            mod = m.group(1).replace(".", "/")
            if not (os.path.exists(os.path.join(REPO, mod + ".py"))
                    or os.path.isdir(os.path.join(REPO, mod))):
                missing.append((os.path.basename(path), m.group(1)))
        # committed evidence dirs referenced as prior-capture pointers
        for m in re.finditer(r"\bruns/tpu_window_\d{4}(?:_\d{4})?/", text):
            if not os.path.isdir(os.path.join(REPO, m.group(0))):
                missing.append((os.path.basename(path), m.group(0)))
    assert not missing, missing


def _script_body(name):
    with open(os.path.join(SCRIPTS, name)) as f:
        return "\n".join(ln for ln in f.read().splitlines()
                         if not ln.lstrip().startswith("#"))


def test_serve_script_flags_match_cli():
    """scripts/serve.sh must stay in sync with cli.serve: every --flag the
    launcher passes has to exist in the CLI parser, or the launcher breaks
    exactly when someone reaches for it (the drift failure mode this file
    exists to guard)."""
    from ddp_classification_pytorch_tpu.cli.serve import build_parser

    known = set()
    for action in build_parser()._actions:
        known.update(action.option_strings)
    body = _script_body("serve.sh")
    assert "ddp_classification_pytorch_tpu.cli.serve" in body
    passed = set(re.findall(r"(?<![\w-])--[a-z_]+", body))
    assert passed, "serve.sh passes no flags — launcher gutted?"
    unknown = sorted(passed - known)
    assert not unknown, f"serve.sh passes flags cli.serve rejects: {unknown}"


def test_chaos_drill_flags_match_train_cli():
    """chaos_drill.sh phases drive cli.train through supervise.sh: every
    --flag it passes must exist in the train parser, and the pod phases'
    load-bearing pieces (--multihost, peer_dead, CHAOS_HOST aiming, the
    FLEET_ rendezvous knobs) must stay present — a silently dropped flag
    would skip the pod drill without anyone noticing."""
    from ddp_classification_pytorch_tpu.cli.scenario import (
        build_parser as scenario_parser,
    )
    from ddp_classification_pytorch_tpu.cli.train import build_parser

    known = set()
    for action in build_parser()._actions:
        known.update(action.option_strings)
    # phase 8 delegates to scripts/scenario.sh → cli.scenario; its flags
    # are legal in the drill body too
    for action in scenario_parser()._actions:
        known.update(action.option_strings)
    body = _script_body("chaos_drill.sh")
    # XLA_FLAGS=--xla_... is an env assignment, not a CLI flag
    cli_body = re.sub(r"XLA_FLAGS=\S+", "", body)
    passed = set(re.findall(r"(?<![\w-])--[a-z_]+", cli_body))
    unknown = sorted(passed - known)
    assert not unknown, f"chaos_drill.sh passes flags cli.train rejects: {unknown}"
    for needle in ("--multihost", "peer_dead@step=", "CHAOS_HOST=1",
                   "FLEET_COORDINATOR=", "FLEET_PROCESS_ID=",
                   "--hang_timeout_s", "nan_loss@step=",
                   "ckpt_e1.msgpack.corrupt",
                   # the elastic phases' load-bearing pieces
                   "host_lost@step=", "FLEET_ELASTIC=",
                   "FLEET_MIN_PROCESSES=", "FLEET_HOST_ID=",
                   # phase 8: the train→serve scenario and the evidence it
                   # must find in the recorded event log
                   "scripts/scenario.sh", "GREEN: S1 verified-serve",
                   '"kind": "publish_torn"', '"kind": "watcher_error"',
                   '"kind": "reform"', '"kind": "drain_begin"', "rc=11"):
        assert needle in body, f"chaos_drill.sh lost its {needle!r} phase piece"


def test_scenario_script_flags_match_cli():
    """scripts/scenario.sh must stay in sync with cli.scenario: every
    --flag it passes has to exist in the scenario parser, and its default
    spec must keep staging every fault family the drill exists to prove
    (a silently dropped fault would hollow out phase 8)."""
    from ddp_classification_pytorch_tpu.cli.scenario import build_parser

    known = set()
    for action in build_parser()._actions:
        known.update(action.option_strings)
    body = _script_body("scenario.sh")
    assert "ddp_classification_pytorch_tpu.cli.scenario" in body
    passed = set(re.findall(r"(?<![\w-])--[a-z_]+", body))
    assert passed, "scenario.sh passes no flags — launcher gutted?"
    unknown = sorted(passed - known)
    assert not unknown, \
        f"scenario.sh passes flags cli.scenario rejects: {unknown}"
    for needle in ("ckpt_io@epoch=", "publish_corrupt@epoch=",
                   "nan_loss@step=", "host_lost@step=", "watcher_io@poll=",
                   "drain_replica", "JAX_PLATFORMS=cpu"):
        assert needle in body, \
            f"scenario.sh default spec lost its {needle!r} fault piece"


def test_fuzz_script_flags_match_cli():
    """scripts/fuzz.sh must stay in sync with cli.fuzz: every --flag it
    passes has to exist in the fuzz parser, and it must keep the seeded
    knobs (seed/budget/runner) wired through the environment — a dropped
    knob would quietly make nightly fuzz runs unreproducible."""
    from ddp_classification_pytorch_tpu.cli.fuzz import build_parser

    known = set()
    for action in build_parser()._actions:
        known.update(action.option_strings)
    body = _script_body("fuzz.sh")
    assert "ddp_classification_pytorch_tpu.cli.fuzz" in body
    passed = set(re.findall(r"(?<![\w-])--[a-z_]+", body))
    assert passed, "fuzz.sh passes no flags — launcher gutted?"
    unknown = sorted(passed - known)
    assert not unknown, f"fuzz.sh passes flags cli.fuzz rejects: {unknown}"
    for needle in ("FUZZ_SEED", "FUZZ_BUDGET", "FUZZ_RUNNER",
                   "JAX_PLATFORMS=cpu"):
        assert needle in body, f"fuzz.sh lost its {needle!r} knob"


def test_lint_script_flags_match_analyze_cli():
    """scripts/lint.sh is the CI gate for cli.analyze: every --flag it
    passes must exist in the analyze parser, and it must actually run the
    analyzer (the drift failure mode this file exists to guard)."""
    from ddp_classification_pytorch_tpu.cli.analyze import build_parser

    known = set()
    for action in build_parser()._actions:
        known.update(action.option_strings)
    body = _script_body("lint.sh")
    assert "ddp_classification_pytorch_tpu.cli.analyze" in body
    # hyphen-aware: `--diff-baseline` must match whole, not truncate to
    # `--diff` (which the parser would reject)
    passed = set(re.findall(r"(?<![\w-])--[a-z_]+(?:-[a-z_]+)*", body))
    assert passed, "lint.sh passes no flags — gate gutted?"
    unknown = sorted(passed - known)
    assert not unknown, f"lint.sh passes flags cli.analyze rejects: {unknown}"
    # the gate must run ALL pass families, on CPU, and diff the committed
    # program baseline (the sharding/comms regression fence)
    assert "jaxpr" in body and "lint" in body and "sharding" in body
    assert "dtype" in body, "lint.sh stopped running the dtype numerics pass"
    assert "--diff-baseline" in body
    assert "JAX_PLATFORMS=cpu" in body


def test_zero_opt_knobs_locked_in_both_entrypoints():
    """The ZeRO-1 / wire-dtype knobs must stay addressable from both
    entrypoints with matching value sets: cli.train (underscore spelling,
    feeds cfg.parallel) and bench.py (dashed spelling, feeds the e2e
    row's collective/HBM evidence). The A/B workflow documented in
    docs/performance.md dies silently if either side drops or renames a
    knob — the drift failure mode this file exists to guard."""
    from ddp_classification_pytorch_tpu.cli.train import build_parser

    actions = {}
    for action in build_parser()._actions:
        for s in action.option_strings:
            actions[s] = action
    assert "--zero_opt" in actions, "cli.train lost --zero_opt"
    assert set(actions["--zero_opt"].choices) == {"", "auto", "on", "off"}
    assert "--grad_reduce_dtype" in actions, \
        "cli.train lost --grad_reduce_dtype"
    assert set(actions["--grad_reduce_dtype"].choices) == \
        {"", "float32", "bfloat16"}
    # bench.py is a script, not an importable module (import runs backend
    # probes) — lock the dashed spellings and their value sets textually
    with open(os.path.join(REPO, "bench.py")) as f:
        src = f.read()
    assert '"--zero-opt"' in src, "bench.py lost --zero-opt"
    assert '"auto", "on", "off"' in src
    assert '"--grad-reduce-dtype"' in src, "bench.py lost --grad-reduce-dtype"
    assert '"float32", "bfloat16"' in src


def test_grad_accum_h2d_knobs_locked_in_both_entrypoints():
    """The grad-accum / H2D-overlap knobs must stay addressable from both
    entrypoints: cli.train (underscore `--grad_accum`, dashed
    `--h2d-overlap`; feed cfg.parallel/cfg.data) and bench.py (dashed
    spellings; feed the e2e row's grad_accum /
    collective_bytes_per_optimizer_step / h2d_overlap evidence). Same
    drift guard as the ZeRO knobs above."""
    from ddp_classification_pytorch_tpu.cli.train import build_parser

    known = set()
    actions = {}
    for action in build_parser()._actions:
        known.update(action.option_strings)
        for s in action.option_strings:
            actions[s] = action
    assert "--grad_accum" in known, "cli.train lost --grad_accum"
    assert actions["--grad_accum"].type is int
    assert "--h2d-overlap" in known, "cli.train lost --h2d-overlap"
    with open(os.path.join(REPO, "bench.py")) as f:
        src = f.read()
    assert '"--grad-accum"' in src, "bench.py lost --grad-accum"
    assert '"--h2d-overlap"' in src, "bench.py lost --h2d-overlap"


def test_worklist_captures_grad_accum_comms_ab():
    """The owed-work list must keep the K∈{1,4} × wire {f32,bf16} comms
    A/B corners (plus the overlap evidence riding the K=4 rows) — a
    silently dropped corner un-proves the ÷K/÷2K amortization claim on
    the next window."""
    body = _script_body("tpu_up_worklist.sh")
    for needle in ("--grad-accum 4", "--grad-reduce-dtype bfloat16",
                   "--h2d-overlap", "accum4_bf16:", "accum1_bf16:",
                   "accum4_f32:"):
        assert needle in body, f"worklist lost its {needle!r} A/B piece"


def test_worklist_bench_step_captures_serve_row():
    """The owed-work list must keep running bench with ALL evidence rows:
    --e2e (uint8 wire), --serve (serve_latency) and --trace (the on-device
    step_breakdown_ms capture) — a silently dropped flag would skip the
    owed TPU capture without anyone noticing."""
    body = _script_body("tpu_up_worklist.sh")
    bench_lines = [ln for ln in body.splitlines() if "bench.py" in ln]
    assert bench_lines, "worklist no longer runs bench.py"
    assert any("--e2e" in ln and "--serve" in ln and "--trace" in ln
               for ln in bench_lines), bench_lines


def test_serve_dp_aot_knobs_locked():
    """The dp-serving / AOT-sidecar knobs must stay addressable in both
    spellings on cli.serve (scripts use underscores, operators type
    hyphens), and the worklist's bench step must keep verifying the warm
    path it exists to capture (cold_start_ms banked, aot_cache_hit true)
    — a dropped knob or needle would silently un-prove the instant
    cold-start story on the next window."""
    from ddp_classification_pytorch_tpu.cli.serve import build_parser

    known = set()
    for action in build_parser()._actions:
        known.update(action.option_strings)
    for flag in ("--serve_devices", "--serve-devices",
                 "--aot_cache", "--aot-cache"):
        assert flag in known, f"cli.serve lost {flag}"
    body = _script_body("tpu_up_worklist.sh")
    for needle in ("cold_start_ms", "aot_cache_hit"):
        assert needle in body, \
            f"worklist lost its {needle!r} warm-path verification"


def test_serve_fleet_admission_knobs_locked():
    """The serve-fleet control-plane knobs must stay addressable in both
    spellings on cli.serve (scripts use underscores, operators type
    hyphens), scripts/serve.sh must keep its env→flag plumbing for them,
    and chaos_drill.sh phase 9 must keep asserting the fleet evidence it
    exists to prove (drain token, load spike, autoscale answer, the S5
    verdict line) — drop any of these and the rolling-wave/SLO story
    silently stops being exercised."""
    from ddp_classification_pytorch_tpu.cli.serve import build_parser

    known = set()
    for action in build_parser()._actions:
        known.update(action.option_strings)
    for flag in ("--fleet_dir", "--fleet-dir",
                 "--fleet_replica", "--fleet-replica",
                 "--fleet_ttl_s", "--fleet-ttl-s",
                 "--admission_deadline_ms", "--admission-deadline-ms",
                 "--admission_tenants", "--admission-tenants"):
        assert flag in known, f"cli.serve lost {flag}"
    body = _script_body("serve.sh")
    for knob in ("FLEET_DIR", "FLEET_REPLICA", "FLEET_TTL_S",
                 "ADMISSION_DEADLINE_MS", "ADMISSION_TENANTS"):
        assert knob in body, f"serve.sh lost its {knob} env knob"
    drill = _script_body("chaos_drill.sh")
    for needle in ('"kind": "drain_token_acquire"', '"kind": "spike_load"',
                   '"kind": "scale_out"', "kill_replica_during_wave",
                   "S5 fleet", "max_replicas", "fleet_ttl_s",
                   "admission_deadline_ms", "scale_out_deadline_s"):
        assert needle in drill, \
            f"chaos_drill.sh lost its {needle!r} fleet-drill piece"
