"""Dependency-free TensorBoard writer: round-trip + framing integrity +
(when torch's tensorboard reader is importable) cross-validation against a
real third-party parser."""

import struct

import pytest

from ddp_classification_pytorch_tpu.utils.tensorboard import (
    SummaryWriter,
    _crc32c,
    read_scalars,
)


def test_crc32c_known_vectors():
    # RFC 3720 / kernel test vectors
    assert _crc32c(b"") == 0
    assert _crc32c(b"123456789") == 0xE3069283
    assert _crc32c(b"\x00" * 32) == 0x8A9136AA


def test_scalar_round_trip(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.add_scalar("train/loss", 1.5, 0)
    w.add_scalar("train/loss", 0.75, 1)
    w.add_scalar("val/top1", 0.9, 1)
    w.close()
    got = list(read_scalars(w.path))
    assert got == [
        (0, "train/loss", 1.5),
        (1, "train/loss", 0.75),
        (1, "val/top1", pytest.approx(0.9)),
    ]


def test_corruption_detected(tmp_path):
    w = SummaryWriter(str(tmp_path))
    w.add_scalar("x", 1.0, 0)
    w.close()
    data = bytearray(open(w.path, "rb").read())
    data[-6] ^= 0xFF  # flip a payload byte of the last record
    p = tmp_path / "corrupt"
    p.write_bytes(bytes(data))
    with pytest.raises(ValueError, match="corrupt"):
        list(read_scalars(str(p)))


def test_record_framing_layout(tmp_path):
    """First record is the brain.Event:2 version header in TFRecord framing."""
    w = SummaryWriter(str(tmp_path))
    w.close()
    data = open(w.path, "rb").read()
    (length,) = struct.unpack("<Q", data[:8])
    payload = data[12:12 + length]
    assert b"brain.Event:2" in payload
    assert len(data) == 16 + length  # header(8) + crc(4) + payload + crc(4)


def test_third_party_reader_cross_validation(tmp_path):
    """If a real TensorBoard reader is installed, it must parse our files."""
    try:
        from tensorboard.backend.event_processing.event_file_loader import (
            EventFileLoader,
        )
    except ImportError:
        pytest.skip("tensorboard not installed")
    w = SummaryWriter(str(tmp_path))
    w.add_scalar("loss", 2.5, 3)
    w.close()
    events = list(EventFileLoader(w.path).Load())
    scalars = [
        # the loader's data_compat pass migrates simple_value → rank-0 tensor
        (e.step, v.tag,
         v.tensor.float_val[0] if v.HasField("tensor") else v.simple_value)
        for e in events if e.HasField("summary")
        for v in e.summary.value
    ]
    assert scalars == [(3, "loss", 2.5)]


def test_trainer_writes_tb_events(tmp_path):
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.train.loop import Trainer

    cfg = get_preset("baseline")
    cfg.data.dataset = "synthetic"
    cfg.data.image_size = 32
    cfg.data.num_classes = 4
    cfg.data.synthetic_size = 32
    cfg.data.batch_size = 32
    cfg.data.num_workers = 1
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.run.epochs = 1
    cfg.run.out_dir = str(tmp_path)
    cfg.run.write_records = False
    cfg.run.save_every_epoch = False
    cfg.run.tensorboard = True
    Trainer(cfg).run()
    tb_files = list((tmp_path / "tb").iterdir())
    assert len(tb_files) == 1
    tags = {t for _, t, _ in read_scalars(str(tb_files[0]))}
    assert {"train/loss", "train/top1", "val/val_top1"} <= tags


def test_negative_step_round_trip(tmp_path):
    """int64 two's-complement varint: negative steps must not hang or corrupt."""
    w = SummaryWriter(str(tmp_path))
    w.add_scalar("x", 1.0, -3)
    w.close()
    assert list(read_scalars(w.path)) == [(-3, "x", 1.0)]
