"""dp×tp×pp composition: three parallelism axes in ONE train step.

VERDICT r3 #4: every prior multi-axis proof was 2-axis (data × model, one
role per config). This exercises the pentad actually COMPOSING: a ViT
block stack stage-sharded over a dedicated 'pipe' mesh axis
(ops/pipeline.py GPipe ring), an ArcFace margin head class-sharded over
'model' (partial-FC online-softmax CE, ops/sharded_head.py), and the
batch over 'data' — mesh (data=2, model=2, pipe=2) on the 8-device
virtual CPU mesh.

Correctness oracle: the SAME model (same init rng → identical parameter
values) on a 1-axis data=8 mesh, where the pipeline degenerates to a
sequential scan and the dense margin-CE path runs. The 3-axis losses must
match the 1-axis losses step for step — partitioning may only change
float reduction order.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
from ddp_classification_pytorch_tpu.train.state import create_train_state
from ddp_classification_pytorch_tpu.train.steps import make_train_step

BATCH, CLASSES, SIZE, STEPS = 16, 64, 32, 3


def _cfg(mp: int, pp: int):
    cfg = get_preset("arcface")
    cfg.data.image_size = SIZE
    cfg.data.num_classes = CLASSES
    cfg.data.batch_size = BATCH
    cfg.model.arch = "vit_t16"
    cfg.model.dtype = "float32"
    cfg.model.dropout = 0.0
    cfg.parallel.model_axis = mp
    cfg.parallel.pipeline_stages = pp
    cfg.parallel.pipeline_microbatches = 2
    cfg.parallel.arcface_sharded_ce = mp > 1
    return cfg


def _losses(mesh, mp, pp):
    cfg = _cfg(mp, pp)
    batches = [
        (np.random.default_rng(10 + i).normal(
            size=(BATCH, SIZE, SIZE, 3)).astype(np.float32),
         np.random.default_rng(20 + i).integers(0, CLASSES, BATCH).astype(np.int32))
        for i in range(STEPS)
    ]
    with mesh:
        model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=STEPS)
        step = make_train_step(cfg, model, tx, mesh=mesh)
        losses = []
        for images, labels in batches:
            images = jax.device_put(images, meshlib.batch_sharding(mesh))
            labels = jax.device_put(labels, meshlib.batch_sharding(mesh))
            state, metrics = step(state, images, labels)
            losses.append(float(metrics["loss"]))
    return losses, state


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_dp_tp_pp_composes_and_matches_single_axis():
    mesh3 = meshlib.make_mesh(meshlib.MeshSpec(2, 2, 2), jax.devices()[:8])
    assert dict(mesh3.shape) == {"data": 2, "model": 2, "pipe": 2}
    losses3, state3 = _losses(mesh3, mp=2, pp=2)
    assert all(np.isfinite(losses3)), losses3

    # the three axes actually hold their assigned roles
    blocks_leaf = jax.tree_util.tree_leaves(
        state3.params["backbone"]["blocks"])[0]
    assert blocks_leaf.sharding.spec[0] == meshlib.PIPE_AXIS, (
        blocks_leaf.sharding)
    w = state3.params["margin"]["weight"]
    assert w.sharding.spec[0] == meshlib.MODEL_AXIS, w.sharding

    # oracle: same params (same seed), 1-axis mesh, dense margin CE,
    # degenerate pipeline (sequential scan)
    mesh1 = meshlib.make_mesh(meshlib.MeshSpec(8, 1, 1), jax.devices()[:8])
    losses1, _ = _losses(mesh1, mp=1, pp=1)
    np.testing.assert_allclose(losses3, losses1, rtol=5e-4, atol=1e-5)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_gpipe_arcface_inference_scores_match_dense_head():
    """GPipeArcFaceViT's labels=None path must produce exactly the dense
    ArcMarginHead s·cosθ inference scores for the same embeddings — the
    eval contract every arcface workload shares (ARCFACE eval semantics),
    here through the pipelined backbone."""
    from ddp_classification_pytorch_tpu.models.heads import ArcMarginHead
    from ddp_classification_pytorch_tpu.models.pipeline_vit import (
        GPipeArcFaceViT,
    )

    mesh = meshlib.make_mesh(meshlib.MeshSpec(4, 1, 2), jax.devices()[:8])
    with mesh:
        model = GPipeArcFaceViT("vit_t16", 11, mesh, microbatches=2,
                                dtype=jnp.float32, axis_name="pipe")
        v = model.init(jax.random.PRNGKey(3), jnp.zeros((1, SIZE, SIZE, 3)))
        x = jnp.asarray(np.random.default_rng(5).normal(
            size=(8, SIZE, SIZE, 3)), jnp.float32)
        scores = np.asarray(model.apply(v, x, None, train=False))
        emb = np.asarray(model.apply(v, x, train=False, method="features"))
    head = ArcMarginHead(num_classes=11, in_features=emb.shape[1])
    ref = head.apply({"params": v["params"]["margin"]}, jnp.asarray(emb), None)
    np.testing.assert_allclose(scores, np.asarray(ref), rtol=1e-5, atol=1e-5)
    assert scores.shape == (8, 11)
