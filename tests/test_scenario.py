"""Scenario subsystem (scenario/): spec grammar, event log, serve-side
chaos kinds, watcher backoff, and — the point — each S1–S4 invariant
checker proven to FIRE on a violating synthetic timeline and pass on a
clean one. The full supervised drill (elastic pod + replicas + load) runs
as the `slow` test at the bottom; everything else is tier-1-lean: no
subprocesses, no sleeps beyond the watcher's own sub-second backoff.
"""

import json
import os
import subprocess
import sys
from types import SimpleNamespace

import numpy as np
import pytest

from ddp_classification_pytorch_tpu.scenario import events as ev
from ddp_classification_pytorch_tpu.scenario.invariants import (
    check_invariants,
    check_restarts_log,
    check_s1_verified_serve,
    check_s2_availability,
    check_s3_adoption,
    check_s4_analyzer,
    check_s5_fleet,
    good_publishes,
    replica_retire_times,
)
from ddp_classification_pytorch_tpu.scenario.spec import SpecError, load_spec
from ddp_classification_pytorch_tpu.utils.chaos import FaultPlan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ spec --


def test_spec_defaults_and_full_parse(tmp_path):
    s = load_spec("{}")
    assert s.trainer.hosts == 2 and s.serve.replicas == 2
    assert s.availability.floor == 0.5 and s.adopt_deadline_s == 120.0

    full = {
        "trainer": {"hosts": 2, "epochs": 4, "min_processes": 1,
                    "fault_specs": {"0": "ckpt_io@epoch=0",
                                    "1": "host_lost@step=10"}},
        "serve": {"replicas": 2, "poll_s": 0.5,
                  "fault_specs": {"1": "watcher_io@poll=3"}},
        "load": {"rps": 2.0, "timeout_s": 10},
        "availability": {"floor": 0.8, "window_s": 5, "min_samples": 2},
        "adopt_deadline_s": 60,
        "timeline": [{"at": "publish:1", "action": "drain_replica",
                      "replica": 1},
                     {"at": "t:30", "action": "kill_replica"}],
    }
    p = tmp_path / "spec.json"
    p.write_text(json.dumps(full))
    s = load_spec(str(p))  # file path form
    assert s.trainer.fault_specs == {0: "ckpt_io@epoch=0",
                                     1: "host_lost@step=10"}
    assert s.serve.fault_specs == {1: "watcher_io@poll=3"}
    assert [(t.at_kind, t.at_value, t.action, t.replica)
            for t in s.timeline] == [("publish", 1, "drain_replica", 1),
                                     ("t", 30, "kill_replica", 0)]


@pytest.mark.parametrize("bad", [
    "",                                           # empty
    "/nonexistent/spec.json",                     # missing file
    '{"trainer": "x"}',                           # wrong type
    '{"bogus": 1}',                               # unknown key
    '{"trainer": {"hosts": 0}}',                  # out of range
    '{"trainer": {"min_processes": 3}}',          # > hosts
    '{"serve": {"replicas": 0}}',                 # no one to answer
    '{"availability": {"floor": 1.5}}',           # floor out of (0,1]
    '{"adopt_deadline_s": -1}',                   # negative deadline
    '{"trainer": {"fault_specs": {"0": "frobnicate@step=1"}}}',  # bad kind
    '{"trainer": {"fault_specs": {"9": "ckpt_io@epoch=0"}}}',    # bad index
    '{"serve": {"fault_specs": {"0": "watcher_io@step=3"}}}',    # bad unit
    '{"timeline": [{"at": "epoch:1", "action": "drain_replica"}]}',
    '{"timeline": [{"at": "t:1", "action": "explode"}]}',
    '{"timeline": [{"at": "t:1", "action": "drain_replica", "replica": 7}]}',
    '{"serve": {"replicas": 2, "max_replicas": 1}}',  # cap below floor
    '{"serve": {"fleet_ttl_s": 0}}',                  # dead-on-arrival leases
    '{"serve": {"admission_deadline_ms": -1}}',       # negative deadline
    '{"serve": {"scale_out_deadline_s": 0}}',         # zero SLA
    '{"timeline": [{"at": "t:1", "action": "spike_load"}]}',       # no rps
    '{"timeline": [{"at": "t:1", "action": "spike_load", "rps": 0}]}',
    '{"timeline": [{"at": "t:1", "action": "spike_load", "rps": "x"}]}',
    '{"timeline": [{"at": "publish:1", "action": "spike_load", "rps": 2}]}',
    '{"timeline": [{"at": "t:1", "action": "spike_load", "rps": 2, '
    '"replica": 0}]}',
    '{"timeline": [{"at": "t:1", "action": "kill_replica", "rps": 2}]}',
    '{"timeline": [{"at": "t:1", "action": "kill_replica_during_wave", '
    '"replica": 1}]}',
])
def test_spec_errors(bad):
    with pytest.raises(SpecError):
        load_spec(bad)


def test_spec_fleet_keys_and_new_actions_parse():
    s = load_spec(json.dumps({
        "serve": {"replicas": 2, "max_replicas": 3, "fleet_ttl_s": 2.5,
                  "admission_deadline_ms": 250.0,
                  "scale_out_deadline_s": 30.0},
        "timeline": [{"at": "t:30", "action": "spike_load", "rps": 12},
                     {"at": "t:40", "action": "kill_replica_during_wave"}],
    }))
    assert s.serve.max_replicas == 3
    assert s.serve.fleet_ttl_s == 2.5
    assert s.serve.admission_deadline_ms == 250.0
    assert s.serve.scale_out_deadline_s == 30.0
    assert str(s.timeline[0]) == "spike_load@t:30(rps=12.0)"
    assert str(s.timeline[1]) == "kill_replica_during_wave@t:40(holder)"


def test_cli_scenario_bad_spec_exits_2(capsys):
    from ddp_classification_pytorch_tpu.cli.scenario import main

    with pytest.raises(SystemExit) as exc:
        main(["--scenario_spec", '{"bogus": 1}', "--check_only"])
    assert exc.value.code == 2
    assert "spec error" in capsys.readouterr().err


# ----------------------------------------------------- serve-side chaos --


def test_new_fault_kinds_parse_and_validate():
    plan = FaultPlan.parse("publish_corrupt@epoch=2,watcher_io@poll=3")
    assert len(plan.faults) == 2
    with pytest.raises(ValueError):
        FaultPlan.parse("publish_corrupt@step=1")  # epoch-keyed only
    with pytest.raises(ValueError):
        FaultPlan.parse("watcher_io@epoch=1")  # poll-keyed only
    with pytest.raises(ValueError):
        FaultPlan.parse("nan_loss@poll=1")  # poll belongs to watcher_io


def test_watcher_io_fires_once():
    plan = FaultPlan.parse("watcher_io@poll=2")
    plan.maybe_fail_watcher_poll(poll=1)  # below range: no fire
    with pytest.raises(OSError):
        plan.maybe_fail_watcher_poll(poll=2)
    plan.maybe_fail_watcher_poll(poll=2)  # one-shot: consumed


def test_publish_corrupt_tears_published_candidate(tmp_path, monkeypatch):
    """publish_corrupt tears the landed epoch file exactly like ckpt_io
    (sidecar stays from the intact bytes, so verification fails) and the
    publish + publish_torn events land in the armed event log."""
    from ddp_classification_pytorch_tpu.train.checkpoint import (
        CheckpointManager,
    )

    events_path = str(tmp_path / "events.jsonl")
    monkeypatch.setenv(ev.ENV_EVENTS, events_path)
    monkeypatch.setenv(ev.ENV_SOURCE, "trainer.h0")
    plan = FaultPlan.parse("publish_corrupt@epoch=0")
    mgr = CheckpointManager(str(tmp_path), async_save=False, chaos=plan)
    state = {"w": np.arange(16, dtype=np.float32)}
    mgr.save(state, epoch=0)

    assert mgr.verify_checkpoint(mgr.epoch_path(0)) == "corrupt"
    recs = ev.read_events(events_path)
    kinds = [r["kind"] for r in recs]
    assert kinds == ["publish", "publish_torn"]
    assert recs[0]["epoch"] == 0 and recs[0]["source"] == "trainer.h0"
    assert len(recs[0]["digest"]) == 64

    # a verifier quarantines it — and the quarantine event lands too
    assert mgr.restore_verified(state, mgr.epoch_path(0)) is None
    assert os.path.exists(mgr.epoch_path(0) + ".corrupt")
    assert ev.read_events(events_path)[-1]["kind"] == "quarantine"


# -------------------------------------------------------------- events --


def test_event_log_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = ev.EventLog(path, "supervisor")
    log.emit("scenario_start", out="x")
    log.emit("publish", epoch=0, digest="d")
    with open(path, "a") as f:
        f.write('{"kind": "swap", "ts": 99')  # producer SIGKILLed mid-append
    recs = ev.read_events(path)
    assert [r["kind"] for r in recs] == ["scenario_start", "publish"]
    assert all(r["source"] == "supervisor" for r in recs)
    assert recs[0]["ts"] <= recs[1]["ts"]


def test_emit_is_noop_without_env(tmp_path, monkeypatch):
    monkeypatch.delenv(ev.ENV_EVENTS, raising=False)
    ev.emit("publish", epoch=0)  # must not write anywhere or raise
    monkeypatch.setenv(ev.ENV_EVENTS, str(tmp_path / "e.jsonl"))
    monkeypatch.setenv(ev.ENV_SOURCE, "t")
    ev.emit("publish", epoch=0)
    assert len(ev.read_events(str(tmp_path / "e.jsonl"))) == 1


# ------------------------------------------------ watcher poll hardening --


class _StubEngine:
    def __init__(self):
        self.swaps = []

    def swap_state(self, state, digest="", generation=-1):
        self.swaps.append((digest, generation))


def test_watcher_poll_backoff_is_bounded_deterministic_and_rearms(tmp_path):
    """Transient fs errors during the poll must not kill the watcher: each
    failure doubles the delay (bounded by max_backoff_s), and the next
    clean poll resets it — the exact sequence is pinned."""
    from ddp_classification_pytorch_tpu.serve.reload import CheckpointWatcher

    plan = FaultPlan.parse(
        "watcher_io@poll=1,watcher_io@poll=2,watcher_io@poll=3,"
        "watcher_io@poll=4,watcher_io@poll=5,watcher_io@poll=6")
    w = CheckpointWatcher(str(tmp_path), _StubEngine(), template_state=None,
                          poll_s=1.0, chaos=plan, max_backoff_s=8.0)
    delays = [w.poll_once() for _ in range(7)]
    # 6 failures: 2,4,8,8,8,8 (capped) — then the clean poll re-arms to 1
    assert delays == [2.0, 4.0, 8.0, 8.0, 8.0, 8.0, 1.0]
    assert w.consecutive_errors == 0 and w.last_error is None
    assert w.polls == 7


def test_watcher_thread_survives_poll_fault_and_stays_alive(tmp_path):
    """The poll THREAD re-arms after an injected EIO: it keeps polling
    (counter advances past the fault) and `alive` stays True — a dead
    watcher may never be silent."""
    from ddp_classification_pytorch_tpu.serve.reload import CheckpointWatcher

    plan = FaultPlan.parse("watcher_io@poll=2")
    w = CheckpointWatcher(str(tmp_path), _StubEngine(), template_state=None,
                          poll_s=0.05, chaos=plan, max_backoff_s=0.1)
    w.start()
    try:
        import time

        deadline = time.monotonic() + 5.0
        while w.polls < 4 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert w.polls >= 4, "watcher thread stopped polling after the fault"
        assert w.alive
    finally:
        w.stop()
    assert not w.alive


# ------------------------------------------------------ invariant FIREs --


def _clean_timeline():
    E = []

    def mk(ts, kind, src, **kw):
        E.append({"ts": ts, "kind": kind, "source": src, **kw})

    mk(0.0, "scenario_start", "supervisor")
    for r in ("replica0", "replica1"):
        mk(1.0, "serve_ready", r, port=1, epoch=-1)
    mk(5.0, "publish", "trainer.h0", epoch=0, path="c0", digest="D0",
       world_size=2)
    for r in ("replica0", "replica1"):
        mk(6.0, "verify_ok", r, epoch=0, path="c0", digest="D0")
        mk(6.1, "swap", r, epoch=0, digest="D0")
    # a TORN publish (epoch 1) that was quarantined: exempt from S3
    mk(8.0, "publish", "trainer.h0", epoch=1, path="c1", digest="D1",
       world_size=2)
    mk(8.0, "publish_torn", "trainer.h0", epoch=1, path="c1")
    mk(9.0, "quarantine", "replica0", path="c1", reason="checksum mismatch")
    for i in range(20):
        ts = 3.0 + i
        status = "busy" if i == 7 else "ok"  # one 503 is degraded-but-alive
        if status != "ok":
            kw = {"code": 503}
        elif ts < 6.0:  # pre-adoption answers on init params: S1-exempt
            kw = {"digest": "fresh", "generation": -1}
        else:
            kw = {"digest": "D0", "generation": 0}
        mk(ts, "request", "loadgen", status=status,
           replica=f"replica{i % 2}", **kw)
    mk(30.0, "lint", "supervisor", rc=0)
    mk(31.0, "scenario_end", "supervisor", ok=True)
    return sorted(E, key=lambda r: r["ts"])


def _spec():
    return load_spec('{"availability": {"floor": 0.5, "window_s": 10.0, '
                     '"min_samples": 3}, "adopt_deadline_s": 20}')


def test_clean_timeline_passes_all_invariants():
    assert check_invariants(_clean_timeline(), _spec()) == []


def test_good_publishes_excludes_torn_and_quarantined():
    goods = good_publishes(_clean_timeline())
    assert [g["epoch"] for g in goods] == [0]


def test_good_publishes_clean_rewrite_of_condemned_path_counts():
    """Fuzzer-found checker bug: condemnation is per WRITE, not per path
    forever. A restart that re-publishes a previously-torn path with a
    clean write must make that publish good again — the old path-set
    implementation silently masked S3/S5(b) on every re-published path
    (regression corpus: tests/data/scenarios/torn-republish-quarantine)."""
    E = [
        {"ts": 1.0, "kind": "publish", "source": "trainer.h0", "epoch": 1,
         "path": "c1", "digest": "TORN", "world_size": 1},
        {"ts": 1.1, "kind": "publish_torn", "source": "trainer.h0",
         "epoch": 1, "path": "c1"},
        {"ts": 2.0, "kind": "quarantine", "source": "replica0", "path": "c1",
         "reason": "checksum mismatch"},
        # restart rewrites the SAME path cleanly
        {"ts": 5.0, "kind": "publish", "source": "trainer.h0", "epoch": 1,
         "path": "c1", "digest": "CLEAN", "world_size": 1},
    ]
    goods = good_publishes(E)
    assert [g["digest"] for g in goods] == ["CLEAN"]
    # and a quarantine AFTER the rewrite condemns only the rewrite
    E.append({"ts": 6.0, "kind": "quarantine", "source": "replica0",
              "path": "c1", "reason": "checksum mismatch"})
    assert good_publishes(E) == []


def test_s1_fires_on_unverified_digest_serve():
    E = _clean_timeline()
    # replica1 answers with a digest only replica0 verified — cross-replica
    # verification does NOT count (each replica attests its own params)
    E.append({"ts": 25.0, "kind": "request", "source": "loadgen",
              "status": "ok", "replica": "replica1", "digest": "DEVIL",
              "generation": 9})
    v = check_s1_verified_serve(E)
    assert len(v) == 1 and v[0].invariant == "S1"
    assert "never verified" in v[0].message


def test_s1_fires_on_missing_digest():
    E = _clean_timeline()
    E.append({"ts": 25.0, "kind": "request", "source": "loadgen",
              "status": "ok", "replica": "replica0", "digest": None})
    assert any("no params digest" in v.message
               for v in check_s1_verified_serve(E))


def test_s2_fires_on_availability_dip():
    E = _clean_timeline()
    for i in range(8):  # a window of connection-refused: fleet dead
        E.append({"ts": 40.0 + i, "kind": "request", "source": "loadgen",
                  "status": "refused", "replica": "-"})
    v = check_s2_availability(sorted(E, key=lambda r: r["ts"]), _spec())
    assert v and all(x.invariant == "S2" for x in v)
    assert "floor" in v[0].message


def test_s2_503s_count_as_alive():
    E = _clean_timeline()
    for i in range(8):  # pure backpressure: degraded but ALIVE
        E.append({"ts": 40.0 + i, "kind": "request", "source": "loadgen",
                  "status": "busy", "replica": "replica0", "code": 503})
    assert check_s2_availability(sorted(E, key=lambda r: r["ts"]),
                                 _spec()) == []


def test_s2_fires_on_no_requests_at_all():
    E = [e for e in _clean_timeline() if e["kind"] != "request"]
    assert any("never ran" in v.message
               for v in check_s2_availability(E, _spec()))


def test_s3_fires_on_missed_adoption():
    E = _clean_timeline()
    # a good publish (epoch 2) nobody ever swaps to
    E.append({"ts": 25.0, "kind": "publish", "source": "trainer.h0",
              "epoch": 2, "path": "c2", "digest": "D2", "world_size": 1})
    v = check_s3_adoption(sorted(E, key=lambda r: r["ts"]), _spec())
    assert len(v) == 2  # one per replica
    assert all(x.invariant == "S3" and "never adopted" in x.message
               for x in v)


def test_s3_fires_on_late_adoption_but_not_after_replica_restart():
    E = _clean_timeline()
    E.append({"ts": 25.0, "kind": "publish", "source": "trainer.h0",
              "epoch": 2, "path": "c2", "digest": "D2", "world_size": 1})
    for r in ("replica0", "replica1"):  # adopted 30s late (deadline 20s)
        E.append({"ts": 55.0, "kind": "swap", "source": r, "epoch": 2,
                  "digest": "D2"})
    v = check_s3_adoption(sorted(E, key=lambda r: r["ts"]), _spec())
    assert len(v) == 2 and all("past deadline" in x.message for x in v)
    # ...but a replica that RESTARTED at ts=50 gets its deadline re-based
    # (a deliberate drain/relaunch must not be an instant red)
    E.append({"ts": 50.0, "kind": "serve_ready", "source": "replica0",
              "port": 1, "epoch": 2})
    v = check_s3_adoption(sorted(E, key=lambda r: r["ts"]), _spec())
    assert len(v) == 1 and "replica1" in v[0].message


def test_s3_fires_on_no_good_publish():
    E = [e for e in _clean_timeline()
         if e["kind"] not in ("publish", "verify_ok", "swap")]
    assert any("never published" in v.message
               for v in check_s3_adoption(E, _spec()))


def test_restarts_log_gen_world_fields(tmp_path):
    good = tmp_path / "restarts.log"
    good.write_text(  # host= is a hostname, not necessarily numeric
        "2026-08-05T10:00:00+00:00 host=tpu-vm-3 proc=1 rc=11 backoff=1s "
        "attempt=2/8 gen=3 world=0,1 action=restart\n"
        "2026-08-05T10:05:00+00:00 host=tpu-vm-3 proc=1 rc=0 backoff=0s "
        "attempt=2/8 gen=3 world=0,1 action=exit\n")
    assert check_restarts_log(str(good)) == []
    bad = tmp_path / "bad.log"
    bad.write_text(  # the elastic bookkeeping fields went missing
        "2026-08-05T10:00:00+00:00 host=1 proc=4242 rc=11 backoff=1s "
        "attempt=2/8 action=restart\n")
    v = check_restarts_log(str(bad))
    assert len(v) == 1 and v[0].invariant == "S3"
    assert "gen=" in v[0].message


def test_s4_fires_on_missing_or_red_lint():
    E = [e for e in _clean_timeline() if e["kind"] != "lint"]
    assert any("no lint event" in v.message for v in check_s4_analyzer(E))
    E.append({"ts": 30.0, "kind": "lint", "source": "supervisor", "rc": 1})
    assert any("rc=1" in v.message for v in check_s4_analyzer(E))


def _fleet_spec():
    return load_spec(
        '{"serve": {"replicas": 2, "max_replicas": 3, '
        '"scale_out_deadline_s": 30.0}, '
        '"availability": {"floor": 0.5, "window_s": 10.0, "min_samples": 3},'
        ' "adopt_deadline_s": 20}')


def test_s5_passes_on_serialized_wave_and_on_no_fleet_events():
    assert check_s5_fleet(_clean_timeline(), _spec()) == []  # vacuous
    E = _clean_timeline()
    E += [{"ts": 40.0, "kind": "drain_token_acquire", "source": "replica0",
           "replica": 0, "digest": "D0"},
          {"ts": 41.0, "kind": "drain_token_release", "source": "replica0",
           "replica": 0, "digest": "D0", "generation": 0},
          {"ts": 42.0, "kind": "drain_token_acquire", "source": "replica1",
           "replica": 1, "digest": "D0"},
          {"ts": 43.0, "kind": "drain_token_release", "source": "replica1",
           "replica": 1, "digest": "D0", "generation": 0}]
    assert check_s5_fleet(sorted(E, key=lambda r: r["ts"]), _spec()) == []


def test_s5_fires_on_overlapping_drains():
    E = _clean_timeline()
    E += [{"ts": 40.0, "kind": "drain_token_acquire", "source": "replica0",
           "replica": 0, "digest": "D0"},
          {"ts": 41.0, "kind": "drain_token_acquire", "source": "replica1",
           "replica": 1, "digest": "D0"}]
    v = check_s5_fleet(sorted(E, key=lambda r: r["ts"]), _spec())
    assert len(v) == 1 and v[0].invariant == "S5"
    assert "two replicas draining at once" in v[0].message


def test_s5_takeover_closes_the_wedged_holders_interval():
    E = _clean_timeline()
    # replica0 acquires then dies without releasing; replica1's TTL
    # takeover force-closes the interval, so its acquire is NOT an overlap
    E += [{"ts": 40.0, "kind": "drain_token_acquire", "source": "replica0",
           "replica": 0, "digest": "D0"},
          {"ts": 50.0, "kind": "drain_token_takeover", "source": "replica1",
           "replica": 1, "digest": "D0"},
          {"ts": 50.1, "kind": "drain_token_acquire", "source": "replica1",
           "replica": 1, "digest": "D0"},
          {"ts": 51.0, "kind": "drain_token_release", "source": "replica1",
           "replica": 1, "digest": "D0", "generation": 0}]
    assert check_s5_fleet(sorted(E, key=lambda r: r["ts"]), _spec()) == []


def test_s5_fires_on_survivor_digest_divergence():
    E = _clean_timeline()
    E.append({"ts": 25.0, "kind": "swap", "source": "replica1", "epoch": 0,
              "digest": "DX"})
    v = check_s5_fleet(sorted(E, key=lambda r: r["ts"]), _spec())
    assert any("did not converge" in x.message for x in v)
    # ...unless that replica was retired by scale-in: survivors only
    E.append({"ts": 26.0, "kind": "replica_retire", "source": "supervisor",
              "replica": "replica1"})
    assert check_s5_fleet(sorted(E, key=lambda r: r["ts"]), _spec()) == []
    assert replica_retire_times(E) == {"replica1": 26.0}


def test_s5_fires_on_convergence_to_a_stale_digest():
    E = _clean_timeline()
    for r in ("replica0", "replica1"):  # both end on a digest that is not
        E.append({"ts": 25.0, "kind": "swap", "source": r, "epoch": 0,
                  "digest": "STALE"})  # the newest good publish (D0)
    v = check_s5_fleet(sorted(E, key=lambda r: r["ts"]), _spec())
    assert len(v) == 1 and "newest good publish" in v[0].message


def test_s5_spike_load_demands_scale_out_within_deadline():
    E = _clean_timeline()
    E.append({"ts": 40.0, "kind": "spike_load", "source": "supervisor",
              "rps": 10.0})
    # scaler disarmed (max_replicas == 0): no demand on the timeline
    assert not any("spike_load" in x.message
                   for x in check_s5_fleet(E, _spec()))
    # armed spec: the unanswered spike is a violation...
    v = check_s5_fleet(sorted(E, key=lambda r: r["ts"]), _fleet_spec())
    assert any("never answered by a" in x.message for x in v)
    # ...a scale_out past the deadline still is...
    late = E + [{"ts": 75.0, "kind": "scale_out", "source": "supervisor",
                 "replica": "replica2", "replicas": 3}]
    v = check_s5_fleet(sorted(late, key=lambda r: r["ts"]), _fleet_spec())
    assert any("never answered by a" in x.message for x in v)
    # ...and one inside it settles the demand
    ok = E + [{"ts": 55.0, "kind": "scale_out", "source": "supervisor",
               "replica": "replica2", "replicas": 3}]
    assert check_s5_fleet(sorted(ok, key=lambda r: r["ts"]),
                          _fleet_spec()) == []


def test_s5_spike_with_fleet_already_at_max_is_excused():
    """Fuzzer-found checker bug: a spike landing when earlier scale_outs
    already grew the fleet to max_replicas demands nothing — the
    autoscaler has no headroom left (regression corpus:
    tests/data/scenarios/spike-at-max-fleet)."""
    E = _clean_timeline()
    E += [{"ts": 30.0, "kind": "scale_out", "source": "supervisor",
           "replica": "replica2", "replicas": 3},
          {"ts": 40.0, "kind": "spike_load", "source": "supervisor",
           "rps": 10.0}]
    assert check_s5_fleet(sorted(E, key=lambda r: r["ts"]),
                          _fleet_spec()) == []
    # a scale_in before the spike reopens headroom: demand is back on
    down = E + [{"ts": 35.0, "kind": "scale_in", "source": "supervisor",
                 "replica": "replica2", "replicas": 2}]
    v = check_s5_fleet(sorted(down, key=lambda r: r["ts"]), _fleet_spec())
    assert any("never answered by a" in x.message for x in v)


def test_s3_scale_in_retirement_excuses_adoption():
    E = _clean_timeline()
    E.append({"ts": 25.0, "kind": "publish", "source": "trainer.h0",
              "epoch": 2, "path": "c2", "digest": "D2", "world_size": 1})
    E.append({"ts": 26.0, "kind": "swap", "source": "replica0", "epoch": 2,
              "digest": "D2"})
    # without the retirement record, replica1 is a plain S3 red
    v = check_s3_adoption(sorted(E, key=lambda r: r["ts"]), _spec())
    assert len(v) == 1 and "replica1" in v[0].message
    # retired before its deadline and never came back: excused
    E.append({"ts": 30.0, "kind": "replica_retire", "source": "supervisor",
              "replica": "replica1"})
    assert check_s3_adoption(sorted(E, key=lambda r: r["ts"]), _spec()) == []
    # a serve_ready AFTER the retirement voids the excusal (it rejoined)
    E.append({"ts": 35.0, "kind": "serve_ready", "source": "replica1",
              "port": 1, "epoch": 2})
    v = check_s3_adoption(sorted(E, key=lambda r: r["ts"]), _spec())
    assert len(v) == 1 and "replica1" in v[0].message


def test_cli_scenario_check_only_red_and_green(tmp_path, capsys):
    from ddp_classification_pytorch_tpu.cli.scenario import main

    ev_path = tmp_path / "events.jsonl"
    with open(ev_path, "w") as f:
        for r in _clean_timeline():
            f.write(json.dumps(r) + "\n")
    spec = ('{"availability": {"floor": 0.5, "window_s": 10.0, '
            '"min_samples": 3}, "adopt_deadline_s": 20}')
    main(["--scenario_spec", spec, "--check_only", "--events", str(ev_path),
          "--out", str(tmp_path)])
    assert "GREEN" in capsys.readouterr().out

    with open(ev_path, "a") as f:  # one stale-digest answer → rc 1
        f.write(json.dumps({"ts": 25.0, "kind": "request",
                            "source": "loadgen", "status": "ok",
                            "replica": "replica0", "digest": "BAD"}) + "\n")
    with pytest.raises(SystemExit) as exc:
        main(["--scenario_spec", spec, "--check_only",
              "--events", str(ev_path), "--out", str(tmp_path)])
    assert exc.value.code == 1
    assert "VIOLATION [S1]" in capsys.readouterr().err


def test_cli_scenario_check_only_rejects_malformed_events(tmp_path, capsys):
    """--check_only is strict: an unknown event kind or a kind missing a
    schema-required field is rc 2 (bad input), never a silent skip that
    would let a truncated/corrupt events.jsonl replay 'green'."""
    from ddp_classification_pytorch_tpu.cli.scenario import main

    spec = ('{"availability": {"floor": 0.5, "window_s": 10.0, '
            '"min_samples": 3}, "adopt_deadline_s": 20}')

    def run(extra):
        ev_path = tmp_path / "events.jsonl"
        with open(ev_path, "w") as f:
            for r in _clean_timeline() + extra:
                f.write(json.dumps(r) + "\n")
        main(["--scenario_spec", spec, "--check_only",
              "--events", str(ev_path), "--out", str(tmp_path)])

    with pytest.raises(SystemExit) as exc:  # unknown kind
        run([{"ts": 25.0, "kind": "warp_core_breach", "source": "x"}])
    assert exc.value.code == 2
    assert "unknown kind" in capsys.readouterr().err

    with pytest.raises(SystemExit) as exc:  # publish missing its digest
        run([{"ts": 25.0, "kind": "publish", "source": "trainer.h0",
              "epoch": 3, "path": "c3"}])
    assert exc.value.code == 2
    assert "missing required field" in capsys.readouterr().err


def test_validate_events_unit():
    from ddp_classification_pytorch_tpu.obs.events import (EVENT_SCHEMA,
                                                           validate_events)

    assert validate_events(_clean_timeline()) == []
    errs = validate_events([{"ts": 1.0, "kind": "nope", "source": "x"},
                            {"kind": "swap", "epoch": 0, "digest": "D"}])
    assert len(errs) == 2
    assert "unknown kind" in errs[0]
    assert "missing required field" in errs[1] and "ts" in errs[1]
    assert "scenario_start" in EVENT_SCHEMA and "request" in EVENT_SCHEMA


# ------------------------------------------------------- the full drill --


@pytest.mark.slow
def test_full_scenario_drill(tmp_path):
    """chaos_drill.sh phase 8: the complete supervised train→serve drill —
    elastic 2-host pod through NaN burst / torn ckpt / host SIGKILL /
    corrupt published candidate / watcher flake / reload-during-drain,
    2 replicas under offered load, S1–S4 asserted from events.jsonl."""
    env = dict(os.environ)
    env["CHAOS_PHASES"] = "8"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "chaos_drill.sh"),
         str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=1800)
    assert proc.returncode == 0, \
        f"drill failed:\n{proc.stdout[-4000:]}\n{proc.stderr[-4000:]}"
    assert "phase 8 OK" in proc.stdout
