"""Observability spine (obs/): registry semantics + exposition, atomic
scrape-file rewrite, Chrome-trace parsing, the SpanRecorder fallback, and
the promoted event plane's compat surface.

The registry tests pin the operational contracts the instruments are
trusted for: thread-safe counting, quantiles bit-identical to the legacy
ServeMetrics estimator (so `/metrics` and `/metrics.json` can never
disagree about p99), deterministic exposition (golden-testable), and a
`write_prom` a concurrent scraper can read mid-rewrite without ever seeing
a torn file. The trace tests run the SAME parser bench's --trace path uses
over a checked-in fixture shaped like a real CPU capture — known bucket
sums, unknown-op-goes-to-idle, window clipping, per-lane overlap union.
"""

import gzip
import json
import os
import threading

import pytest

from ddp_classification_pytorch_tpu.obs import events as obs_events
from ddp_classification_pytorch_tpu.obs import trace as tracelib
from ddp_classification_pytorch_tpu.obs.registry import Registry

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "data", "fixture.trace.json")


# ----------------------------------------------------------------- registry --

def test_counter_concurrent_increments():
    reg = Registry()
    c = reg.counter("t_total", "concurrent counter")

    def worker():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000


def test_counter_rejects_negative_and_type_mismatch():
    reg = Registry()
    c = reg.counter("a_total", "x")
    with pytest.raises(ValueError):
        c.inc(-1)
    # re-registration with the same kind returns the SAME instrument
    assert reg.counter("a_total", "x") is c
    # ... but a different kind under the same name is a hard error
    with pytest.raises(ValueError):
        reg.gauge("a_total", "x")
    with pytest.raises(ValueError):
        reg.counter("bad name", "x")


def test_gauge_set_inc_dec():
    reg = Registry()
    g = reg.gauge("depth", "x")
    g.set(5)
    g.inc(2)
    g.dec(3)
    assert g.value == 4.0


def test_histogram_quantiles_match_legacy_percentile():
    """The registry quantile estimator must be bit-identical to the
    `serve/metrics.py::percentile` the JSON snapshot always reported —
    otherwise /metrics and /metrics.json disagree about the same window."""
    from ddp_classification_pytorch_tpu.serve.metrics import percentile

    reg = Registry()
    h = reg.histogram("lat_ms", "x", window=64)
    data = [float(v) for v in
            [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3, 2, 3, 8, 4]]
    for v in data:
        h.observe(v)
    window = sorted(h.values())
    for q, pct in ((0.5, 50), (0.95, 95), (0.99, 99)):
        assert h.quantile(q) == percentile(window, pct), q
    assert h.count == len(data)
    assert h.sum == sum(data)


def test_histogram_window_is_bounded_but_totals_are_not():
    reg = Registry()
    h = reg.histogram("w_ms", "x", window=4)
    for v in range(10):
        h.observe(float(v))
    assert h.values() == [6.0, 7.0, 8.0, 9.0]  # bounded window
    assert h.count == 10 and h.sum == 45.0     # monotonic all-time totals


def test_exposition_golden():
    """Deterministic exposition: sorted families, one HELP/TYPE block each,
    label escaping, summary shape for histograms."""
    reg = Registry()
    reg.counter("req_total", "requests", labels={"code": "200"}).inc(3)
    reg.counter("req_total", "requests", labels={"code": "503"}).inc()
    reg.gauge("depth", "queue depth").set(2)
    h = reg.histogram("lat_ms", "latency", window=16)
    h.observe(1.0)
    h.observe(3.0)
    assert reg.expose() == (
        "# HELP depth queue depth\n"
        "# TYPE depth gauge\n"
        "depth 2\n"
        "# HELP lat_ms latency\n"
        "# TYPE lat_ms summary\n"
        'lat_ms{quantile="0.5"} 1\n'
        'lat_ms{quantile="0.95"} 3\n'
        'lat_ms{quantile="0.99"} 3\n'
        "lat_ms_sum 4\n"
        "lat_ms_count 2\n"
        "# HELP req_total requests\n"
        "# TYPE req_total counter\n"
        'req_total{code="200"} 3\n'
        'req_total{code="503"} 1\n'
    )


def test_snapshot_maps_samples_to_values():
    reg = Registry()
    reg.counter("a_total", "x").inc(2)
    reg.gauge("g", "x").set(1.5)
    snap = reg.snapshot()
    assert snap["a_total"] == 2
    assert snap["g"] == 1.5


def test_write_prom_atomic_under_concurrent_reads(tmp_path):
    """A scraper reading the file while the writer loops must always see a
    COMPLETE exposition (the final family line present) — torn reads would
    mean os.replace is not being used or the tmp file leaked into place."""
    reg = Registry()
    c = reg.counter("rewrites_total", "x")
    reg.gauge("zz_last", "sentinel family, sorts last").set(1)
    path = str(tmp_path / "metrics.prom")
    reg.write_prom(path)
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            c.inc()
            reg.write_prom(path)

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(300):
            with open(path) as f:
                body = f.read()
            # complete snapshot: ends with the lexicographically-last
            # family's sample line, and the counter line parses
            assert body.endswith("zz_last 1\n"), body[-80:]
            lines = [ln for ln in body.splitlines()
                     if ln.startswith("rewrites_total ")]
            assert len(lines) == 1 and float(lines[0].split()[1]) >= 0
    finally:
        stop.set()
        t.join()
    assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]


# -------------------------------------------------------------------- trace --

def test_classify_table():
    assert tracelib.classify("all-reduce.5") == "collectives"
    assert tracelib.classify("ReduceScatter-start") == "collectives"
    # every op kind the ZeRO-1 step puts on the wire (reduce-scatter of
    # grads, all-gather of updated params, GSPMD's permute decomposition)
    assert tracelib.classify("reduce-scatter.4") == "collectives"
    assert tracelib.classify("all-gather-start.2") == "collectives"
    assert tracelib.classify("collective-permute.7") == "collectives"
    assert tracelib.classify("TransferToDevice") == "h2d"
    assert tracelib.classify("copy-start.3") == "h2d"
    assert tracelib.classify("transpose(dot.7)") == "bwd"
    assert tracelib.classify("gradients/conv1") == "bwd"
    assert tracelib.classify("adamw.update") == "optimizer"
    assert tracelib.classify("forward/block1") == "fwd"
    # exact bucket names map to themselves (the SpanRecorder contract)
    for b in tracelib.BUCKETS:
        assert tracelib.classify(b) == b
    # unknown ops are NOT guessed — they become idle via the remainder
    assert tracelib.classify("dot.3") is None
    assert tracelib.classify("reduce-window.2") is None
    assert tracelib.classify("fusion.12") is None


def test_parse_fixture_trace():
    """The checked-in fixture (shaped like a real CPU `.trace.json.gz`
    payload) parses to known per-step sums: overlapping same-lane events
    union, a window-straddling event clips, unknown ops land in idle, and
    the six buckets sum to the wall time exactly."""
    with open(FIXTURE) as f:
        steps = tracelib.parse_chrome_trace(json.load(f))
    assert [s["step"] for s in steps] == [0, 1]
    s0, s1 = steps
    assert s0["step_ms"] == pytest.approx(10.0)
    # all-reduce.5 [1500,3500] and .6 [2000,3000] share a lane → union 2 ms;
    # all-gather.1 [500,1500] clips to the window start → +0.5 ms
    assert s0["collectives"] == pytest.approx(2.5)
    assert s0["h2d"] == pytest.approx(1.0)
    assert s0["fwd"] == 0.0 and s0["bwd"] == 0.0 and s0["optimizer"] == 0.0
    assert s0["idle"] == pytest.approx(6.5)  # dot.3 (unknown) → remainder
    assert s1["step_ms"] == pytest.approx(8.0)
    assert s1["bwd"] == pytest.approx(2.0)
    assert s1["optimizer"] == pytest.approx(1.0)
    # the ZeRO-1 step's op kinds (reduce-scatter.4 1 ms + collective-
    # permute.2 0.8 ms) land in collectives, NOT idle — a trace of the
    # sharded-optimizer step keeps the breakdown honest
    assert s1["collectives"] == pytest.approx(1.8)
    assert s1["idle"] == pytest.approx(3.2)  # reduce-window.2 is unknown
    for s in steps:
        assert sum(s[b] for b in tracelib.BUCKETS) == pytest.approx(
            s["step_ms"])


def _x(name, ts_us, dur_us, tid=0):
    return {"ph": "X", "name": name, "pid": 1, "tid": tid,
            "ts": float(ts_us), "dur": float(dur_us)}


def test_parse_accum_window_buckets_and_amortization():
    """Scanned gradient accumulation: ONE StepTraceAnnotation window (one
    optimizer step) containing K=4 microbatch fwd/bwd executions and a
    single deferred all-reduce. The per-lane union must sum the K disjoint
    same-lane spans (and union a nested one) with the six buckets still
    covering the wall time exactly; the collective lane carries ONE
    reduction's time per window — the same absolute payload as a K=1
    window but ÷K per microbatch, so its share of the wall shrinks vs the
    K=1 fixture below."""
    # K=1 reference: 4 optimizer steps, each its own 10 ms window with its
    # own 2 ms gradient all-reduce (the per-step reduction being amortized)
    k1_events = []
    for n in range(4):
        base = n * 11_000.0  # 10 ms window + 1 ms gap
        k1_events += [
            {**_x("bench_step", base, 10_000.0),
             "args": {"step_num": n}},
            _x("forward/block", base, 3_000.0),
            _x("transpose(dot.1)", base + 3_000, 3_000.0),
            _x("all-reduce.1", base + 6_000, 2_000.0),
            _x("optimizer/sgd", base + 8_000, 1_000.0),
        ]
    k1 = tracelib.parse_chrome_trace({"traceEvents": k1_events})
    assert len(k1) == 4

    # K=4 accumulated step: one 40 ms window, 4 scanned microbatches on
    # the same lane, ONE deferred all-reduce at the optimizer boundary
    ev = [{**_x("bench_step", 0.0, 40_000.0), "args": {"step_num": 0}}]
    for mb in range(4):
        base = mb * 6_500.0
        ev.append(_x("forward/block", base, 3_000.0))
        ev.append(_x("transpose(dot.1)", base + 3_000, 3_000.0))
    # a fusion nested inside microbatch 0's fwd span, same lane: must
    # union into the covering span, not double-count
    ev.append(_x("forward/stem_fusion", 500.0, 1_000.0))
    ev.append(_x("all-reduce.1", 26_000.0, 2_000.0))
    ev.append(_x("optimizer/sgd", 28_000.0, 1_000.0))
    (acc,) = tracelib.parse_chrome_trace({"traceEvents": ev})

    assert acc["step_ms"] == pytest.approx(40.0)
    # 4 disjoint 3 ms fwd spans; the nested fusion unions away
    assert acc["fwd"] == pytest.approx(12.0)
    assert acc["bwd"] == pytest.approx(12.0)
    assert acc["optimizer"] == pytest.approx(1.0)
    # exactly ONE reduction's microseconds in the whole optimizer step —
    # equal to a single K=1 window's collective time (payload parity)...
    assert acc["collectives"] == pytest.approx(k1[0]["collectives"])
    # ...so the collective share of the wall is ~K× smaller than K=1
    k1_share = sum(s["collectives"] for s in k1) / sum(
        s["step_ms"] for s in k1)
    acc_share = acc["collectives"] / acc["step_ms"]
    assert acc_share < k1_share / 3.5
    # the invariant the whole breakdown hangs on: buckets sum to the wall
    # time exactly, idle the remainder — even with K scanned microbatches
    # inside one window
    assert sum(acc[b] for b in tracelib.BUCKETS) == pytest.approx(
        acc["step_ms"])
    for s in k1:
        assert sum(s[b] for b in tracelib.BUCKETS) == pytest.approx(
            s["step_ms"])


def test_aggregate_means_and_empty():
    with open(FIXTURE) as f:
        agg = tracelib.aggregate(tracelib.parse_chrome_trace(json.load(f)))
    assert agg["n_steps"] == 2
    assert agg["step_ms"] == pytest.approx(9.0)
    assert agg["collectives"] == pytest.approx(2.15)
    assert tracelib.aggregate([]) == {}


def test_find_trace_file_and_gz_roundtrip(tmp_path):
    """find_trace_file walks the jax.profiler layout and load_chrome_trace
    is gzip-aware — the exact path bench's --trace capture goes through."""
    d = tmp_path / "plugins" / "profile" / "2026_08_05"
    d.mkdir(parents=True)
    with open(FIXTURE, "rb") as f:
        payload = f.read()
    gz = d / "host.trace.json.gz"
    with gzip.open(gz, "wb") as f:
        f.write(payload)
    assert tracelib.find_trace_file(str(tmp_path)) == str(gz)
    steps = tracelib.breakdown_from_trace_dir(str(tmp_path))
    assert [s["step"] for s in steps] == [0, 1]
    assert tracelib.find_trace_file(str(tmp_path / "plugins" / "empty")) is None
    assert tracelib.breakdown_from_trace_dir(str(tmp_path / "nope")) == []


def test_span_recorder_roundtrip():
    """Host-measured phases → synthetic trace → the SAME parser → the same
    numbers back, with idle as the unattributed remainder."""
    rec = tracelib.SpanRecorder()
    rec.add_step(0, 0.010, {"fwd": 0.004, "bwd": 0.003, "optimizer": 0.001})
    rec.add_step(1, 0.012, {"fwd": 0.005, "bwd": 0.004, "optimizer": 0.001})
    steps = rec.breakdown()
    assert [s["step"] for s in steps] == [0, 1]
    assert steps[0]["fwd"] == pytest.approx(4.0)
    assert steps[0]["idle"] == pytest.approx(2.0)
    agg = tracelib.aggregate(steps)
    assert agg["fwd"] == pytest.approx(4.5)
    assert sum(agg[b] for b in tracelib.BUCKETS) == pytest.approx(
        agg["step_ms"], rel=1e-6)


def test_span_recorder_clips_overflowing_phases():
    """A probe mis-measurement larger than the step window must clip — the
    buckets can never sum past the wall time."""
    rec = tracelib.SpanRecorder()
    rec.add_step(0, 0.005, {"fwd": 0.004, "bwd": 0.004, "optimizer": 0.002})
    (s,) = rec.breakdown()
    assert s["fwd"] == pytest.approx(4.0)
    assert s["bwd"] == pytest.approx(1.0)  # clipped at the window edge
    assert s["optimizer"] == 0.0 and s["idle"] == 0.0
    assert sum(s[b] for b in tracelib.BUCKETS) == pytest.approx(s["step_ms"])


def test_span_recorder_rejects_unknown_phase():
    rec = tracelib.SpanRecorder()
    with pytest.raises(ValueError):
        rec.add_step(0, 0.01, {"fwdd": 0.001})
    with pytest.raises(ValueError):
        rec.add_step(0, 0.01, {"idle": 0.001})  # idle is derived, not fed


# ------------------------------------------------------------- event plane --

def test_scenario_events_is_compat_reexport():
    """The promotion must keep every historical `scenario.events` name
    bound to the SAME objects — env-gated emitters registered against one
    module must be visible through the other."""
    from ddp_classification_pytorch_tpu.scenario import events as compat

    for name in ("ENV_EVENTS", "ENV_SOURCE", "EventLog", "emit",
                 "read_events", "write_event"):
        assert getattr(compat, name) is getattr(obs_events, name), name


def test_emit_gated_and_readable(tmp_path, monkeypatch):
    path = str(tmp_path / "events.jsonl")
    monkeypatch.delenv(obs_events.ENV_EVENTS, raising=False)
    obs_events.emit("swap", epoch=3)  # ungated: must be a no-op
    assert not os.path.exists(path)
    monkeypatch.setenv(obs_events.ENV_EVENTS, path)
    monkeypatch.setenv(obs_events.ENV_SOURCE, "test")
    obs_events.emit("swap", epoch=3)
    (rec,) = obs_events.read_events(path)
    assert rec["kind"] == "swap" and rec["epoch"] == 3
    assert rec["source"] == "test"


# ------------------------------------------------------- serve wire surface --

class _StubEngine:
    """Just enough engine for the HTTP layer: metrics + health attrs."""

    def __init__(self):
        from ddp_classification_pytorch_tpu.serve.metrics import ServeMetrics

        self.metrics = ServeMetrics()
        self.queue_depth = 0
        self.closed = False
        self.params_digest = "d" * 8
        self.params_generation = 1


def _get(port, path):
    """One HTTP/1.0 exchange (the stdlib handler closes per response)."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.getheader("Content-Type"), r.read().decode()
    finally:
        conn.close()


def test_http_metrics_exposition_and_json(tmp_path):
    """GET /metrics serves Prometheus text exposition (versioned
    Content-Type) carrying at least one counter from each owning family —
    serve_*, engine_*, and the watcher's watcher_* (registered into the
    same registry at construction) — while /metrics.json preserves the
    legacy dict and /healthz stays JSON. The wire-contract acceptance."""
    from ddp_classification_pytorch_tpu.serve.http import make_server
    from ddp_classification_pytorch_tpu.serve.reload import CheckpointWatcher

    engine = _StubEngine()
    engine.metrics.record_submit()
    # constructing the watcher registers the watcher_* family into the
    # engine's registry — no poll thread needed for the exposition
    watcher = CheckpointWatcher(str(tmp_path), engine, template_state=None,
                                metrics=engine.metrics)
    server = make_server(engine, 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        status, ctype, body = _get(port, "/metrics")
        assert status == 200
        assert ctype == "text/plain; version=0.0.4"
        assert "# TYPE serve_requests_total counter" in body
        assert "serve_requests_total 1" in body
        assert "# TYPE engine_batches_total counter" in body
        assert "# TYPE watcher_polls_total counter" in body
        status, ctype, body = _get(port, "/metrics.json")
        assert status == 200 and ctype == "application/json"
        snap = json.loads(body)
        assert snap["requests"] == 1 and "p99_ms" in snap
        status, ctype, body = _get(port, "/healthz")
        assert status == 200 and ctype == "application/json"
        health = json.loads(body)
        assert health["ok"] is True and health["digest"] == "d" * 8
    finally:
        server.shutdown()
        server.server_close()
    assert watcher.alive is False


def test_serve_metrics_registry_bridge_preserves_legacy_snapshot():
    """The instrument-backed ServeMetrics must report the EXACT legacy
    snapshot keys/values (bench's serve row and /healthz key on them)."""
    from ddp_classification_pytorch_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics(latency_window=8)
    m.record_submit()
    m.record_submit()
    m.record_reject()
    m.record_batch(4, 2, [1.0, 2.0])
    m.record_error()
    m.record_reload(ok=True)
    m.record_reload(ok=False)
    m.record_recompile()
    s = m.snapshot(queue_depth=5)
    assert s["requests"] == 2 and s["completed"] == 2 and s["rejected"] == 1
    assert s["batches"] == 1 and s["errors"] == 1
    assert s["reloads"] == 1 and s["reloads_rejected"] == 1
    assert s["recompiles"] == 1
    assert s["bucket_hist"] == {4: 1}
    assert s["fill_ratio"] == 0.5
    assert s["p50_ms"] == 1.0 and s["p99_ms"] == 2.0
    assert s["queue_depth"] == 5
    # and the same numbers exposed through the registry
    exp = m.registry.expose()
    assert "engine_rows_padded_total 2" in exp
    assert 'engine_bucket_batches_total{bucket="4"} 1' in exp
    assert "serve_queue_depth 5" in exp


def test_watcher_instruments_count_polls_and_backoff(tmp_path):
    """The watcher's registry instruments track polls/errors/backoff next
    to the quarantine counter — check_once on an empty dir ticks polls;
    a failing poll sets the backoff gauge; a quiet one resets it."""
    from ddp_classification_pytorch_tpu.serve.metrics import ServeMetrics
    from ddp_classification_pytorch_tpu.serve.reload import CheckpointWatcher

    metrics = ServeMetrics()
    w = CheckpointWatcher(str(tmp_path), engine=None, template_state=None,
                          poll_s=0.5, metrics=metrics)
    assert w.poll_once() == 0.5
    snap = metrics.registry.snapshot()
    assert snap["watcher_polls_total"] == 1
    assert snap["watcher_errors_total"] == 0
    assert snap["watcher_backoff_seconds"] == 0

    def boom():
        raise OSError("fs fault")

    w.check_once = boom
    backoff = w.poll_once()
    assert backoff == 1.0  # poll_s * 2^1
    snap = metrics.registry.snapshot()
    assert snap["watcher_errors_total"] == 1
    assert snap["watcher_backoff_seconds"] == 1.0
