"""CIFAR local-pickle dataset tests (synthesized pickle files)."""

import pickle

import numpy as np
import pytest

from ddp_classification_pytorch_tpu.data.cifar import CIFARDataset
from ddp_classification_pytorch_tpu.data.transforms import build_transform


@pytest.fixture(scope="module")
def cifar_root(tmp_path_factory):
    root = tmp_path_factory.mktemp("cifar") / "cifar-10-batches-py"
    root.mkdir()
    rng = np.random.default_rng(0)
    for i in range(1, 6):
        data = {
            "data": rng.integers(0, 256, (20, 3072), dtype=np.int64).astype(np.uint8),
            "labels": rng.integers(0, 10, 20).tolist(),
        }
        with open(root / f"data_batch_{i}", "wb") as f:
            pickle.dump(data, f)
    test = {
        "data": rng.integers(0, 256, (10, 3072), dtype=np.int64).astype(np.uint8),
        "labels": rng.integers(0, 10, 10).tolist(),
    }
    with open(root / "test_batch", "wb") as f:
        pickle.dump(test, f)
    return str(root.parent)  # point at the PARENT: _find_root must descend


def test_cifar10_loads_and_transforms(cifar_root):
    t = build_transform("cifar", train=True, image_size=32)
    ds = CIFARDataset(cifar_root, train=True, transform=t)
    assert len(ds) == 100
    img, label = ds.__getitem__(0, np.random.default_rng(1))
    assert img.shape == (32, 32, 3) and img.dtype == np.float32
    assert 0 <= label < 10

    val = CIFARDataset(cifar_root, train=False,
                       transform=build_transform("cifar", train=False, image_size=32))
    assert len(val) == 10


def test_cifar_missing_files_error(tmp_path):
    t = build_transform("cifar", train=True, image_size=32)
    with pytest.raises(FileNotFoundError, match="cannot download"):
        CIFARDataset(str(tmp_path), train=True, transform=t)
