"""Class-sharded ArcFace CE vs the dense reference, on the 8-device mesh.

The class dimension is this framework's long-context analogue (SURVEY §5):
these tests pin the partial-FC-style sharded loss — values, gradients, and
top-k counts — against ops/arcface.py::arc_margin_logits + dense CE.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddp_classification_pytorch_tpu.ops.arcface import arc_margin_logits
from ddp_classification_pytorch_tpu.ops.sharded_head import arc_margin_ce_sharded
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib


def _setup(b=8, d=16, c=12, seed=0):
    rng = np.random.default_rng(seed)
    feats = jnp.asarray(rng.normal(size=(b, d)), jnp.float32)
    weight = jnp.asarray(rng.normal(size=(c, d)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, c, b), jnp.int32)
    return feats, weight, labels


def _dense_loss(feats, weight, labels, **kw):
    logits = arc_margin_logits(feats, weight, labels, **kw)
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


@pytest.mark.parametrize("mp", [2, 4])
@pytest.mark.parametrize("easy_margin", [True, False])
def test_sharded_ce_matches_dense(mp, easy_margin):
    mesh = meshlib.make_mesh(meshlib.MeshSpec(len(jax.devices()) // mp, mp))
    feats, weight, labels = _setup()
    loss, t1, t3 = jax.jit(
        lambda f, w, l: arc_margin_ce_sharded(
            f, w, l, mesh, meshlib.MODEL_AXIS, batch_axis=meshlib.DATA_AXIS,
            easy_margin=easy_margin)
    )(feats, weight, labels)
    dense = _dense_loss(feats, weight, labels, easy_margin=easy_margin)
    np.testing.assert_allclose(float(loss), float(dense), atol=1e-5)

    # top-k counts vs a dense top-k with the same semantics
    logits = arc_margin_logits(feats, weight, labels, easy_margin=easy_margin)
    _, top3 = jax.lax.top_k(logits, 3)
    hits = np.asarray(top3) == np.asarray(labels)[:, None]
    assert float(t1) == hits[:, :1].sum()
    assert float(t3) == hits.sum()


def test_sharded_ce_gradients_match_dense():
    mp = 4
    mesh = meshlib.make_mesh(meshlib.MeshSpec(len(jax.devices()) // mp, mp))
    feats, weight, labels = _setup()

    def sharded(f, w):
        return arc_margin_ce_sharded(
            f, w, labels, mesh, meshlib.MODEL_AXIS,
            batch_axis=meshlib.DATA_AXIS)[0]

    gf = jax.jit(jax.grad(sharded, argnums=(0, 1)))(feats, weight)
    gd = jax.grad(lambda f, w: _dense_loss(f, w, labels), argnums=(0, 1))(
        feats, weight)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_sharded_ce_rejects_indivisible_classes():
    mesh = meshlib.make_mesh(meshlib.MeshSpec(2, 4))
    feats, weight, labels = _setup(c=10)
    with pytest.raises(ValueError, match="not divisible"):
        arc_margin_ce_sharded(feats, weight, labels, mesh, meshlib.MODEL_AXIS)


def test_arcface_sharded_step_matches_dense_step():
    """Full train-step equivalence: the partial-FC step (flag on) and the
    dense step produce the same loss/metrics from identical initial state
    on a data×model mesh."""
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    mesh = meshlib.make_mesh(meshlib.MeshSpec(2, 4))
    rng = np.random.default_rng(0)
    images = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 16, 8).astype(np.int32)

    results = {}
    for name, flag in (("dense", False), ("sharded", True)):
        cfg = get_preset("arcface")
        cfg.data.image_size = 32
        cfg.data.num_classes = 16
        cfg.data.batch_size = 8
        cfg.model.arch = "resnet18"
        cfg.model.variant = "cifar"
        cfg.model.dtype = "float32"
        cfg.parallel.arcface_sharded_ce = flag
        with mesh:
            model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
            step = make_train_step(cfg, model, tx, mesh=mesh)
            x = jax.device_put(images, meshlib.batch_sharding(mesh))
            y = jax.device_put(labels, meshlib.batch_sharding(mesh))
            state, metrics = step(state, x, y)
            state, metrics = step(state, x, y)  # second step: grads applied
            results[name] = {k: float(v) for k, v in metrics.items()}
    for k in ("loss", "top1", "top3"):
        np.testing.assert_allclose(
            results["sharded"][k], results["dense"][k], atol=1e-4), k


def test_sharded_ce_flag_without_model_axis_raises():
    """--sharded_ce with no model axis must fail loudly, not silently run
    the dense (B, C) path it exists to avoid."""
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    cfg = get_preset("arcface")
    cfg.data.image_size = 32
    cfg.data.num_classes = 16
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.parallel.arcface_sharded_ce = True
    mesh = meshlib.make_mesh(meshlib.MeshSpec(len(jax.devices()), 1))
    with mesh:
        model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
        with pytest.raises(ValueError, match="model axis"):
            make_train_step(cfg, model, tx, mesh=mesh)
        with pytest.raises(ValueError, match="model axis"):
            make_train_step(cfg, model, tx)  # no mesh at all


def test_arcface_sharded_eval_matches_dense_eval():
    """Partial-FC eval (m=0 → s·cosθ scores, valid-masked) must produce the
    same loss_sum/top-k counts as the dense eval step, including a
    wrap-padded final batch."""
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_eval_step

    mesh = meshlib.make_mesh(meshlib.MeshSpec(2, 4))
    rng = np.random.default_rng(1)
    images = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 16, 8).astype(np.int32)
    valid = np.array([1, 1, 1, 1, 1, 1, 0, 0], np.float32)  # padded tail

    results = {}
    for name, flag in (("dense", False), ("sharded", True)):
        cfg = get_preset("arcface")
        cfg.data.image_size = 32
        cfg.data.num_classes = 16
        cfg.data.batch_size = 8
        cfg.model.arch = "resnet18"
        cfg.model.variant = "cifar"
        cfg.model.dtype = "float32"
        cfg.parallel.arcface_sharded_ce = flag
        with mesh:
            model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
            ev = make_eval_step(cfg, model, mesh=mesh)
            x = jax.device_put(images, meshlib.batch_sharding(mesh))
            y = jax.device_put(labels, meshlib.batch_sharding(mesh))
            m = jax.device_put(valid, meshlib.batch_sharding(mesh))
            results[name] = {k: float(v) for k, v in ev(state, x, y, m).items()}
    for k in ("loss_sum", "top1", "top3", "n"):
        np.testing.assert_allclose(
            results["sharded"][k], results["dense"][k], atol=1e-4)
