"""DevicePrefetcher tests on the virtual 8-device CPU mesh.

The prefetch pipeline moves batch assembly + H2D staging
(`make_global_array`) onto a background stager thread. These tests pin the
contract: staged batches are bit-identical to the synchronous path and in
order; worker exceptions surface at the iteration site; teardown on early
exit cannot deadlock; the buffer is depth-bounded; and the Trainer's hot
loop really does stage off the consumer thread (depth 0 really doesn't).
"""

import threading
import time

import numpy as np
import pytest

import jax

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.data.device_prefetch import DevicePrefetcher
from ddp_classification_pytorch_tpu.data.loader import ShardedLoader
from ddp_classification_pytorch_tpu.data.synthetic import SyntheticDataset
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
from ddp_classification_pytorch_tpu.train.loop import Trainer


def _loader(n=64, batch=8, image=4, **kw):
    ds = SyntheticDataset(n, image, 4, seed=7)
    kw.setdefault("shuffle", False)
    return ShardedLoader(ds, batch, seed=7, num_workers=1,
                         host_id=0, num_hosts=1, **kw)


def _get(batch):
    return jax.tree_util.tree_map(np.asarray, jax.device_get(batch))


def test_batches_match_undecorated_loader_in_order():
    loader = _loader()
    mesh = meshlib.make_mesh()
    sync = [_get(b) for b in DevicePrefetcher(loader, mesh, depth=0)]
    staged = [_get(b) for b in DevicePrefetcher(loader, mesh, depth=2)]
    assert len(sync) == len(staged) == len(loader)
    for (si, sl), (pi, pl) in zip(sync, staged):
        np.testing.assert_array_equal(si, pi)
        np.testing.assert_array_equal(sl, pl)


def test_reiterable_across_epochs():
    loader = _loader(n=32, batch=8, shuffle=True)
    mesh = meshlib.make_mesh()
    pre = DevicePrefetcher(loader, mesh, depth=1)
    loader.set_epoch(0)
    e0 = [_get(b)[1] for b in pre]
    loader.set_epoch(1)
    e1 = [_get(b)[1] for b in pre]
    assert len(e0) == len(e1) == 4
    # different epoch → different permutation of the same label multiset
    assert not all(np.array_equal(a, b) for a, b in zip(e0, e1))
    np.testing.assert_array_equal(np.sort(np.concatenate(e0)),
                                  np.sort(np.concatenate(e1)))


class _Poisoned:
    def __len__(self):
        return 64

    def __getitem__(self, i, rng=None):
        if i == 40:
            raise RuntimeError("corrupt sample")
        return np.zeros((4, 4, 3), np.float32), 0


def test_dataset_exception_propagates_through_both_threads():
    loader = ShardedLoader(_Poisoned(), 8, shuffle=False, num_workers=2,
                           host_id=0, num_hosts=1)
    pre = DevicePrefetcher(loader, meshlib.make_mesh(), depth=2)
    with pytest.raises(RuntimeError, match="corrupt sample"):
        list(pre)


def test_assemble_exception_propagates():
    def explode(i, hb):
        if i == 2:
            raise ValueError("bad stage")
        return hb

    pre = DevicePrefetcher(_loader(), depth=2, assemble=explode)
    with pytest.raises(ValueError, match="bad stage"):
        list(pre)


def test_early_break_tears_down_and_reiterates():
    loader = _loader(n=128, batch=8)
    mesh = meshlib.make_mesh()
    pre = DevicePrefetcher(loader, mesh, depth=1)
    for i, _ in enumerate(pre):
        if i == 1:
            break  # abandon mid-epoch: stager + loader producer must exit
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if not any(t.name == "device-stager" and t.is_alive()
                   for t in threading.enumerate()):
            break
        time.sleep(0.05)
    else:
        pytest.fail("stager thread still alive after abandoned iteration")
    # a fresh full pass must work — would hang if teardown deadlocked
    assert len(list(pre)) == 16


def test_buffer_is_depth_bounded():
    depth = 2
    staged = []
    consumed = []
    overshoot = []

    def assemble(i, hb):
        staged.append(i)
        overshoot.append(len(staged) - len(consumed))
        return hb

    pre = DevicePrefetcher(_loader(n=96, batch=8), depth=depth,
                           assemble=assemble)
    for b in pre:
        consumed.append(b)
        time.sleep(0.02)  # slow consumer: the stager runs far ahead if unbounded
    assert len(staged) == 12
    # stager may be ahead by: `depth` queued + 1 in its own hand + 1 popped
    # but not yet recorded by the consumer — never more (an unbounded
    # buffer would reach 11 here with this consumer pacing)
    assert max(overshoot) <= depth + 2, max(overshoot)


def test_staging_runs_on_stager_thread():
    idents = []

    def assemble(i, hb):
        idents.append(threading.get_ident())
        return hb

    pre = DevicePrefetcher(_loader(n=32, batch=8), depth=2, assemble=assemble)
    list(pre)
    assert pre.staged == 4
    assert pre.stager_thread is not None
    assert set(idents) == {pre.stager_thread}
    assert threading.get_ident() not in idents

    # depth 0: inline on the consumer thread, stager_thread stays None
    idents.clear()
    sync = DevicePrefetcher(_loader(n=32, batch=8), depth=0, assemble=assemble)
    list(sync)
    assert sync.stager_thread is None
    assert set(idents) == {threading.get_ident()}


def test_requires_mesh_or_assemble():
    with pytest.raises(ValueError, match="mesh"):
        DevicePrefetcher(_loader())


# ---------------------------------------------------- double-buffered H2D --

def _gone(*names, deadline_s=5.0):
    """True once no live thread carries any of the given names."""
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if not any(t.name in names and t.is_alive()
                   for t in threading.enumerate()):
            return True
        time.sleep(0.05)
    return False


def test_overlap_batches_bit_identical_and_in_order():
    loader = _loader()
    mesh = meshlib.make_mesh()
    sync = [_get(b) for b in DevicePrefetcher(loader, mesh, depth=0)]
    over = [_get(b) for b in DevicePrefetcher(loader, mesh, depth=2,
                                              overlap=True)]
    assert len(sync) == len(over) == len(loader)
    for (si, sl), (oi, ol) in zip(sync, over):
        np.testing.assert_array_equal(si, oi)
        np.testing.assert_array_equal(sl, ol)


def test_overlap_splits_fetch_and_h2d_onto_distinct_threads():
    """The dispatch evidence: host-batch fetch and assemble/H2D run on
    two different named threads, neither of them the consumer; at depth 0
    the flag is ignored bit-for-bit (inline, no threads)."""
    fetch_idents = []
    h2d_idents = []

    class Spy:
        def __init__(self, host):
            self.host = host

        def __iter__(self):
            for hb in self.host:
                fetch_idents.append(threading.get_ident())
                yield hb

    def assemble(i, hb):
        h2d_idents.append(threading.get_ident())
        return hb

    pre = DevicePrefetcher(Spy(_loader(n=32, batch=8)), depth=2,
                           assemble=assemble, overlap=True)
    list(pre)
    assert pre.staged == 4
    assert pre.fetch_thread is not None and pre.stager_thread is not None
    assert pre.fetch_thread != pre.stager_thread
    assert set(fetch_idents) == {pre.fetch_thread}
    assert set(h2d_idents) == {pre.stager_thread}
    assert threading.get_ident() not in fetch_idents + h2d_idents

    # depth 0 ignores overlap: inline, synchronous, no thread idents
    h2d_idents.clear()
    sync = DevicePrefetcher(_loader(n=32, batch=8), depth=0,
                            assemble=assemble, overlap=True)
    list(sync)
    assert sync.stager_thread is None and sync.fetch_thread is None
    assert set(h2d_idents) == {threading.get_ident()}


def test_overlap_pipelines_fetch_behind_transfer():
    """The deterministic timing smoke: with fetch and assemble each
    costing ~delay per batch, the single-stager path pays fetch+assemble
    serially (~2·delay/batch) while overlap pipelines them (~delay/batch
    steady-state). Generous margins keep this robust to scheduler noise:
    the overlapped wall must land below 0.75× the serial wall."""
    delay, n = 0.04, 6

    class Sleepy:
        def __iter__(self):
            for i in range(n):
                time.sleep(delay)
                yield (np.full((8, 4, 4, 3), i, np.float32),
                       np.full((8,), i, np.int32))

    def assemble(i, hb):
        time.sleep(delay)
        return hb

    t0 = time.perf_counter()
    serial = [b for b in DevicePrefetcher(Sleepy(), depth=2,
                                          assemble=assemble)]
    t_serial = time.perf_counter() - t0
    t0 = time.perf_counter()
    overlapped = [b for b in DevicePrefetcher(Sleepy(), depth=2,
                                              assemble=assemble,
                                              overlap=True)]
    t_overlap = time.perf_counter() - t0
    assert len(serial) == len(overlapped) == n
    # serial ≈ n·2·delay = 480 ms; overlap ≈ (n+1)·delay = 280 ms
    assert t_overlap < 0.75 * t_serial, (t_overlap, t_serial)


def test_overlap_exception_mid_transfer_joins_both_threads():
    """Satellite fix: an assemble failure mid-pipeline must surface at the
    iteration site AND leave neither the fetcher nor the h2d-stager
    running — an orphaned H2D thread would race the sentinel's rc-8
    drain (or a supervise.sh restart) for device memory."""

    def explode(i, hb):
        if i == 2:
            raise ValueError("bad transfer")
        return hb

    pre = DevicePrefetcher(_loader(), depth=2, assemble=explode,
                           overlap=True)
    with pytest.raises(ValueError, match="bad transfer"):
        list(pre)
    assert _gone("host-fetcher", "h2d-stager"), (
        "overlap pipeline thread still alive after assemble exception")
    # the prefetcher stays reusable: a fresh pass re-raises, not hangs
    with pytest.raises(ValueError, match="bad transfer"):
        list(pre)


def test_overlap_early_break_joins_threads_mid_transfer():
    """Generator close (the trainer loops' try/finally, a SIGTERM unwind)
    while a transfer is IN FLIGHT must drain and join both pipeline
    threads, then support a fresh full pass."""

    def slow_assemble(i, hb):
        time.sleep(0.1)
        return hb

    pre = DevicePrefetcher(_loader(n=64, batch=8), depth=1,
                           assemble=slow_assemble, overlap=True)
    for i, _ in enumerate(pre):
        if i == 1:
            break  # batch 3's transfer is mid-flight on the h2d-stager
    assert _gone("host-fetcher", "h2d-stager"), (
        "overlap pipeline thread still alive after abandoned iteration")
    assert len(list(pre)) == 8


# ---------------------------------------------------------------- trainer --

def _tiny_cfg(prefetch_depth):
    cfg = get_preset("baseline")
    cfg.data.dataset = "synthetic"
    cfg.data.image_size = 32
    cfg.data.num_classes = 4
    cfg.data.synthetic_size = 128
    cfg.data.batch_size = 32
    cfg.data.num_workers = 2
    cfg.data.device_prefetch = prefetch_depth
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.run.epochs = 1
    cfg.run.write_records = False
    cfg.run.save_every_epoch = False
    return cfg


def test_trainer_prefetch_stages_off_thread_and_matches_sync_bitwise(monkeypatch):
    """Two acceptance criteria through ONE Trainer (the compile is the cost
    here; `device_prefetch` is read per epoch, so the same trainer replays
    the same epoch from a state snapshot under both depths):

    - with device_prefetch >= 1, the per-step host time between dispatches
      no longer includes batch assembly/H2D — every make_global_array call
      in train AND eval lands on a stager thread (and with depth 0, every
      call is back inline on the consumer thread);
    - depth 0 falls back to the synchronous path bit-for-bit: identical
      epoch metrics on the synthetic dataset (the prefetcher changes WHERE
      assembly runs, never WHAT is computed)."""
    main_ident = threading.get_ident()
    idents = []
    real = meshlib.make_global_array

    def spy(batch, mesh, sharding=None):
        idents.append(threading.get_ident())
        return real(batch, mesh, sharding=sharding)

    monkeypatch.setattr(meshlib, "make_global_array", spy)

    tr = Trainer(_tiny_cfg(2))
    # deep copy: the train step DONATES the state buffers (steps.py), so an
    # alias would be invalidated by the first epoch
    state0 = jax.tree_util.tree_map(jax.numpy.copy, tr.state)
    train_pre = tr.train_epoch(0)
    eval_pre = tr.evaluate()
    assert idents, "make_global_array never called"
    assert main_ident not in idents

    # same trainer, same starting state, synchronous depth-0 replay
    idents.clear()
    tr.state = state0
    tr.cfg.data.device_prefetch = 0
    train_sync = tr.train_epoch(0)
    eval_sync = tr.evaluate()
    assert idents and set(idents) == {main_ident}

    assert train_sync == train_pre, (train_sync, train_pre)
    assert eval_sync == eval_pre, (eval_sync, eval_pre)
