"""K-step gradient accumulation: parity, amortized-comms evidence, and the
`grad-accum-indivisible` construction rejections.

The perf claim lives in the committed program baseline (the
`train_step_accum4*` cells in analysis/baselines.json: ONE data-axis
gradient reduction per optimizer step, payload flat vs the K=1 anchor
while per-microbatch reduction bytes fall ÷K, ÷2K composed with the bf16
wire). What THIS file proves:

- state-for-state parity: K=4 × mb=8 reproduces the K=1 × batch=32 run
  within f32 reduction-order noise after 3 optimizer steps — the scanned
  accumulator computes the SAME mean gradient, just in K partial sums
  (pinned on a LayerNorm model: BatchNorm's per-microbatch batch stats
  make K>1 a genuinely different — not wrong, different — program);
- the banked cells keep exhibiting the amortization the knob buys,
  so regenerating the baseline from a regressed program fails here even
  if --update-baseline banked it;
- every named rejection exits rc 2 through cli.train's config-error
  mapping (in-process, same pattern as test_recovery_rc_discipline).
"""

import json
import os

import jax
import numpy as np
import pytest

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINES = os.path.join(REPO, "ddp_classification_pytorch_tpu",
                         "analysis", "baselines.json")


def _tiny_vit_cfg(grad_accum=1):
    """LayerNorm-normalized model, dropout off: the configs where K=4 and
    K=1 are the same mathematical function (resnet BN would compute
    per-microbatch batch statistics — correct accumulation semantics,
    but not bit-comparable to the full-batch run)."""
    cfg = get_preset("baseline")
    cfg.data.image_size = 32
    cfg.data.num_classes = 4
    cfg.data.batch_size = 32
    cfg.model.arch = "vit_t16"
    cfg.model.dtype = "float32"
    cfg.model.dropout = 0.0
    cfg.parallel.grad_accum = grad_accum
    return cfg


def _dp2_mesh():
    return meshlib.make_mesh(meshlib.MeshSpec(2, 1),
                             devices=jax.devices()[:2])


def _run_steps(cfg, mesh, steps=3):
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    rng = np.random.default_rng(7)
    images = rng.normal(size=(32, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 4, 32).astype(np.int32)
    with mesh:
        model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
        step = make_train_step(cfg, model, tx, mesh=mesh)
        batch = meshlib.make_global_array((images, labels), mesh)
        losses = []
        for _ in range(steps):
            state, metrics = step(state, *batch)
            losses.append(float(metrics["loss"]))
    return losses, jax.device_get(state)


def _assert_trees_close(a, b, rtol, atol):
    la, ta = jax.tree_util.tree_flatten_with_path(a)
    lb, _ = jax.tree_util.tree_flatten_with_path(b)
    assert len(la) == len(lb)
    for (path, x), (_, y) in zip(la, lb):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=rtol, atol=atol,
            err_msg=jax.tree_util.keystr(path))


def test_accum4_matches_single_batch_state_for_state():
    """The tentpole parity pin: K=4 × mb=8 (global) and K=1 × batch=32
    run the SAME update — the scan accumulates K partial-mean gradients
    into f32 and the single deferred cross-replica mean reproduces the
    full-batch mean gradient — so after 3 optimizer steps the whole
    state (params, opt_state) agrees within f32 reduction-order noise.
    A real divergence here means the accumulator mis-weighted a
    microbatch or the deferred reduction ran on the wrong values."""
    mesh = _dp2_mesh()
    losses_k4, state_k4 = _run_steps(_tiny_vit_cfg(grad_accum=4), mesh)
    losses_k1, state_k1 = _run_steps(_tiny_vit_cfg(grad_accum=1), mesh)
    np.testing.assert_allclose(losses_k4, losses_k1, rtol=2e-4, atol=2e-4)
    _assert_trees_close(state_k4, state_k1, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_accum4_bf16_wire_tracks_f32_accum():
    """The two levers compose: K=4 with the bf16 wire quantizes only the
    ONE summed-gradient reduction (accumulator stays f32), so the run
    tracks the f32-wire K=4 run within the same one-rounding envelope
    test_bf16_grad_reduce_tracks_f32 pins for K=1. Slow-marked: two extra
    full scan-program compiles on top of the tier-1 parity pin; the
    composed cell's banked wire evidence stays tier-1 just below."""
    mesh = _dp2_mesh()
    cfg_bf = _tiny_vit_cfg(grad_accum=4)
    cfg_bf.parallel.zero_opt = "off"
    cfg_bf.parallel.grad_reduce_dtype = "bfloat16"
    cfg_f = _tiny_vit_cfg(grad_accum=4)
    cfg_f.parallel.zero_opt = "off"
    losses_bf, state_bf = _run_steps(cfg_bf, mesh)
    losses_f, state_f = _run_steps(cfg_f, mesh)
    np.testing.assert_allclose(losses_bf, losses_f, rtol=0.05, atol=0.1)
    _assert_trees_close(state_bf, state_f, rtol=0.1, atol=5e-2)


def test_banked_accum_cells_amortize_the_wire():
    """The acceptance criterion made durable on the COMMITTED baseline:
    the accumulated step's data-axis gradient reduction happens ONCE per
    optimizer step — its total all-reduce payload stays ~flat vs the K=1
    anchor (a per-microbatch reduction would bank ~K× the bytes), which
    IS the ÷K per-microbatch amortization — the bf16-wire cell halves it
    again (÷2K compound), and donation stays full everywhere."""
    programs = json.load(open(BASELINES))["programs"]
    anchor = programs["train_step@dp2"]
    acc = programs["train_step_accum4@dp2"]
    acc_tp = programs["train_step_accum4@dp2tp2"]
    acc_bf = programs["train_step_accum4_bf16@dp2"]

    ar_anchor = anchor["collectives"]["all-reduce"]["bytes"]
    ar = acc["collectives"]["all-reduce"]
    # one reduction per optimizer step: payload parity with the anchor
    # (0.95–1.05×), i.e. per-microbatch bytes = anchor ÷ 4
    assert set(ar["axes"]) == {"data"}
    assert 0.95 * ar_anchor <= ar["bytes"] <= 1.05 * ar_anchor
    # ZeRO-1 still rides the same boundary: one data-axis param
    # all-gather per optimizer step, not per microbatch
    ag = acc["collectives"]["all-gather"]
    assert set(ag["axes"]) == {"data"}
    assert ag["bytes"] <= 1.05 * anchor["collectives"]["all-gather"]["bytes"]

    # composed with the tp axis the head gather joins in, data-axis
    # payload stays amortized
    assert (acc_tp["collectives"]["all-reduce"]["axes"]["data"]
            <= 1.05 * ar_anchor)

    # bf16 wire on the SUMMED grads: ≤0.55× the f32 anchor — the ÷2K
    # compound — and it matches the K=1 bf16 cell (same wire, same bytes)
    ar_bf = acc_bf["collectives"]["all-reduce"]["bytes"]
    assert ar_bf <= 0.55 * ar_anchor
    assert ar_bf == programs["train_step_bf16@dp2"][
        "collectives"]["all-reduce"]["bytes"]
    assert "bf16" in acc_bf["wire_dtypes"]["all-reduce"]

    for key in ("train_step_accum4@dp2", "train_step_accum4@dp2tp2",
                "train_step_accum4_bf16@dp2"):
        assert programs[key]["donation_coverage"] == 1.0, key


def test_scan_rejects_ragged_microbatch_at_trace_time():
    """The meshless scan helper's own guard (the last line of defense
    behind the construction-time rejection): a batch K cannot slice
    evenly must raise, not silently re-weight the remainder."""
    import jax.numpy as jnp

    from ddp_classification_pytorch_tpu.train.steps import _scan_microbatches

    def loss_fn(params, stats, x, y, rng):
        loss = jnp.mean((x.sum(axis=(1, 2, 3)) - y) ** 2)
        return loss, (stats, jnp.zeros((x.shape[0], 4), jnp.float32))

    params = {"w": jnp.ones((2,), jnp.float32)}
    x = jnp.zeros((6, 4, 4, 3), jnp.float32)
    y = jnp.zeros((6,), jnp.float32)
    with pytest.raises(ValueError, match="grad-accum-indivisible"):
        _scan_microbatches(loss_fn, 4, params, {}, x, y,
                           jax.random.PRNGKey(0))


# ------------------------------------------------- rc-2 construction errors --

def _main_rc(argv, capsys):
    """Drive cli.train.main in-process (the suite already runs on the
    8-device CPU mesh; `--platform cpu` skips the backend probe) and
    return (exit code, stderr)."""
    from ddp_classification_pytorch_tpu.cli.train import main

    with pytest.raises(SystemExit) as exc:
        main(argv)
    return exc.value.code, capsys.readouterr().err


def test_indivisible_batch_rejection_exits_2(capsys, tmp_path):
    """--grad_accum that cannot slice the per-replica batch into equal
    microbatches is deterministic config damage → rc 2 with the named
    `grad-accum-indivisible` error, before any probe or compile."""
    rc, err = _main_rc(
        ["baseline", "--dataset", "synthetic", "--platform", "cpu",
         "-b", "8", "--grad_accum", "3", "--epochs", "1",
         "--out", str(tmp_path)], capsys)
    assert rc == 2, err[-500:]
    assert "config error" in err
    assert "grad-accum-indivisible" in err
    assert "equal microbatches" in err


def test_pipeline_compose_rejection_exits_2(capsys, tmp_path):
    """grad_accum > 1 + the pipeline schedule: two owners of the
    microbatch loop → rc 2, named, up front."""
    rc, err = _main_rc(
        ["baseline", "--dataset", "synthetic", "--model", "vit_t16",
         "--platform", "cpu", "--pp_microbatches", "2",
         "--grad_accum", "2", "--epochs", "1", "--out", str(tmp_path)],
        capsys)
    assert rc == 2, err[-500:]
    assert "config error" in err
    assert "grad-accum-indivisible" in err
    assert "pipeline" in err


def test_sharded_ce_compose_rejection_exits_2(capsys, tmp_path):
    """grad_accum > 1 + arcface_sharded_ce: the partial-FC loss is its
    own shard_map program the accumulation scan cannot slice → rc 2."""
    rc, err = _main_rc(
        ["arcface", "--dataset", "synthetic", "--platform", "cpu",
         "--mp", "2", "--sharded_ce", "--num_classes", "8", "-b", "8",
         "--grad_accum", "2", "--epochs", "1", "--out", str(tmp_path)],
        capsys)
    assert rc == 2, err[-500:]
    assert "config error" in err
    assert "grad-accum-indivisible" in err
    assert "arcface_sharded_ce" in err
