"""Explicit shard_map DDP step vs the auto-sharded jit step: same math.

Runs both on the 8-device CPU mesh from identical initial state and batch;
parameters after one step must agree to float tolerance (reduction order may
differ), proving the auto-sharded path really does compute DDP semantics.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
from ddp_classification_pytorch_tpu.parallel.collectives import (
    build_ddp_model,
    make_shard_map_train_step,
)
from ddp_classification_pytorch_tpu.train.schedule import build_optimizer
from ddp_classification_pytorch_tpu.train.state import create_train_state
from ddp_classification_pytorch_tpu.train.steps import make_train_step


def _tiny_cfg():
    cfg = get_preset("baseline")
    cfg.data.dataset = "synthetic"
    cfg.data.image_size = 16
    cfg.data.num_classes = 4
    cfg.data.batch_size = 16
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    return cfg


def test_shard_map_step_matches_auto_sharded():
    cfg = _tiny_cfg()
    mesh = meshlib.make_mesh()
    rng = np.random.default_rng(0)
    images = rng.normal(size=(16, 16, 16, 3)).astype(np.float32)
    labels = rng.integers(0, 4, 16).astype(np.int32)

    with mesh:
        # auto-sharded path
        model_a, tx_a, state_a = create_train_state(cfg, mesh, steps_per_epoch=4)
        auto_step = make_train_step(cfg, model_a, tx_a)
        ia = jax.device_put(images, meshlib.batch_sharding(mesh))
        la = jax.device_put(labels, meshlib.batch_sharding(mesh))
        state_a, metrics_a = auto_step(state_a, ia, la)

        # explicit shard_map path (axis-name BN), same init seed
        model_b = build_ddp_model(cfg)
        p_rng, d_rng = jax.random.split(jax.random.PRNGKey(cfg.run.seed))
        variables = model_b.init(  # identical init stream to create_train_state
            {"params": p_rng, "dropout": d_rng},
            jnp.zeros((2, 16, 16, 3)), train=False)
        tx_b = build_optimizer(cfg.optim, 4)
        from ddp_classification_pytorch_tpu.train.state import TrainState

        state_b = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=jax.device_put(variables["params"], meshlib.replicated(mesh)),
            batch_stats=jax.device_put(variables["batch_stats"], meshlib.replicated(mesh)),
            opt_state=jax.jit(tx_b.init)(variables["params"]),
        )
        ddp_step = make_shard_map_train_step(cfg, model_b, tx_b, mesh)
        state_b, metrics_b = ddp_step(state_b, ia, la)

    # same loss and same updated params (reduction order may differ slightly)
    assert float(metrics_a["loss"]) == pytest.approx(float(metrics_b["loss"]), rel=1e-4)
    assert float(metrics_a["top1"]) == pytest.approx(float(metrics_b["top1"]), abs=1e-6)
    pa = jax.tree_util.tree_leaves(jax.device_get(state_a.params))
    pb = jax.tree_util.tree_leaves(jax.device_get(state_b.params))
    for a, b in zip(pa, pb):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-4)
    # BN batch_stats must match too (global-batch stats == pmean'd stats)
    sa = jax.tree_util.tree_leaves(jax.device_get(state_a.batch_stats))
    sb = jax.tree_util.tree_leaves(jax.device_get(state_b.batch_stats))
    for a, b in zip(sa, sb):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-4)


def test_hybrid_mesh_two_tier_layout_and_training():
    """make_hybrid_mesh: slice-major data axis (2 'slices' x 2 DP x 2 MP on
    the virtual mesh) drives the same jitted train step unchanged."""
    import numpy as np

    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    mesh = meshlib.make_hybrid_mesh(
        meshlib.MeshSpec(4, 2), dcn_data_parallel=2)
    assert dict(mesh.shape) == {"data": 4, "model": 2}

    cfg = get_preset("baseline")
    cfg.data.dataset = "synthetic"
    cfg.data.image_size = 32
    cfg.data.num_classes = 4
    cfg.data.batch_size = 8
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    with mesh:
        model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
        step = make_train_step(cfg, model, tx)
        rng = np.random.default_rng(0)
        images = jax.device_put(
            rng.normal(size=(8, 32, 32, 3)).astype(np.float32),
            meshlib.batch_sharding(mesh))
        labels = jax.device_put(
            rng.integers(0, 4, 8).astype(np.int32),
            meshlib.batch_sharding(mesh))
        state, metrics = step(state, images, labels)
        assert np.isfinite(float(metrics["loss"]))
