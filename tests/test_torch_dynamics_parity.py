"""Torch-vs-flax TRAINING-DYNAMICS parity (VERDICT r4 next #2).

Forward parity (test_torch_oracle_parity.py) pins the weight converters; this
file pins the *step dynamics* against a torch ground-truth run with identical
init and identical batches — the places silent accuracy drift hides
(SURVEY §7.3 #3):

- BN running-stat updates: torch momentum 0.1 == flax momentum 0.9
  (models/resnet.py), training-mode normalization by batch stats;
- SGD coupling order: torch's ``d_p = g + wd*p; buf = m*buf + d_p;
  p -= lr*buf`` vs our ``chain(add_decayed_weights, sgd(momentum))``
  (train/schedule.py::_group_tx);
- the warmup-vs-decay overlay: per-iteration linear warmup while the
  epoch-indexed decay keeps counting from step 0 (reference
  BASELINE/main.py:170-197 ``WarmUp`` + StepLR at :154; our
  build_schedule overlays rather than shifting);
- NESTED freeze-BN: BN modules eval()'d with weight/bias grads off
  (NESTED/model/model.py:44-55) vs our use_running_average +
  optax.masked(set_to_zero).

The flax side runs the PRODUCTION path end to end: the torch oracle's
state_dict is torch.save'd and loaded through ``cfg.model.pretrained_path``
(create_train_state → load_torch_checkpoint → converter → merge), the step
is ``make_train_step`` over the 8-device CPU mesh with a sharded global
batch, and the optimizer is ``build_optimizer``. The torch side replays the
reference recipe literally.

Two tiers, because cross-backend f32 determinism sets a noise floor:

1. ``test_optimizer_coupling_matches_torch_sgd`` feeds IDENTICAL fixed
   gradients to the real ``build_optimizer`` chain and to ``torch.optim.SGD``
   — elementwise arithmetic only, no reductions, so both sides perform the
   same IEEE ops and any wd-coupling-order, momentum-buffer-init, or
   schedule-indexing difference fails at ~1e-6.
2. The full-model tests run real conv nets, where torch-CPU and XLA-CPU
   reduction orders differ at ~1e-6 per step and training amplifies that
   ~40x/step (measured: losses agree 7e-7 at step 0, 2.5e-3 by step 5).
   Their tolerances are therefore SEMANTIC-level (2e-2): they catch a BN
   momentum-convention swap (~9x running-stat error), a wrong lr actually
   applied (warmup/decay overlay), train-vs-eval BN mode mixups, and
   unfrozen freeze-BN — while the subtle couplings are pinned exactly by
   tier 1.

Known, accepted divergence: torch updates running_var with the UNBIASED
batch variance (Bessel n/(n-1)); flax uses the biased one. At the test's
smallest BN reduction (n = 16·32·32 = 16384) that is a 6e-5 relative drift
per step — far inside the tolerances here, and negligible at real batch
sizes.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.models.import_torch import (
    convert_resnet_state_dict,
)
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
from ddp_classification_pytorch_tpu.train.state import create_train_state
from ddp_classification_pytorch_tpu.train.steps import make_train_step

torch = pytest.importorskip("torch")

from torch_resnet_oracle import make_torch_resnet, randomize_  # noqa: E402

N_STEPS = 6
BATCH = 16
CLASSES = 7
SIZE = 64
LR = 0.01
WD = 5e-4
GAMMA = 0.1
WARMUP_ITERS = 3
WARMUP_START = 1e-6
STEPS_PER_EPOCH = 2  # decay fires mid-run: overlay semantics get exercised


def _reference_lr(i: int) -> float:
    """The reference's lr at 0-indexed iteration i: linear warmup
    (BASELINE/main.py:179 ``lr = begin + n_iter*(target-begin)/iter``),
    then StepLR counting epochs from 0 (NOT from warmup's end — the decay
    milestones stay anchored at the true global step, train/schedule.py)."""
    if i < WARMUP_ITERS:
        return WARMUP_START + i * (LR - WARMUP_START) / WARMUP_ITERS
    return LR * GAMMA ** (i // STEPS_PER_EPOCH)


def _batches(seed: int):
    rng = np.random.default_rng(seed)
    xs = rng.normal(size=(N_STEPS, BATCH, 3, SIZE, SIZE)).astype(np.float32)
    ys = rng.integers(0, CLASSES, size=(N_STEPS, BATCH)).astype(np.int64)
    return xs, ys


def _cfg(pth_path: str, freeze_bn: bool):
    cfg = get_preset("baseline")
    cfg.data.dataset = "synthetic"
    cfg.data.image_size = SIZE
    cfg.data.num_classes = CLASSES
    cfg.data.batch_size = BATCH
    cfg.model.arch = "resnet18"
    cfg.model.variant = "imagenet"  # the oracle/converter stem
    cfg.model.dtype = "float32"
    cfg.model.freeze_bn = freeze_bn
    cfg.model.pretrained = True
    cfg.model.pretrained_path = pth_path
    cfg.optim.optimizer = "sgd"
    cfg.optim.lr = LR
    cfg.optim.momentum = 0.9
    cfg.optim.weight_decay = WD
    cfg.optim.schedule = "step"
    cfg.optim.step_size = 1  # in epochs; STEPS_PER_EPOCH makes it per-2-steps
    cfg.optim.gamma = GAMMA
    cfg.optim.warmup_iters = WARMUP_ITERS
    cfg.optim.warmup_start_lr = WARMUP_START
    return cfg


def _run_flax(cfg, xs, ys):
    mesh = meshlib.make_mesh(meshlib.MeshSpec())  # all devices on 'data'
    model, tx, state = create_train_state(cfg, mesh, STEPS_PER_EPOCH)
    step = make_train_step(cfg, model, tx)
    losses = []
    for i in range(N_STEPS):
        imgs = jnp.asarray(xs[i].transpose(0, 2, 3, 1))
        state, metrics = step(state, imgs, jnp.asarray(ys[i], jnp.int32))
        losses.append(float(metrics["loss"]))
    return losses, state


def _run_torch(sd, xs, ys, freeze_bn: bool):
    tmodel = make_torch_resnet("resnet18", CLASSES)
    tmodel.load_state_dict(sd)
    tmodel.train()
    if freeze_bn:
        # the NESTED recipe verbatim (NESTED/model/model.py:44-55)
        for m in tmodel.modules():
            if isinstance(m, torch.nn.BatchNorm2d):
                m.eval()
                m.weight.requires_grad = False
                m.bias.requires_grad = False
    opt = torch.optim.SGD(tmodel.parameters(), lr=LR, momentum=0.9,
                          weight_decay=WD)
    lossf = torch.nn.CrossEntropyLoss()
    losses = []
    for i in range(N_STEPS):
        opt.param_groups[0]["lr"] = _reference_lr(i)
        opt.zero_grad()
        out = tmodel(torch.from_numpy(xs[i]))
        loss = lossf(out, torch.from_numpy(ys[i]))
        loss.backward()
        opt.step()
        losses.append(float(loss.detach()))
    return losses, tmodel


def _tree_flat(tree):
    return {
        "/".join(str(getattr(k, "key", k)) for k in path): np.asarray(v)
        for path, v in jax.tree_util.tree_leaves_with_path(tree)
    }


def _assert_trees_close(flax_tree, torch_tree, rtol, atol, what):
    got = _tree_flat(flax_tree)
    want = _tree_flat(torch_tree)
    assert got.keys() == want.keys(), (what, got.keys() ^ want.keys())
    for k in sorted(got):
        np.testing.assert_allclose(
            got[k], want[k], rtol=rtol, atol=atol,
            err_msg=f"{what}: {k} diverged after {N_STEPS} steps")


def _converted_after(tmodel):
    """Torch's post-training weights, pushed through the SAME converter the
    init crossed — any coupling/momentum/BN drift shows up as a tree diff."""
    return convert_resnet_state_dict(tmodel.state_dict())


@pytest.fixture(scope="module")
def oracle_pth(tmp_path_factory):
    tmodel = make_torch_resnet("resnet18", CLASSES)
    randomize_(tmodel, seed=11)
    path = tmp_path_factory.mktemp("dyn") / "oracle_rn18.pth"
    torch.save(tmodel.state_dict(), str(path))
    return str(path), tmodel.state_dict()


def test_optimizer_coupling_matches_torch_sgd():
    """The production optimizer chain (build_optimizer: warmup-overlaid step
    schedule → add_decayed_weights → momentum trace → -lr) vs torch SGD fed
    the SAME fixed gradients. Pure elementwise arithmetic — both sides run
    the identical IEEE op sequence, so coupling-order / buffer-init /
    schedule-off-by-one bugs fail at near-ulp tolerance."""
    from ddp_classification_pytorch_tpu.train.schedule import build_optimizer

    cfg = _cfg("/dev/null", freeze_bn=False).optim
    tx = build_optimizer(cfg, STEPS_PER_EPOCH)

    rng = np.random.default_rng(7)
    p0 = {"w": rng.normal(size=(5, 3)).astype(np.float32),
          "b": rng.normal(size=(3,)).astype(np.float32)}
    grads = [
        {"w": rng.normal(size=(5, 3)).astype(np.float32),
         "b": rng.normal(size=(3,)).astype(np.float32)}
        for _ in range(N_STEPS)
    ]

    import optax

    fparams = jax.tree_util.tree_map(jnp.asarray, p0)
    opt_state = tx.init(fparams)
    for g in grads:
        updates, opt_state = tx.update(
            jax.tree_util.tree_map(jnp.asarray, g), opt_state, fparams)
        fparams = optax.apply_updates(fparams, updates)

    tparams = {k: torch.nn.Parameter(torch.from_numpy(v.copy()))
               for k, v in p0.items()}
    opt = torch.optim.SGD(tparams.values(), lr=LR, momentum=0.9,
                          weight_decay=WD)
    for i, g in enumerate(grads):
        opt.param_groups[0]["lr"] = _reference_lr(i)
        for k in tparams:
            tparams[k].grad = torch.from_numpy(g[k].copy())
        opt.step()

    for k in p0:
        np.testing.assert_allclose(
            np.asarray(fparams[k]), tparams[k].detach().numpy(),
            rtol=1e-6, atol=1e-7,
            err_msg=f"optimizer coupling diverged on {k!r}")


def test_sgd_bn_warmup_dynamics_match_torch(oracle_pth):
    path, sd = oracle_pth
    xs, ys = _batches(21)
    flax_losses, state = _run_flax(_cfg(path, freeze_bn=False), xs, ys)
    torch_losses, tmodel = _run_torch(sd, xs, ys, freeze_bn=False)

    # per-step loss trajectory: pins training-mode BN normalization + the
    # lr actually applied each iteration (warmup AND the step-2/4 decays);
    # tolerance is the measured chaos floor x margin (see module docstring)
    np.testing.assert_allclose(flax_losses, torch_losses, rtol=2e-2,
                               err_msg=f"{flax_losses} vs {torch_losses}")
    # the first warmup step happens before any drift can amplify: a wrong
    # warmup start lr or a train/eval BN mixup shows here at f32 precision
    np.testing.assert_allclose(flax_losses[0], torch_losses[0], rtol=1e-4)

    converted = _converted_after(tmodel)
    _assert_trees_close(state.params["backbone"], converted["params"],
                        rtol=2e-2, atol=1e-3, what="params")
    # running stats: the running mean tracks the drifting activations, so
    # its absolute floor is higher (measured 7e-3 after 6 steps) — still
    # far below the ~0.5-scale error a 0.1-vs-0.9 momentum mixup produces
    _assert_trees_close(state.batch_stats["backbone"],
                        converted["batch_stats"],
                        rtol=2e-2, atol=2e-2, what="batch_stats")


def test_freeze_bn_dynamics_match_torch(oracle_pth):
    """NESTED's freeze-BN: running stats AND BN scale/bias must stay at
    their init values on both sides while everything else trains."""
    path, sd = oracle_pth
    xs, ys = _batches(22)
    flax_losses, state = _run_flax(_cfg(path, freeze_bn=True), xs, ys)
    torch_losses, tmodel = _run_torch(sd, xs, ys, freeze_bn=True)

    np.testing.assert_allclose(flax_losses, torch_losses, rtol=2e-2)
    np.testing.assert_allclose(flax_losses[0], torch_losses[0], rtol=1e-4)

    init_converted = convert_resnet_state_dict(sd)
    _assert_trees_close(state.batch_stats["backbone"],
                        init_converted["batch_stats"],
                        rtol=0, atol=0, what="frozen running stats (flax)")
    after = _converted_after(tmodel)
    _assert_trees_close(after["batch_stats"],
                        init_converted["batch_stats"],
                        rtol=0, atol=0, what="frozen running stats (torch)")
    _assert_trees_close(state.params["backbone"], after["params"],
                        rtol=2e-2, atol=1e-3, what="params under freeze_bn")
