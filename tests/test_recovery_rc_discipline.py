"""Behavioral tests for the recovery chain's exit-code discipline.

Round-3 advisor (medium): rc=1 used to mean BOTH a deterministic config
error and any unhandled runtime exception, so `supervise.sh` stopped the
whole chain on transient crashes (a tunneled XlaRuntimeError, in-process
OOM, dataloader IO) that `--auto_resume` exists to absorb. The contract
now is:

- rc 2 — deterministic config/usage error (argparse uses 2; the trainer
  maps its own config validation to SystemExit(2) BEFORE any backend
  probe). supervise.sh stops immediately: restarting replays the bug.
- bare rc 1 — unhandled runtime exception. Retryable with
  ``RUNTIME_BACKOFF_S`` backoff (default 30 s).
- rc 3 — backend unreachable, long ``OUTAGE_BACKOFF_S`` backoff.

`window_catcher.sh` (advisor low): a failing PROBE is only retried when
the failure is outage-shaped (timeout / "backend unreachable"); a broken
venv (ImportError, rc 126/127) stops the catcher loudly instead of
polling every 10 minutes forever.

The supervise/catcher tests drive the real scripts with a stub `python`
on PATH whose per-call exit codes come from ``FAKE_RCS`` — no backend,
no sleeps (backoffs are env-zeroed), so the suite stays fast.
"""

import os
import stat
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STUB = """#!/usr/bin/env bash
state="${FAKE_STATE:?}"
n=$(cat "$state" 2>/dev/null || echo 0)
n=$((n+1)); echo "$n" > "$state"
[ -n "${FAKE_STDOUT:-}" ] && echo "$FAKE_STDOUT"
rc=$(echo "${FAKE_RCS:?}" | tr ',' '\\n' | sed -n "${n}p")
[ -z "$rc" ] && rc=$(echo "$FAKE_RCS" | tr ',' '\\n' | tail -1)
exit "$rc"
"""


def _stub_env(tmp_path, rcs, stdout=""):
    fakebin = tmp_path / "bin"
    fakebin.mkdir(exist_ok=True)
    stub = fakebin / "python"
    stub.write_text(STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IXUSR)
    env = dict(os.environ)
    env["PATH"] = f"{fakebin}:{env['PATH']}"
    env["FAKE_STATE"] = str(tmp_path / "calls")
    env["FAKE_RCS"] = rcs
    if stdout:
        env["FAKE_STDOUT"] = stdout
    return env


def _calls(tmp_path):
    return int((tmp_path / "calls").read_text())


def test_supervise_retries_runtime_rc1(tmp_path):
    """A transient runtime crash (bare rc 1) restarts with backoff."""
    env = _stub_env(tmp_path, "1,0")
    env["RUNTIME_BACKOFF_S"] = "0"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"), "baseline"],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 0, p.stderr
    assert _calls(tmp_path) == 2, "rc=1 must be retried, then succeed"
    assert "restart 1/" in p.stderr


def test_supervise_stops_on_config_rc2(tmp_path):
    """A deterministic config/usage error must NOT be retried."""
    env = _stub_env(tmp_path, "2,0")
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"), "baseline"],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 2, (p.returncode, p.stderr)
    assert _calls(tmp_path) == 1, "rc=2 must stop without a restart"


def test_supervise_gives_up_after_max_restarts(tmp_path):
    env = _stub_env(tmp_path, "1,1,1")
    env["RUNTIME_BACKOFF_S"] = "0"
    env["MAX_RESTARTS"] = "2"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"), "baseline"],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 1
    assert _calls(tmp_path) == 3  # initial + 2 restarts
    assert "giving up" in p.stderr


def test_trainer_config_error_exits_2():
    """Config validation exits 2 before any probe/backend work (and argparse
    usage errors already exit 2), so supervisors see one deterministic code."""
    p = subprocess.run(
        [sys.executable, "-m", "ddp_classification_pytorch_tpu.cli.train",
         "baseline", "--folder", "/tmp/nonexistent",
         "--moe_experts", "4", "--moe_aux_weight", "-1"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 2, (p.returncode, p.stderr[-500:])
    assert "config error" in p.stderr


def test_trainer_construction_config_error_exits_2():
    """Config-shaped ValueErrors raised during Trainer construction (here:
    MeshSpec.resolve "mesh does not cover N devices" for a --dp that doesn't
    divide the device count) must ALSO map to rc 2 — a bare rc 1 would make
    supervise.sh replay the deterministic bug MAX_RESTARTS times (ADVICE r4)."""
    p = subprocess.run(
        [sys.executable, "-m", "ddp_classification_pytorch_tpu.cli.train",
         "baseline", "--dataset", "synthetic", "--dp", "3", "--epochs", "1"],
        cwd=REPO, capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert p.returncode == 2, (p.returncode, p.stderr[-500:])
    assert "config error" in p.stderr
    assert "does not cover" in p.stderr


def _main_rc(argv, capsys):
    """Drive cli.train.main in-process (the suite already runs on the
    8-device CPU mesh, and `--platform cpu` skips the backend probe) and
    return (exit code, stderr) — each construction-time case costs one
    Trainer build attempt, not a fresh interpreter + jax import."""
    import pytest

    from ddp_classification_pytorch_tpu.cli.train import main

    with pytest.raises(SystemExit) as exc:
        main(argv)
    return exc.value.code, capsys.readouterr().err


def test_pipeline_arch_rejection_exits_2(capsys, tmp_path):
    """build_model's pipeline rejection (--pp_microbatches on a non-ViT
    arch) is config-shaped and deterministic → rc 2, not a bare rc 1
    supervise.sh would replay with backoff (ADVICE r4)."""
    rc, err = _main_rc(
        ["baseline", "--dataset", "synthetic", "--platform", "cpu",
         "--pp_microbatches", "2", "--epochs", "1",
         "--out", str(tmp_path)], capsys)
    assert rc == 2, err[-500:]
    assert "config error" in err
    assert "requires a ViT" in err


def test_pipeline_head_rejection_exits_2(capsys, tmp_path):
    """build_model's pipeline HEAD rejection (--pp_microbatches supports
    fc/arcface only; the nested preset's head is 'nested') is config-shaped
    and deterministic → rc 2 (ADVICE r4: the remaining named construction
    errors all map like the arch rejection above)."""
    rc, err = _main_rc(
        ["nested", "--dataset", "synthetic", "--model", "vit_t16",
         "--platform", "cpu", "--pp_microbatches", "2", "--epochs", "1",
         "--out", str(tmp_path)], capsys)
    assert rc == 2, err[-500:]
    assert "config error" in err
    assert "supports head=" in err


def test_pipeline_dropout_rejection_exits_2(capsys, tmp_path):
    """build_model's pipeline DROPOUT rejection (the tick loop carries no
    per-tick rng) must exit 2 from Trainer construction too."""
    rc, err = _main_rc(
        ["baseline", "--dataset", "synthetic", "--model", "vit_t16",
         "--dropout", "0.1", "--platform", "cpu", "--pp_microbatches", "2",
         "--epochs", "1", "--out", str(tmp_path)], capsys)
    assert rc == 2, err[-500:]
    assert "config error" in err
    assert "does not support dropout" in err


def test_hybrid_dcn_plus_pp_rejection_exits_2(capsys, tmp_path):
    """make_hybrid_mesh's dcn+pp rejection (the hybrid mesh is two-axis)
    must exit 2 from Trainer construction too."""
    rc, err = _main_rc(
        ["baseline", "--dataset", "synthetic", "--platform", "cpu",
         "--dcn_slices", "2", "--pp_microbatches", "2", "--pp_stages", "2",
         "--epochs", "1", "--out", str(tmp_path)], capsys)
    assert rc == 2, err[-500:]
    assert "config error" in err
    assert "does not compose" in err


def test_malformed_fleet_env_exits_2(capsys, tmp_path, monkeypatch):
    """A malformed FLEET_* launch env is deterministic — every restart
    replays the same bad value — so it must exit rc 2 with the offending
    key NAMED, not dissolve into rc 6 rendezvous retries."""
    monkeypatch.setenv("FLEET_COORDINATOR", "localhost:12345")
    monkeypatch.setenv("FLEET_NUM_PROCESSES", "two")
    monkeypatch.setenv("FLEET_PROCESS_ID", "0")
    rc, err = _main_rc(
        ["baseline", "--dataset", "synthetic", "--platform", "cpu",
         "--multihost", "--epochs", "1", "--out", str(tmp_path)], capsys)
    assert rc == 2, err[-500:]
    assert "config error" in err
    assert "FLEET_NUM_PROCESSES" in err


def test_fleet_coordinator_without_port_exits_2(capsys, tmp_path, monkeypatch):
    monkeypatch.setenv("FLEET_COORDINATOR", "localhost")
    monkeypatch.setenv("FLEET_NUM_PROCESSES", "2")
    monkeypatch.setenv("FLEET_PROCESS_ID", "0")
    rc, err = _main_rc(
        ["baseline", "--dataset", "synthetic", "--platform", "cpu",
         "--multihost", "--epochs", "1", "--out", str(tmp_path)], capsys)
    assert rc == 2, err[-500:]
    assert "config error" in err
    assert "host:port" in err


def test_catcher_stops_loudly_on_broken_probe(tmp_path):
    """rc 127 (missing interpreter) / ImportError is a broken harness, not an
    outage — the catcher must stop with that rc, not poll forever."""
    env = _stub_env(tmp_path, "127",
                    stdout="bash: python3: command not found")
    env["CATCHER_OUT"] = str(tmp_path / "out")
    env["DOWN_POLL_S"] = "0"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "window_catcher.sh")],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 127, (p.returncode, p.stderr)
    log = (tmp_path / "out" / "catcher.log").read_text()
    # "command not found" hits the broken-harness signature grep; a bare
    # unexplained rc would hit the "not outage-shaped" fallback — both stop
    assert "broken-harness signature" in log or "not outage-shaped" in log
    assert _calls(tmp_path) == 1


def test_catcher_stops_when_unreachable_wraps_import_error(tmp_path):
    """require_backend wraps the probe subprocess's stderr into its 'backend
    unreachable' message, so a venv whose `import jax` dies reads as BOTH
    outage and broken harness — the broken-harness signature must win."""
    env = _stub_env(
        tmp_path, "1",
        stdout=("RuntimeError: JAX backend unreachable after 1 probes "
                "(CalledProcessError: ModuleNotFoundError: "
                "No module named 'jax')"))
    env["CATCHER_OUT"] = str(tmp_path / "out")
    env["DOWN_POLL_S"] = "0"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "window_catcher.sh")],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 1, (p.returncode, p.stderr)
    log = (tmp_path / "out" / "catcher.log").read_text()
    assert "broken-harness signature" in log
    assert _calls(tmp_path) == 1


def test_catcher_retries_outage_shaped_probe(tmp_path):
    """A probe that times out / reports "backend unreachable" keeps polling —
    bounded here by killing the catcher after a few cycles."""
    env = _stub_env(
        tmp_path, "1",  # stub repeats its last rc forever
        stdout="RuntimeError: JAX backend unreachable after 1 probes")
    env["CATCHER_OUT"] = str(tmp_path / "out")
    env["DOWN_POLL_S"] = "0"
    try:
        subprocess.run(
            ["bash", os.path.join(REPO, "scripts", "window_catcher.sh")],
            env=env, capture_output=True, text=True, timeout=3)
        raise AssertionError("catcher stopped on an outage-shaped probe")
    except subprocess.TimeoutExpired:
        pass  # still polling — the desired behavior
    log = (tmp_path / "out" / "catcher.log").read_text()
    assert "down at" in log
    assert _calls(tmp_path) >= 2
