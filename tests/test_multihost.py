"""True multi-process "multi-host" validation on CPU.

The reference's multi-node story is broken by construction (local rank used
as global rank — SURVEY §2.2); this framework's `--multihost` path is
`jax.distributed.initialize()` + per-host data sharding. Here we actually
RUN it: two OS processes, one virtual CPU device each, joined into one
2-device platform (gloo standing in for DCN — see multihost_worker.py for
why one device per process on this jaxlib), driving the real mesh /
global-array / train-step path. The per-step losses must match a
single-process 8-device run of the identical global batch — distribution
must change where shards live, never the math (the oracle and the workers
deliberately run DIFFERENT topologies).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_run_matches_single_process():
    """Fixed in the pod-fault-tolerance PR (three stacked root causes: the
    multi-process CPU client had no cross-host collectives implementation;
    jaxlib 0.4.37's gloo aborts on the concurrent collectives >1 local
    device issues; the workers drew different init params than the
    conftest-pinned oracle without jax_threefry_partitionable). It now
    PASSES but costs ~6 min of wall clock — three full resnet18 compiles
    in each of three processes — and the tier-1 suite is timeout-bound
    (DOTS_PASSED at the cutoff is the budget), so it runs in the slow lane
    next to the pod chaos drill that builds on it."""
    import jax

    from multihost_common import run_composed_steps, run_steps

    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib

    port = _free_port()
    out = os.path.join(os.environ.get("TMPDIR", "/tmp"),
                       f"multihost_{port}.json")
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "multihost_worker.py"),
             str(pid), "2", str(port), out],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in range(2)
    ]
    try:
        # oracle runs WHILE the workers initialize/compile — it shares no
        # state with them, and overlapping the two JAX startups roughly
        # halves the test's wall-clock
        oracle = run_steps(meshlib.make_mesh(), host_rows=slice(0, 16))
        oracle_composed = run_composed_steps(host_rows=slice(0, 16))
        logs = [p.communicate(timeout=540)[0].decode() for p in procs]
        for p, log in zip(procs, logs):
            assert p.returncode == 0, f"worker failed:\n{log}"
        with open(out) as f:
            payload = json.load(f)
        losses = payload["losses"]
        composed = payload["composed"]
        # TP-sharded checkpoint round-trip across the process boundary
        # (shards not addressable from host 0) must preserve the weights
        assert payload["ckpt_ok"] is True
    finally:
        for p in procs:  # no leaked workers pinned at the gloo barrier
            if p.poll() is None:
                p.kill()
        if os.path.exists(out):
            os.remove(out)
    # tolerance: the workers run 2 devices, the oracle 8 — partial sums
    # reduce in a different order, and the f32 drift compounds per step
    # (observed ~7e-5 by step 3); a real divergence (e.g. mismatched rng
    # config) shows up as ~3e-1, three orders louder than this bound
    np.testing.assert_allclose(losses, oracle, rtol=2e-4, atol=2e-4)
    # composed dp×tp (class-sharded partial-FC CE) with the TP pair across
    # the process boundary (1×2) vs the single-process 4×2 oracle: same
    # math on a third topology
    np.testing.assert_allclose(composed, oracle_composed, rtol=2e-4, atol=2e-4)
    # the parent's own backend must be unaffected
    assert jax.process_count() == 1
