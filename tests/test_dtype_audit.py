"""Dtype-flow auditor (analysis/dtype_audit.py) — the numerics contracts.

Same two halves as test_analysis.py, per the acceptance contract:

1. **Every D1–D6 detector must trip on a known-bad sample** — an f64 leak,
   a bf16 master-weight / optimizer hop, a bf16 dot without f32
   accumulation, a large bf16 reduction, a bf16 softmax, an undeclared
   bf16 collective, a no-op round-trip cast chain, an int→bf16 label
   downcast. Fixtures are 3-line traces, milliseconds each.

2. **The real repo passes** — a module-scoped audit of a lean cell subset
   (the f32 train step, the shipped-bf16 train/serve cells, the composed
   bf16-wire cell, the declared `--ln_bf16` cell), asserted clean AND
   matching the committed `dtype_programs` baseline; the full 19-cell
   matrix runs slow-marked and in scripts/lint.sh.

Plus the parity pins for the real findings this auditor caught and this
PR fixed (the f32→bf16→f32 pool/LN seams in resnet and vit): the fixed
seam must sit within 2e-4 of the all-f32 seam reference while the OLD
recipe must NOT — proving both the fix and that the pin bites.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddp_classification_pytorch_tpu.analysis import baseline as baselib
from ddp_classification_pytorch_tpu.analysis.dtype_audit import (
    REDUCE_ELEMS,
    WAIVER_BF16_REDUCE,
    WAIVER_BF16_SOFTMAX,
    WAIVER_BF16_TRUNK,
    WAIVER_BF16_WIRE,
    WAIVER_LN_BF16,
    WAIVER_REASONS,
    audit_dtype_registry,
    audit_program,
    diff_dtype_baseline,
    dtype_registry,
    step_dtype_evidence,
)
from ddp_classification_pytorch_tpu.analysis.jaxpr_audit import AuditContext
from ddp_classification_pytorch_tpu.analysis.lint import (
    lint_jit_sites,
    lint_jit_source,
)
from ddp_classification_pytorch_tpu.analysis.sharding_audit import (
    audit_wire_dtypes,
    collective_wire_dtypes,
)
from ddp_classification_pytorch_tpu.utils.compat import shard_map_unchecked

# --------------------------------------------------------------- fixtures --

# the tier-1-lean cell subset: one f32 cell (D2 on the pinned audit
# config), the shipped bf16 compute cells (train + the serve softmax
# customer), the two-lever composition, and the declared --ln_bf16 cell
_LEAN_CELLS = {
    "train_step",
    "train_step#bf16",
    "topk_predict_serve#bf16",
    "train_step_bf16_reduce#bf16",
    "vit_eval#ln_bf16",
}


@pytest.fixture(scope="module")
def dtype_audit():
    """The one expensive piece in this file: two extra state inits (bf16
    resnet, bf16 vit) + jaxpr traces — no compiles. Shared by every
    real-repo assertion below."""
    from types import SimpleNamespace

    ctx = AuditContext()
    cases = [c for c in dtype_registry() if c.name in _LEAN_CELLS]
    findings, records = audit_dtype_registry(ctx, cases=cases)
    return SimpleNamespace(ctx=ctx, findings=findings, records=records)


# ------------------------------------------------- detectors must trip --


def test_d1_fires_on_f64_aval():
    """A NumPy f64 scalar leaking into a jit under x64 must be caught at
    the aval level, not discovered as a TPU-vs-CPU parity break."""
    with jax.experimental.enable_x64():
        findings, _ = audit_program(lambda x: x * 2.0,
                                    (np.zeros((4,), np.float64),))
    assert any(f.check == "dtype-f64" for f in findings)


def test_d2_fires_on_bf16_master_leaf():
    """A bf16 leaf under a params path breaks the master-weights invariant
    on BOTH sides of the step (input and output directions report)."""
    state = {"params": {"w": jnp.zeros((4,), jnp.bfloat16)}}
    findings, _ = audit_program(lambda s: s, (state,), train=True)
    dirs = {f.evidence["direction"] for f in findings
            if f.check == "dtype-master"}
    assert dirs == {"input", "output"}


def test_d2_fires_on_bf16_optimizer_update():
    """An optimizer update that dips through bf16 produces the opt_state
    output from a sub-f32 eqn — the classic silent-divergence regression."""
    state = {"opt_state": {"mu": jnp.zeros((4,), jnp.float32)}}

    def fn(s):
        mu = s["opt_state"]["mu"].astype(jnp.bfloat16) * 0.9
        return {"opt_state": {"mu": mu.astype(jnp.float32)}}

    findings, _ = audit_program(fn, (state,), train=True)
    assert any(f.check == "dtype-master" and "produced by" in f.message
               for f in findings)


def test_d2_clean_on_f32_update():
    state = {"opt_state": {"mu": jnp.zeros((4,), jnp.float32)},
             "params": {"w": jnp.zeros((4,), jnp.float32)}}
    findings, _ = audit_program(
        lambda s: jax.tree_util.tree_map(lambda x: x * 0.9, s),
        (state,), train=True)
    assert not findings


def test_d3_fires_on_bf16_dot_without_f32_accum():
    a = jnp.zeros((8, 8), jnp.bfloat16)
    findings, summary = audit_program(lambda a, b: a @ b, (a, a))
    assert any(f.check == "dtype-accum" for f in findings)
    assert summary["accum"]["dot_general"]["sub_f32"] == 1

    # the declared-trunk waiver admits it (and banks it in the summary)
    waived, _ = audit_program(lambda a, b: a @ b, (a, a),
                              waivers=frozenset({WAIVER_BF16_TRUNK}))
    assert not waived

    # preferred_element_type=f32 is clean WITHOUT any waiver
    f32acc, s2 = audit_program(
        lambda a, b: jax.lax.dot(a, b, preferred_element_type=jnp.float32),
        (a, a))
    assert not f32acc
    assert s2["accum"]["dot_general"]["f32_accum"] == 1


def test_d3_fires_on_large_bf16_reduction():
    # the raw reduce_sum primitive keeps the operand dtype (jnp.sum
    # upcasts f16/bf16 to f32 internally — which is WHY the repo audits
    # clean); code reaching for lax directly is what this detector guards
    def raw_sum(x):
        return jax.lax.reduce_sum_p.bind(x, axes=(0,))

    x = jnp.zeros((2 * REDUCE_ELEMS,), jnp.bfloat16)
    findings, summary = audit_program(raw_sum, (x,))
    assert any(f.check == "dtype-accum" and "folds" in f.message
               for f in findings)
    assert summary["large_reductions"]["sub_f32"] == 1

    # explicit f32 accumulation is clean; so is the declared waiver —
    # and ln_bf16 IMPLIES bf16_reduce (the LN-at-width story)
    assert not audit_program(lambda x: jnp.sum(x, dtype=jnp.float32), (x,))[0]
    for w in (WAIVER_BF16_REDUCE, WAIVER_LN_BF16):
        assert not audit_program(raw_sum, (x,), waivers=frozenset({w}))[0]


def test_d3_small_reduction_is_in_family():
    """A LayerNorm-sized fold (hidden dim ≪ REDUCE_ELEMS) is the recipe's
    accepted rounding, not a finding."""
    x = jnp.zeros((8, 192), jnp.bfloat16)
    findings, _ = audit_program(lambda x: jnp.sum(x, axis=-1), (x,))
    assert not findings


def test_d4_fires_on_bf16_softmax():
    x = jnp.zeros((4, 16), jnp.bfloat16)
    findings, summary = audit_program(jax.nn.softmax, (x,))
    assert any(f.check == "dtype-loss-head" for f in findings)
    assert summary["exp_log_sub_f32"] >= 1
    assert not audit_program(jax.nn.softmax, (x,),
                             waivers=frozenset({WAIVER_BF16_SOFTMAX}))[0]
    assert not audit_program(jax.nn.softmax,
                             (x.astype(jnp.float32),))[0]


def test_d5_fires_on_undeclared_bf16_collective():
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("d",))
    P = jax.sharding.PartitionSpec
    fn = shard_map_unchecked(lambda x: jax.lax.psum(x, "d"),
                             mesh=mesh, in_specs=P("d"), out_specs=P())
    x = jnp.zeros((2, 4), jnp.bfloat16)
    findings, summary = audit_program(fn, (x,))
    assert any(f.check == "dtype-wire" for f in findings)
    assert summary["collective_dtypes"] == ["bfloat16"]
    assert not audit_program(fn, (x,),
                             waivers=frozenset({WAIVER_BF16_WIRE}))[0]


def test_d6_fires_on_roundtrip_cast_chain():
    x = jnp.zeros((4,), jnp.float32)
    findings, summary = audit_program(
        lambda x: x.astype(jnp.bfloat16).astype(jnp.float32) + 1.0, (x,))
    assert any(f.check == "dtype-cast" and "round-trip" in f.message
               for f in findings)
    assert summary["cast_roundtrips"] == 1

    # compute between the casts makes it a REAL precision seam, not a
    # no-op — and that is the trunk's business, not D6's
    clean, _ = audit_program(
        lambda x: (x.astype(jnp.bfloat16) * 2).astype(jnp.float32), (x,))
    assert not [f for f in clean if f.check == "dtype-cast"]


def test_d6_fires_on_label_downcast():
    labels = jnp.zeros((8,), jnp.int32)
    findings, _ = audit_program(lambda i: i.astype(jnp.bfloat16), (labels,))
    assert any(f.check == "dtype-cast" and "label" in f.message
               for f in findings)


def test_unknown_waiver_token_is_an_error():
    with pytest.raises(ValueError, match="undeclared waiver"):
        audit_program(lambda x: x, (jnp.zeros(2),),
                      waivers=frozenset({"bogus_token"}))


def test_waiver_catalogue_is_documented():
    """Every waiver token must carry a reviewed reason AND appear in the
    docs' waiver table — an undocumented waiver cannot land silently."""
    docs = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "analysis.md")
    with open(docs) as f:
        text = f.read()
    for token, reason in WAIVER_REASONS.items():
        assert reason.strip(), token
        assert f"`{token}`" in text, (
            f"waiver `{token}` missing from docs/analysis.md")


# ----------------------------------------------------- baseline drift --


def _rec(**over):
    rec = {
        "n_eqns": 10,
        "casts": {"float32->bfloat16": 4, "bfloat16->float32": 4},
        "cast_roundtrips": 0,
        "bf16_op_fraction": 1.0,
        "accum": {"dot_general": {"sub_f32": 2, "f32_accum": 1, "f32": 0},
                  "conv": {"sub_f32": 3, "f32_accum": 0, "f32": 0}},
        "large_reductions": {"sub_f32": 0, "f32": 1},
        "exp_log_sub_f32": 0,
        "collective_dtypes": ["float32"],
        "waivers": [WAIVER_BF16_TRUNK],
    }
    rec.update(over)
    return rec


def _base():
    return {"dtype_programs": {"cell": _rec()}, "tolerances": {}}


def test_dtype_baseline_identity_is_clean():
    assert not diff_dtype_baseline({"cell": _rec()}, _base())


@pytest.mark.parametrize("mutation,needle", [
    ({"accum": {"dot_general": {"sub_f32": 3, "f32_accum": 1, "f32": 0},
                "conv": {"sub_f32": 3, "f32_accum": 0, "f32": 0}}},
     "accumulating below f32 grew"),
    ({"exp_log_sub_f32": 1}, "exp/log ops grew"),
    ({"cast_roundtrips": 1}, "round-trip cast chains grew"),
    ({"large_reductions": {"sub_f32": 1, "f32": 1}},
     "sub-f32 reductions grew"),
    ({"collective_dtypes": ["bfloat16", "float32"]},
     "precision cut on the wire"),
    ({"waivers": [WAIVER_BF16_TRUNK, WAIVER_BF16_WIRE]},
     "waiver set changed"),
    ({"casts": {"float32->bfloat16": 8, "bfloat16->float32": 8}},
     "cast count grew"),
])
def test_dtype_baseline_drift_classes_fire(mutation, needle):
    """Each banked numerics property is a fence: any growth (or, for
    casts, growth beyond the layout-noise tolerance) is rc 1."""
    findings = diff_dtype_baseline({"cell": _rec(**mutation)}, _base())
    assert any(f.check == "dtype-baseline" and needle in f.message
               for f in findings), [str(f) for f in findings]


def test_dtype_baseline_cell_membership():
    # a fresh cell not yet banked
    findings = diff_dtype_baseline({"new": _rec()}, _base(), subset=True)
    assert any("not in the committed baseline" in f.message
               for f in findings)
    # a banked cell missing from the audit: full run flags it, a declared
    # subset run (the tier-1 lean fixture) does not
    assert any("matrix shrank" in f.message
               for f in diff_dtype_baseline({}, _base()))
    assert not diff_dtype_baseline({}, _base(), subset=True)


# ------------------------------------------- D5 at the compiled tier --

_PROMOTED_HLO = """\
ENTRY %main (p0: f32[1024]) -> f32[1024] {
  %p0 = f32[1024] parameter(0)
  %narrow = bf16[1024] convert(f32[1024] %p0)
  %widen = f32[1024] convert(bf16[1024] %narrow)
  %ar = f32[1024] all-reduce(f32[1024] %widen), replica_groups={}
  ROOT %r = f32[1024] add(f32[1024] %ar, f32[1024] %p0)
}
"""

_PLAIN_HLO = """\
ENTRY %main (p0: f32[1024]) -> f32[1024] {
  %p0 = f32[1024] parameter(0)
  ROOT %ar = f32[1024] all-reduce(f32[1024] %p0), replica_groups={}
}
"""


def test_wire_dtype_resolves_promotion_roundtrip():
    """CPU XLA's f32-only reduction runtime materialises a requested bf16
    collective as convert(bf16)→all-reduce(f32)→convert-back; the table
    must charge the op at the SOURCE dtype the program asked for."""
    assert collective_wire_dtypes(_PROMOTED_HLO) == {
        "all-reduce": {"bf16": 1}}
    assert collective_wire_dtypes(_PLAIN_HLO) == {"all-reduce": {"f32": 1}}


def test_wire_dtype_contract_fires_and_admits_declared():
    table = collective_wire_dtypes(_PROMOTED_HLO)
    findings = audit_wire_dtypes(table, "f32", "fixture")
    assert findings and findings[0].check == "dtype-wire"
    assert "declares wire_dtype=f32" in findings[0].message
    assert not audit_wire_dtypes(table, "bf16", "fixture")
    assert not audit_wire_dtypes(collective_wire_dtypes(_PLAIN_HLO),
                                 "f32", "fixture")


# -------------------------------------------- jit-registration lint --


def test_jit_lint_fires_on_unregistered_site():
    src = ("import jax\n"
           "fn = jax.jit(lambda x: x)\n"          # module level
           "def rogue():\n"
           "    return jax.jit(lambda x: x + 1)\n")
    findings = lint_jit_source(src, registered={"make_train_step"})
    assert len(findings) == 2
    assert all(f.check == "jit-registration" for f in findings)
    owners = {f.evidence["function"] for f in findings}
    assert owners == {None, "rogue"}


def test_jit_lint_admits_registered_and_delegates():
    src = ("import jax\n"
           "def make_train_step():\n"
           "    return jax.jit(lambda s, x: s)\n"
           "def _build_step():\n"                 # documented delegate
           "    return jax.jit(lambda s: s)\n")
    assert not lint_jit_source(src, registered={"make_train_step"})


def test_repo_jit_sites_all_registered():
    """The real train/steps.py audits clean (also enforced session-wide by
    the conftest guard — this is the named, greppable assertion)."""
    assert not lint_jit_sites()


# ----------------------------------------------------- real repo half --


def test_repo_lean_cells_audit_clean(dtype_audit):
    assert set(dtype_audit.records) == _LEAN_CELLS
    assert not dtype_audit.findings, \
        [str(f) for f in dtype_audit.findings]


def test_repo_lean_cells_match_committed_baseline(dtype_audit):
    base = baselib.load_baseline()
    findings = diff_dtype_baseline(dtype_audit.records, base, subset=True)
    assert not findings, [str(f) for f in findings]


def test_bf16_cells_report_the_recipe(dtype_audit):
    rec = dtype_audit.records
    # the f32-pinned audit config has zero sub-f32 dot work; the shipped
    # bf16 cells are all-bf16 trunk (FLOP-weighted)
    assert rec["train_step"]["bf16_op_fraction"] == 0.0
    assert rec["train_step#bf16"]["bf16_op_fraction"] == 1.0
    # the banked trunk table: bf16 convs accumulate per the declared
    # waiver; any growth beyond these counts is a baseline finding
    assert rec["train_step#bf16"]["accum"]["conv"]["sub_f32"] > 0
    # serve softmax stays f32 under a bf16 trunk (the D4 customer)
    assert rec["topk_predict_serve#bf16"]["exp_log_sub_f32"] == 0
    # flax LN statistics stay f32 even under --ln_bf16 at audit width
    assert rec["vit_eval#ln_bf16"]["large_reductions"]["sub_f32"] == 0


def test_bf16_wire_cell_declares_its_collective(dtype_audit):
    rec = dtype_audit.records["train_step_bf16_reduce#bf16"]
    assert "bfloat16" in rec["collective_dtypes"]
    assert WAIVER_BF16_WIRE in rec["waivers"]
    assert WAIVER_BF16_TRUNK in rec["waivers"]


def test_master_weights_stay_f32_under_bf16_compute(dtype_audit):
    """The D2 contract on the real shipped-precision train step: no
    master-weights finding means every params/opt_state leaf is f32 both
    directions and the optimizer update computes at f32 — with the trunk
    at bf16. (The invariant the whole recipe hangs on.)"""
    assert not [f for f in dtype_audit.findings
                if f.check == "dtype-master"]


@pytest.mark.slow
def test_full_dtype_matrix_matches_baseline(dtype_audit):
    """Every registry cell (the wrapped step registry + the precision
    cells), audited clean and fenced against the committed baseline —
    what scripts/lint.sh runs in CI."""
    findings, records = audit_dtype_registry(dtype_audit.ctx)
    assert not findings, [str(f) for f in findings]
    base = baselib.load_baseline()
    drift = diff_dtype_baseline(records, base)
    assert not drift, [str(f) for f in drift]
    assert set(records) == set(base["dtype_programs"])


def test_committed_baseline_has_dtype_sections():
    """The checked-in artifact carries the dtype fence: the cells, the
    tolerance knob, and per-sharded-cell wire_dtypes tables."""
    base = baselib.load_baseline()
    assert len(base["dtype_programs"]) >= 15
    assert "cast_growth_pct" in base["tolerances"]
    sharded = base["programs"]["train_step_bf16@dp2"]
    assert "bf16" in sharded["wire_dtypes"].get("all-reduce", {})


# --------------------------------------------------- bench evidence --


def test_step_dtype_evidence_shape():
    a = jnp.zeros((8, 8), jnp.float32)
    ev = step_dtype_evidence(lambda a, b: a @ b, (a, a))
    assert ev == {"bf16_op_fraction": 0.0, "accum_dtype_ok": True}
    b = a.astype(jnp.bfloat16)
    ev = step_dtype_evidence(lambda a, b: a @ b, (b, b))
    assert ev["bf16_op_fraction"] == 1.0      # trunk matmuls are declared
    assert ev["accum_dtype_ok"] is True       # ...and not an unwaivable


# -------------------------------------------------------- parity pins --


def test_resnet_pool_seam_parity_pin():
    """The real D6 finding this PR fixed: the resnet global-average-pool
    fed the f32 head through a bf16 rounding (jnp.mean accumulates f32
    internally, then rounded back to bf16). The FIXED seam must equal the
    all-f32 seam to 2e-4; the OLD recipe must NOT — the pin bites."""
    import ddp_classification_pytorch_tpu.models.resnet as rn

    model = rn.resnet18(num_classes=10, variant="cifar",
                        dtype=jnp.bfloat16)
    x = jax.random.uniform(jax.random.PRNGKey(0), (8, 32, 32, 3),
                           jnp.float32)
    variables = model.init(jax.random.PRNGKey(1), x, train=False)
    logits, st = model.apply(variables, x, train=False,
                             capture_intermediates=True,
                             mutable=["intermediates"])
    trunk = st["intermediates"]["layer4_block1"]["__call__"][0]
    assert trunk.dtype == jnp.bfloat16
    W = variables["params"]["fc"]["kernel"]
    b = variables["params"]["fc"]["bias"]

    ref = jnp.mean(trunk.astype(jnp.float32), axis=(1, 2)) @ W + b
    fixed = jnp.mean(trunk, axis=(1, 2), dtype=jnp.float32) @ W + b
    old = jnp.mean(trunk, axis=(1, 2)).astype(jnp.float32) @ W + b

    # the manual fixed seam IS the model's seam (no hidden math between)
    assert float(jnp.max(jnp.abs(fixed - logits))) == 0.0
    assert float(jnp.max(jnp.abs(fixed - ref))) <= 2e-4
    assert float(jnp.max(jnp.abs(old - ref))) > 2e-4


def test_vit_ln_final_seam_parity_pin():
    """Same shape of finding in the ViT head: ln_final + token pool used
    to round through bf16 on the way into the f32 fc — including under
    --ln_bf16, where a bf16 ln_final bought no matmul throughput at all
    (its output feeds only the pool/head)."""
    from ddp_classification_pytorch_tpu.models import vit as vitlib

    model = vitlib.build_vit("vit_t16", num_classes=10,
                             dtype=jnp.bfloat16, ln_bf16=True)
    x = jax.random.uniform(jax.random.PRNGKey(0), (4, 32, 32, 3),
                           jnp.float32)
    variables = model.init(jax.random.PRNGKey(2), x, train=False)
    logits, st = model.apply(variables, x, train=False,
                             capture_intermediates=True,
                             mutable=["intermediates"])
    ln = st["intermediates"]["ln_final"]["__call__"][0]
    # THE fix: ln_final stays f32 even under --ln_bf16
    assert ln.dtype == jnp.float32
    W = variables["params"]["fc"]["kernel"]
    b = variables["params"]["fc"]["bias"]

    fixed = ln.mean(axis=1) @ W + b
    old = ln.astype(jnp.bfloat16).mean(axis=1).astype(jnp.float32) @ W + b

    assert float(jnp.max(jnp.abs(fixed - logits))) == 0.0
    assert float(jnp.max(jnp.abs(old - fixed))) > 2e-4


@pytest.mark.slow  # two real train-step compiles (~20 s) for one assert
def test_bf16_wire_one_step_parity(dtype_audit):
    """The declared bf16 grad wire (D5's one admitted waiver) after ONE
    real train step: params land within 1e-3 of the f32-wire run (lr ×
    bf16 grad rounding), and NOT bit-identical — the wire is live."""
    from ddp_classification_pytorch_tpu.train.state import (
        create_train_state,
    )
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    ctx = dtype_audit.ctx
    imgs = jax.random.uniform(jax.random.PRNGKey(0), (8, 32, 32, 3),
                              jnp.float32)
    labels = jax.random.randint(jax.random.PRNGKey(1), (8,), 0, 8)
    out_params = {}
    for wire in ("float32", "bfloat16"):
        cfg = ctx.tiny_cfg("baseline")
        cfg.model.dtype = "bfloat16"
        cfg.parallel.grad_reduce_dtype = wire
        model, tx, state = create_train_state(cfg, ctx.mesh,
                                              steps_per_epoch=4)
        step = make_train_step(cfg, model, tx, mesh=ctx.mesh)
        out = step(state, imgs, labels)
        new_state = out[0] if isinstance(out, tuple) else out
        out_params[wire] = new_state.params
    deltas = jax.tree_util.tree_leaves(jax.tree_util.tree_map(
        lambda a, b: float(jnp.max(jnp.abs(a - b))),
        out_params["float32"], out_params["bfloat16"]))
    assert 0.0 < max(deltas) <= 1e-3, max(deltas)
