"""torchvision-weight import tests.

1. Primitive-level oracle vs torch (baked-in dependency): conv stride-2
   pad-1, BN eval semantics, and MaxPool(3,2,1) must match our flax modules
   bitwise-closely — this is exactly what the explicit-padding change in
   models/resnet.py guarantees.
2. Structural round-trip: a synthetic torch state_dict covering every leaf of
   the flax resnet18/resnet50 trees converts and merges with no unmapped or
   mismatched leaves.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddp_classification_pytorch_tpu.models import resnet as R
from ddp_classification_pytorch_tpu.models.import_torch import (
    convert_resnet_state_dict,
    merge_into_variables,
)

torch = pytest.importorskip("torch")


def test_conv_stride2_matches_torch():
    tconv = torch.nn.Conv2d(3, 8, 3, stride=2, padding=1, bias=False)
    x = np.random.default_rng(0).normal(size=(2, 3, 16, 16)).astype(np.float32)
    with torch.no_grad():
        ref = tconv(torch.from_numpy(x)).numpy()

    import flax.linen as nn

    fconv = nn.Conv(8, (3, 3), strides=(2, 2), use_bias=False,
                    padding=[(1, 1), (1, 1)])
    kernel = tconv.weight.detach().numpy().transpose(2, 3, 1, 0)
    out = fconv.apply({"params": {"kernel": jnp.asarray(kernel)}},
                      jnp.asarray(x.transpose(0, 2, 3, 1)))
    np.testing.assert_allclose(
        np.asarray(out).transpose(0, 3, 1, 2), ref, atol=1e-5, rtol=1e-5)


def test_maxpool_matches_torch():
    x = np.random.default_rng(1).normal(size=(2, 3, 15, 15)).astype(np.float32)
    with torch.no_grad():
        ref = torch.nn.functional.max_pool2d(
            torch.from_numpy(x), 3, stride=2, padding=1).numpy()
    import flax.linen as nn

    out = nn.max_pool(jnp.asarray(x.transpose(0, 2, 3, 1)), (3, 3),
                      strides=(2, 2), padding=[(1, 1), (1, 1)])
    np.testing.assert_allclose(
        np.asarray(out).transpose(0, 3, 1, 2), ref, atol=1e-6)


def _torch_key_for(flax_path, leaf):
    """Inverse of import_torch._convert_key, for synthesizing state_dicts."""
    bn_inv = {"scale": "weight", "bias": "bias", "mean": "running_mean",
              "var": "running_var"}
    parts = list(flax_path)
    if parts[0] == "conv_stem":
        return "conv1.weight"
    if parts[0] == "bn_stem":
        return f"bn1.{bn_inv[leaf]}"
    if parts[0] == "fc":
        return f"fc.{'weight' if leaf == 'kernel' else 'bias'}"
    layer, block = parts[0].split("_block")
    prefix = f"{layer}.{block}"
    sub = parts[1]
    if sub == "downsample_conv":
        return f"{prefix}.downsample.0.weight"
    if sub == "downsample_bn":
        return f"{prefix}.downsample.1.{bn_inv[leaf]}"
    if sub.startswith("Conv_"):
        return f"{prefix}.conv{int(sub.split('_')[1]) + 1}.weight"
    if sub.startswith("BatchNorm_"):
        return f"{prefix}.bn{int(sub.split('_')[1]) + 1}.{bn_inv[leaf]}"
    raise AssertionError(flax_path)


@pytest.mark.parametrize("arch", ["resnet18", "resnet50"])
def test_state_dict_roundtrip_covers_every_leaf(arch):
    model = getattr(R, arch)(num_classes=7, dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 64, 64, 3)), train=False)

    rng = np.random.default_rng(2)
    state_dict = {}
    expected = {}
    for coll in ("params", "batch_stats"):
        flat = jax.tree_util.tree_flatten_with_path(variables[coll])[0]
        for path, value in flat:
            names = tuple(p.key for p in path)
            key = _torch_key_for(names[:-1], names[-1])
            arr = rng.normal(size=value.shape).astype(np.float32)
            expected[(coll,) + names] = arr
            if names[-1] == "kernel" and arr.ndim == 4:
                state_dict[key] = arr.transpose(3, 2, 0, 1)  # HWIO → OIHW
            elif names[-1] == "kernel":
                state_dict[key] = arr.T
            else:
                state_dict[key] = arr
    state_dict["bn1.num_batches_tracked"] = np.int64(5)  # must be skipped
    state_dict["mean_vector"] = np.zeros(3)  # vestigial buffer, skipped

    converted = convert_resnet_state_dict(state_dict)
    merged = merge_into_variables(variables, converted)
    for coll in ("params", "batch_stats"):
        flat = jax.tree_util.tree_flatten_with_path(merged[coll])[0]
        for path, value in flat:
            names = (coll,) + tuple(p.key for p in path)
            np.testing.assert_array_equal(
                np.asarray(value), expected[names], err_msg=str(names))


def test_pretrained_path_loads_into_train_state(tmp_path):
    """End to end: torch.save a synthetic torchvision-format checkpoint, point
    ModelConfig.pretrained_path at it, and verify the backbone picks it up."""
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
    from ddp_classification_pytorch_tpu.train.state import create_train_state

    model = R.resnet18(num_classes=1000, dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 64, 64, 3)), train=False)
    rng = np.random.default_rng(3)
    state_dict = {}
    for coll in ("params", "batch_stats"):
        flat = jax.tree_util.tree_flatten_with_path(variables[coll])[0]
        for path, value in flat:
            names = tuple(p.key for p in path)
            key = _torch_key_for(names[:-1], names[-1])
            arr = rng.normal(size=value.shape).astype(np.float32)
            if names[-1] == "kernel" and arr.ndim == 4:
                state_dict[key] = torch.from_numpy(arr.transpose(3, 2, 0, 1))
            elif names[-1] == "kernel":
                state_dict[key] = torch.from_numpy(arr.T)
            else:
                state_dict[key] = torch.from_numpy(arr)
    ckpt = tmp_path / "rn18.pth"
    torch.save(state_dict, str(ckpt))

    cfg = get_preset("baseline")
    cfg.model.arch = "resnet18"
    cfg.model.dtype = "float32"
    cfg.model.pretrained = True
    cfg.model.pretrained_path = str(ckpt)
    cfg.data.image_size = 64
    cfg.data.num_classes = 10  # != 1000 → fc must be skipped, backbone loaded

    mesh = meshlib.make_mesh()
    _, _, state = create_train_state(cfg, mesh, steps_per_epoch=4)
    got = np.asarray(state.params["backbone"]["conv_stem"]["kernel"])
    want = np.asarray(state_dict["conv1.weight"]).transpose(2, 3, 1, 0)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_nested_feat_net_format_converts():
    """Reference NESTED checkpoints key the backbone as feat_net.<seq_idx>.*
    (NetFeat Sequential over [conv1,bn1,relu,maxpool,layer1..4,avgpool],
    NESTED/model/model.py:37-40)."""
    sd = {
        "feat_net.0.weight": np.zeros((64, 3, 7, 7), np.float32),
        "feat_net.1.weight": np.ones((64,), np.float32),
        "feat_net.1.running_mean": np.zeros((64,), np.float32),
        "feat_net.4.0.conv1.weight": np.zeros((64, 64, 3, 3), np.float32),
        "feat_net.4.0.bn1.bias": np.zeros((64,), np.float32),
    }
    out = convert_resnet_state_dict(sd)
    assert out["params"]["conv_stem"]["kernel"].shape == (7, 7, 3, 64)
    assert out["params"]["bn_stem"]["scale"].shape == (64,)
    assert out["batch_stats"]["bn_stem"]["mean"].shape == (64,)
    assert out["params"]["layer1_block0"]["Conv_0"]["kernel"].shape == (3, 3, 64, 64)
    assert out["params"]["layer1_block0"]["BatchNorm_0"]["bias"].shape == (64,)


def test_empty_conversion_raises():
    with pytest.raises(ValueError, match="no convertible"):
        convert_resnet_state_dict({"encoder.blocks.0.w": np.zeros((3, 3))})


def test_pretrained_without_path_raises():
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
    from ddp_classification_pytorch_tpu.train.state import create_train_state

    cfg = get_preset("baseline")
    cfg.model.arch = "resnet18"
    cfg.model.pretrained = True  # no pretrained_path
    cfg.data.image_size = 32
    with pytest.raises(ValueError, match="pretrained_path"):
        create_train_state(cfg, meshlib.make_mesh(), steps_per_epoch=1)


def test_merge_rejects_shape_mismatch():
    model = R.resnet18(num_classes=7, dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3)), train=False)
    bad = {"params": {"conv_stem": {"kernel": np.zeros((3, 3, 3, 63))}}}
    with pytest.raises(ValueError, match="shape mismatch"):
        merge_into_variables(variables, bad)


# ------------------------------------------------------- VGG19-BN import ---

def _vgg_torch_key(flax_path, leaf):
    """Inverse of convert_vgg_state_dict's mapping (torchvision vgg19_bn)."""
    from ddp_classification_pytorch_tpu.models.vgg import _CFG_E

    bn_inv = {"scale": "weight", "bias": "bias", "mean": "running_mean",
              "var": "running_var"}
    name2seq = {}
    seq = i = 0
    for v in _CFG_E:
        if v == "M":
            seq += 1
        else:
            name2seq[f"conv{i}"] = seq
            name2seq[f"bn{i}"] = seq + 1
            seq += 3
            i += 1
    mod = flax_path[0]
    if mod.startswith("conv"):
        return f"features.{name2seq[mod]}.{'weight' if leaf == 'kernel' else 'bias'}"
    if mod.startswith("bn"):
        return f"features.{name2seq[mod]}.{bn_inv[leaf]}"
    cl = {"fc1": "0", "fc2": "3", "fc3": "6"}[mod]
    return f"classifier.{cl}.{'weight' if leaf == 'kernel' else 'bias'}"


def test_vgg_state_dict_roundtrip_covers_every_leaf():
    from ddp_classification_pytorch_tpu.models.import_torch import (
        convert_vgg_state_dict,
    )
    from ddp_classification_pytorch_tpu.models.vgg import vgg19_bn

    model = vgg19_bn(num_classes=13, dtype=jnp.float32)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        jnp.zeros((1, 64, 64, 3)), train=False)

    rng = np.random.default_rng(4)
    state_dict = {}
    expected = {}
    for coll in ("params", "batch_stats"):
        flat = jax.tree_util.tree_flatten_with_path(variables[coll])[0]
        for path, value in flat:
            names = tuple(p.key for p in path)
            key = _vgg_torch_key(names[:-1], names[-1])
            arr = rng.normal(size=value.shape).astype(np.float32)
            expected[(coll,) + names] = arr
            if names[-1] == "kernel" and arr.ndim == 4:
                state_dict[key] = arr.transpose(3, 2, 0, 1)  # HWIO → OIHW
            elif names[-1] == "kernel" and names[-2] == "fc1":
                o = arr.shape[1]
                # flax (HWC-flat, O) → torch (O, CHW-flat)
                state_dict[key] = (arr.T.reshape(o, 7, 7, 512)
                                   .transpose(0, 3, 1, 2).reshape(o, -1))
            elif names[-1] == "kernel":
                state_dict[key] = arr.T
            else:
                state_dict[key] = arr
    state_dict["features.1.num_batches_tracked"] = np.int64(7)  # skipped

    converted = convert_vgg_state_dict(state_dict)
    merged = merge_into_variables(variables, converted)
    for coll in ("params", "batch_stats"):
        flat = jax.tree_util.tree_flatten_with_path(merged[coll])[0]
        for path, value in flat:
            names = (coll,) + tuple(p.key for p in path)
            np.testing.assert_allclose(
                np.asarray(value), expected[names], atol=1e-6,
                err_msg=str(names))


def test_vgg_fc1_flatten_order_matches_torch():
    """The CHW→HWC input-dim permutation on fc1 must keep the linear layer's
    OUTPUT identical between torch (flattening NCHW) and flax (flattening
    NHWC)."""
    from ddp_classification_pytorch_tpu.models.import_torch import (
        convert_vgg_state_dict,
    )

    rng = np.random.default_rng(5)
    x_nchw = rng.normal(size=(2, 512, 7, 7)).astype(np.float32)
    w = rng.normal(size=(16, 512 * 7 * 7)).astype(np.float32)
    ref = x_nchw.reshape(2, -1) @ w.T  # torch fc1 forward

    conv = convert_vgg_state_dict(
        {"classifier.0.weight": w, "classifier.0.bias": np.zeros(16, np.float32)})
    kernel = conv["params"]["fc1"]["kernel"]
    out = x_nchw.transpose(0, 2, 3, 1).reshape(2, -1) @ kernel  # flax forward
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


# ------------------------------------------------------ TResNet-M import ---

def _tresnet_torch_key(flax_path, leaf):
    """Inverse of convert_tresnet_state_dict's mapping (timm tresnet_m)."""
    import re as _re

    bn_inv = {"scale": "weight", "bias": "bias", "mean": "running_mean",
              "var": "running_var"}
    p = flax_path
    if p[0] == "stem_conv":
        return "body.conv1.0.weight"
    if p[0] == "stem_abn":
        return f"body.conv1.1.{bn_inv[leaf]}"
    if p[0] == "fc":
        return f"head.fc.{'weight' if leaf == 'kernel' else 'bias'}"
    m = _re.fullmatch(r"stage(\d+)_block(\d+)", p[0])
    layer, block = int(m.group(1)), int(m.group(2))
    prefix = f"body.layer{layer}.{block}"
    basic = layer in (1, 2)
    aa_conv = 1 if basic else 2  # conv wrapped with the blur at stride 2
    stride2 = block == 0 and layer >= 2
    sub = p[1]
    if sub.startswith("conv"):
        j = int(sub[4:])
        mid = "0.0" if (stride2 and j == aa_conv) else "0"
        return f"{prefix}.conv{j}.{mid}.weight"
    if sub.startswith("abn") or sub in ("bn2", "bn3"):
        j = int(sub[3:]) if sub.startswith("abn") else int(sub[2:])
        mid = "0.1" if (stride2 and j == aa_conv) else "1"
        return f"{prefix}.conv{j}.{mid}.{bn_inv[leaf]}"
    if sub == "se":
        return f"{prefix}.se.{p[2]}.{'weight' if leaf == 'kernel' else 'bias'}"
    if sub == "downsample":
        return f"{prefix}.downsample.1.0.weight"
    if sub == "bn_down":
        return f"{prefix}.downsample.1.1.{bn_inv[leaf]}"
    raise AssertionError(flax_path)


def test_tresnet_state_dict_roundtrip_covers_every_leaf():
    from ddp_classification_pytorch_tpu.models.import_torch import (
        convert_tresnet_state_dict,
    )
    from ddp_classification_pytorch_tpu.models.tresnet import tresnet_m

    model = tresnet_m(num_classes=11, dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 64, 64, 3)), train=False)

    rng = np.random.default_rng(6)
    state_dict = {}
    expected = {}
    for coll in ("params", "batch_stats"):
        flat = jax.tree_util.tree_flatten_with_path(variables[coll])[0]
        for path, value in flat:
            names = tuple(p.key for p in path)
            key = _tresnet_torch_key(names[:-1], names[-1])
            arr = rng.normal(size=value.shape).astype(np.float32)
            expected[(coll,) + names] = arr
            if names[-1] == "kernel" and arr.ndim == 4:
                state_dict[key] = arr.transpose(3, 2, 0, 1)  # HWIO → OIHW
            elif (names[-1] == "kernel" and len(names) >= 3
                    and names[-3] == "se"):
                # Dense (I, O) → timm 1×1-conv (O, I, 1, 1)
                state_dict[key] = arr.T[:, :, None, None]
            elif names[-1] == "kernel":
                state_dict[key] = arr.T
            else:
                state_dict[key] = arr
    # fixed blur buffers + BN counters must be skipped
    state_dict["body.layer2.0.conv1.1.filt"] = np.zeros((128, 1, 3, 3))
    state_dict["body.conv1.1.num_batches_tracked"] = np.int64(3)

    converted = convert_tresnet_state_dict(state_dict)
    merged = merge_into_variables(variables, converted)
    for coll in ("params", "batch_stats"):
        flat = jax.tree_util.tree_flatten_with_path(merged[coll])[0]
        for path, value in flat:
            names = (coll,) + tuple(p.key for p in path)
            np.testing.assert_allclose(
                np.asarray(value), expected[names], atol=1e-6,
                err_msg=str(names))
