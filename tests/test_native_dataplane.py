"""Native C++ dataplane tests: builds the shared lib, decodes real JPEGs and
PNGs, and checks transform semantics against the Python/PIL pipeline."""


import os
import time

import numpy as np
import pytest
from PIL import Image

from ddp_classification_pytorch_tpu.data.native import (
    get_lib,
    native_decodes_png,
    native_load_batch,
)
from ddp_classification_pytorch_tpu.data.transforms import (
    IMAGENET_MEAN,
    IMAGENET_STD,
)

# PNG tests only apply to a full build; the JPEG-only -DDP_NO_PNG fallback
# (hosts without libpng) is supported-degraded, not broken. The probe is a
# fixture, not a module-level skipif value, so collection never triggers
# the g++ build — only actually-selected PNG tests pay for it.
@pytest.fixture
def png_support():
    if not native_decodes_png():
        pytest.skip("native dataplane built without libpng (JPEG-only fallback)")


def _pil_val_ref(im, out=224, short=256):
    """The shared PIL oracle for the val transform: resize short side to
    `short` (BILINEAR), center-crop `out`, ImageNet-normalize."""
    im = im.convert("RGB")
    w, h = im.size
    s = short / min(w, h)
    im2 = im.resize((round(w * s), round(h * s)), Image.BILINEAR)
    left = (im2.width - out) // 2
    top = (im2.height - out) // 2
    ref = np.asarray(im2.crop((left, top, left + out, top + out)), np.float32)
    return (ref / 255.0 - IMAGENET_MEAN) / IMAGENET_STD


@pytest.fixture(scope="module")
def jpegs(tmp_path_factory):
    root = tmp_path_factory.mktemp("jpegs")
    paths = []
    for i, (w, h) in enumerate([(320, 240), (200, 300), (256, 256), (64, 48)]):
        # smooth gradient + color so bilinear comparisons are stable
        x = np.broadcast_to(np.linspace(0, 1, w)[None, :], (h, w))
        y = np.broadcast_to(np.linspace(0, 1, h)[:, None], (h, w))
        img = np.stack([x * 255, y * 255, (x + y) / 2 * 255], axis=2).astype(np.uint8)
        p = str(root / f"img{i}.jpg")
        Image.fromarray(img).save(p, quality=95)
        paths.append(p)
    return paths


def test_native_lib_builds():
    assert get_lib() is not None, "native dataplane failed to build"


def test_val_transform_matches_pil_center_crop(jpegs):
    out, errors = native_load_batch(jpegs, out_size=224, train=False,
                                    resize_short=256, seed=1, num_threads=2)
    assert errors == 0
    assert out.shape == (len(jpegs), 224, 224, 3)
    for i, p in enumerate(jpegs):
        with Image.open(p) as im:
            ref = _pil_val_ref(im)
        # different resample order (resize-then-crop vs fused) and no
        # antialiasing → tolerance in normalized units
        diff = np.abs(out[i] - ref).mean()
        assert diff < 0.12, (i, diff)


def test_train_transform_is_deterministic_and_varied(jpegs):
    a1, e1 = native_load_batch(jpegs, 224, train=True, seed=7, num_threads=2)
    a2, e2 = native_load_batch(jpegs, 224, train=True, seed=7, num_threads=1)
    b, _ = native_load_batch(jpegs, 224, train=True, seed=8, num_threads=2)
    assert e1 == e2 == 0
    np.testing.assert_array_equal(a1, a2)  # same seed → same crops, any thread count
    assert np.abs(a1 - b).mean() > 1e-3    # different seed → different crops


@pytest.fixture(scope="module")
def pngs(tmp_path_factory):
    """RGB, RGBA and palette PNGs — the transform branches of the native
    decoder (PIL convert('RGB') is the semantics oracle)."""
    root = tmp_path_factory.mktemp("pngs")
    x = np.broadcast_to(np.linspace(0, 1, 300)[None, :], (260, 300))
    y = np.broadcast_to(np.linspace(0, 1, 260)[:, None], (260, 300))
    base = np.stack([x * 255, y * 255, (x + y) / 2 * 255], 2).astype(np.uint8)
    paths = []
    rgb = str(root / "rgb.png")
    Image.fromarray(base).save(rgb)
    paths.append(rgb)
    rgba = str(root / "rgba.png")
    Image.fromarray(
        np.concatenate([base, np.full((260, 300, 1), 200, np.uint8)], 2)
    ).save(rgba)  # 4-channel uint8 → RGBA inferred (mode= arg is deprecated)
    paths.append(rgba)
    pal = str(root / "palette.png")
    Image.fromarray(base).convert("P", palette=Image.ADAPTIVE).save(pal)
    paths.append(pal)
    return paths, base


def test_png_decode_matches_pil(pngs, png_support):
    paths, _ = pngs
    out, errors = native_load_batch(paths, out_size=224, train=False,
                                    resize_short=256, seed=2, num_threads=2)
    assert errors == 0
    for i, p in enumerate(paths):
        with Image.open(p) as im:
            ref = _pil_val_ref(im)
        diff = np.abs(out[i] - ref).mean()
        # palette quantization gets a little extra slack
        assert diff < 0.15, (i, p, diff)


def test_png_16bit_rescales_not_clamps(tmp_path, pngs, png_support):
    """16-bit PNGs: libpng's strip_16 rescales (v*257 >> 8 == v) — the
    correct reading. PIL's convert('RGB') CLAMPS >255 instead, so the
    oracle here is the original 8-bit content, not PIL."""
    _, base = pngs
    gray = base[:, :, 0]
    p = str(tmp_path / "sixteen.png")
    # uint16 array → I;16 inferred (the mode= arg is deprecated in Pillow)
    Image.fromarray(gray.astype(np.uint16) * 257).save(p)
    out, errors = native_load_batch([p], out_size=224, train=False,
                                    resize_short=256, seed=2, num_threads=1)
    assert errors == 0
    ref = _pil_val_ref(Image.fromarray(gray))
    assert np.abs(out[0] - ref).mean() < 0.12


def test_mixed_jpeg_png_batch(jpegs, pngs, png_support):
    out, errors = native_load_batch([jpegs[0], pngs[0][0]], 96, train=True,
                                    seed=5, num_threads=2)
    assert errors == 0
    assert np.abs(out).sum(axis=(1, 2, 3)).min() > 0.0


def test_truncated_png_reported_not_crashed(tmp_path, pngs, png_support):
    """Valid PNG signature + corrupt image data drives libpng's longjmp
    error path (the one that must not leak or crash); the slot is
    zero-filled and reported like any other decode failure."""
    with open(pngs[0][0], "rb") as f:
        head = f.read(200)  # signature + IHDR + the start of IDAT
    bad = str(tmp_path / "truncated.png")
    with open(bad, "wb") as f:
        f.write(head)
    out, errors = native_load_batch([bad, pngs[0][0]], 96, train=False, seed=0,
                                    num_threads=2)
    assert errors == 1
    assert np.abs(out[0]).sum() == 0.0
    assert np.abs(out[1]).sum() > 0.0


def _write_adam7_png(path, rgb):
    """Hand-encode a genuinely Adam7-interlaced PNG (Pillow silently
    ignores save(..., interlace=True), so a real fixture must be built by
    hand or the multi-pass decode loop ships untested)."""
    import struct
    import zlib

    h, w, _ = rgb.shape

    def chunk(tag, data):
        body = tag + data
        return (struct.pack(">I", len(data)) + body
                + struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF))

    # IHDR: 8-bit RGB, interlace method 1 (Adam7)
    ihdr = struct.pack(">IIBBBBB", w, h, 8, 2, 0, 0, 1)
    passes = [(0, 0, 8, 8), (4, 0, 8, 8), (0, 4, 4, 8), (2, 0, 4, 4),
              (0, 2, 2, 4), (1, 0, 2, 2), (0, 1, 1, 2)]
    raw = bytearray()
    for x0, y0, dx, dy in passes:
        sub = rgb[y0::dy, x0::dx]
        if sub.size == 0:
            continue
        for row in sub:
            raw.append(0)  # filter type None per scanline
            raw.extend(row.tobytes())
    with open(path, "wb") as f:
        f.write(b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr)
                + chunk(b"IDAT", zlib.compress(bytes(raw)))
                + chunk(b"IEND", b""))


def test_interlaced_png_decodes(tmp_path, pngs, png_support):
    _, base = pngs
    p = str(tmp_path / "interlaced.png")
    _write_adam7_png(p, base)
    with Image.open(p) as probe:  # the fixture really is interlaced
        assert probe.info.get("interlace") == 1
        np.testing.assert_array_equal(np.asarray(probe.convert("RGB")), base)
    out, errors = native_load_batch([p], out_size=224, train=False,
                                    resize_short=256, seed=2, num_threads=1)
    assert errors == 0
    ref = _pil_val_ref(Image.fromarray(base))
    assert np.abs(out[0] - ref).mean() < 0.12


def test_bad_file_reported_and_zero_filled(tmp_path, jpegs):
    bad = str(tmp_path / "not_a.jpg")
    with open(bad, "wb") as f:
        f.write(b"this is not a jpeg")
    out, errors = native_load_batch([jpegs[0], bad], 96, train=False, seed=0)
    assert errors == 1
    assert np.abs(out[1]).sum() == 0.0
    assert np.abs(out[0]).sum() > 0.0


def test_dimension_bomb_header_reported_not_crashed(tmp_path, pngs, png_support):
    """A valid PNG signature declaring absurd dimensions (header bomb) must
    be rejected BEFORE allocation — an std::bad_alloc escaping a pool
    thread would std::terminate the whole trainer instead of degrading to
    the zero-fill + PIL-retry contract."""
    import struct
    import zlib

    def chunk(tag, data):
        body = tag + data
        return (struct.pack(">I", len(data)) + body
                + struct.pack(">I", zlib.crc32(body) & 0xFFFFFFFF))

    bomb = str(tmp_path / "bomb.png")
    ihdr = struct.pack(">IIBBBBB", 1_000_000, 1_000_000, 8, 2, 0, 0, 0)
    with open(bomb, "wb") as f:
        f.write(b"\x89PNG\r\n\x1a\n" + chunk(b"IHDR", ihdr)
                + chunk(b"IDAT", zlib.compress(b"\x00" * 16))
                + chunk(b"IEND", b""))
    out, errors = native_load_batch([bomb, pngs[0][0]], 96, train=False,
                                    seed=0, num_threads=2)
    assert errors == 1
    assert np.abs(out[0]).sum() == 0.0
    assert np.abs(out[1]).sum() > 0.0


def test_stale_binary_without_new_symbol_recovers(tmp_path, monkeypatch):
    """A stale libdataplane.so predating dp_has_png (mtime newer than the
    source, so the rebuild guard misses) must not kill the native path:
    get_lib rebuilds to a FRESH filename and loads that — rebuilding in
    place cannot work because dlopen caches by name and ctypes never
    dlcloses."""
    import subprocess

    from ddp_classification_pytorch_tpu.data import native as native_mod

    stale_src = tmp_path / "stale.cpp"
    stale_src.write_text(
        'extern "C" int dp_load_batch() { return -1; }\n')  # no dp_has_png
    stale_lib = str(tmp_path / "libdataplane.so")
    subprocess.run(["g++", "-shared", "-fPIC", "-o", stale_lib,
                    str(stale_src)], check=True)
    future = time.time() + 3600
    os.utime(stale_lib, (future, future))  # defeat the mtime rebuild guard

    monkeypatch.setattr(native_mod, "_LIB", stale_lib)
    monkeypatch.setattr(native_mod, "_LIB_DIR", str(tmp_path))
    monkeypatch.setattr(native_mod, "_lib", None)
    monkeypatch.setattr(native_mod, "_load_failed", False)
    try:
        lib = native_mod.get_lib()
        assert lib is not None, "stale binary must trigger a fresh-path rebuild"
        assert lib.dp_has_png() in (0, 1)
    finally:
        # never leak the stale/temp libs into the module for later tests
        monkeypatch.setattr(native_mod, "_lib", None)
        monkeypatch.setattr(native_mod, "_load_failed", False)
