"""Native C++ dataplane tests: builds the shared lib, decodes real JPEGs, and
checks transform semantics against the Python/PIL pipeline."""


import numpy as np
import pytest
from PIL import Image

from ddp_classification_pytorch_tpu.data.native import get_lib, native_load_batch
from ddp_classification_pytorch_tpu.data.transforms import (
    IMAGENET_MEAN,
    IMAGENET_STD,
)


@pytest.fixture(scope="module")
def jpegs(tmp_path_factory):
    root = tmp_path_factory.mktemp("jpegs")
    rng = np.random.default_rng(0)
    paths = []
    for i, (w, h) in enumerate([(320, 240), (200, 300), (256, 256), (64, 48)]):
        # smooth gradient + color so bilinear comparisons are stable
        x = np.broadcast_to(np.linspace(0, 1, w)[None, :], (h, w))
        y = np.broadcast_to(np.linspace(0, 1, h)[:, None], (h, w))
        img = np.stack([x * 255, y * 255, (x + y) / 2 * 255], axis=2).astype(np.uint8)
        p = str(root / f"img{i}.jpg")
        Image.fromarray(img).save(p, quality=95)
        paths.append(p)
    return paths


def test_native_lib_builds():
    assert get_lib() is not None, "native dataplane failed to build"


def test_val_transform_matches_pil_center_crop(jpegs):
    out, errors = native_load_batch(jpegs, out_size=224, train=False,
                                    resize_short=256, seed=1, num_threads=2)
    assert errors == 0
    assert out.shape == (len(jpegs), 224, 224, 3)
    for i, p in enumerate(jpegs):
        with Image.open(p) as im:
            w, h = im.size
            s = 256 / min(w, h)
            im2 = im.resize((round(w * s), round(h * s)), Image.BILINEAR)
            left = (im2.width - 224) // 2
            top = (im2.height - 224) // 2
            ref = np.asarray(im2.crop((left, top, left + 224, top + 224)), np.float32)
        ref = (ref / 255.0 - IMAGENET_MEAN) / IMAGENET_STD
        # different resample order (resize-then-crop vs fused) and no
        # antialiasing → tolerance in normalized units
        diff = np.abs(out[i] - ref).mean()
        assert diff < 0.12, (i, diff)


def test_train_transform_is_deterministic_and_varied(jpegs):
    a1, e1 = native_load_batch(jpegs, 224, train=True, seed=7, num_threads=2)
    a2, e2 = native_load_batch(jpegs, 224, train=True, seed=7, num_threads=1)
    b, _ = native_load_batch(jpegs, 224, train=True, seed=8, num_threads=2)
    assert e1 == e2 == 0
    np.testing.assert_array_equal(a1, a2)  # same seed → same crops, any thread count
    assert np.abs(a1 - b).mean() > 1e-3    # different seed → different crops


def test_bad_file_reported_and_zero_filled(tmp_path, jpegs):
    bad = str(tmp_path / "not_a.jpg")
    with open(bad, "wb") as f:
        f.write(b"this is not a jpeg")
    out, errors = native_load_batch([jpegs[0], bad], 96, train=False, seed=0)
    assert errors == 1
    assert np.abs(out[1]).sum() == 0.0
    assert np.abs(out[0]).sum() > 0.0
