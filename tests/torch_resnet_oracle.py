"""Shim: the torch oracles moved into the package so `cli.verify_import`
can run the same parity check against a real `.pth`
(ddp_classification_pytorch_tpu/models/torch_oracle.py). Test imports
keep their historical name."""

from ddp_classification_pytorch_tpu.models.torch_oracle import (  # noqa: F401
    TorchResNet,
    TorchTResNetM,
    TorchVGG19BN,
    make_torch_resnet,
    make_torch_tresnet_m,
    make_torch_vgg19_bn,
    randomize_,
)
