"""Test-only torch ResNet oracle.

A from-scratch torch implementation of the standard torchvision ResNet
topology (v1.5: stride on the Bottleneck's 3x3 conv) with torchvision's
parameter naming (`conv1`, `bn1`, `layer1.0.conv1`, `downsample.0/1`,
`fc`), so its `state_dict()` is exactly the format
`models/import_torch.convert_resnet_state_dict` consumes.

Why it exists: the reference defaults every trainer to pretrained
torchvision weights (BASELINE/main.py:135, CDR/main.py:330,
NESTED/model/imagenet_resnet.py:195-203), but torchvision itself is not
installed in this sandbox and egress is zero — so the only way to prove
the import path end-to-end is to build the same architecture in torch
(which IS installed), randomize it, and assert full-model forward
equality through the converter. This file re-types the public
architecture from its published definition; it is not a copy of the
reference's `NESTED/model/imagenet_resnet.py` (that file carries extra
vestigial buffers and a custom forward this oracle deliberately omits).
"""

from __future__ import annotations

import torch
import torch.nn as nn


class BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, inplanes: int, planes: int, stride: int = 1,
                 downsample: nn.Module | None = None):
        super().__init__()
        self.conv1 = nn.Conv2d(inplanes, planes, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = torch.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return torch.relu(out + identity)


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, inplanes: int, planes: int, stride: int = 1,
                 downsample: nn.Module | None = None):
        super().__init__()
        self.conv1 = nn.Conv2d(inplanes, planes, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        # v1.5: the stride lives on the 3x3, matching models/resnet.py
        self.conv2 = nn.Conv2d(planes, planes, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, planes * self.expansion, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * self.expansion)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = torch.relu(self.bn1(self.conv1(x)))
        out = torch.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return torch.relu(out + identity)


class TorchResNet(nn.Module):
    def __init__(self, block, layers, num_classes: int = 1000):
        super().__init__()
        self.inplanes = 64
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes: int, blocks: int, stride: int = 1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2d(self.inplanes, planes * block.expansion, 1, stride,
                          bias=False),
                nn.BatchNorm2d(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = torch.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        x = x.mean(dim=(2, 3))  # adaptive avg pool to 1x1, flattened
        return self.fc(x)


_DEPTHS = {
    "resnet18": (BasicBlock, [2, 2, 2, 2]),
    "resnet34": (BasicBlock, [3, 4, 6, 3]),
    "resnet50": (Bottleneck, [3, 4, 6, 3]),
}


def make_torch_resnet(arch: str, num_classes: int = 1000) -> TorchResNet:
    block, layers = _DEPTHS[arch]
    return TorchResNet(block, layers, num_classes)


def randomize_(model: TorchResNet, seed: int = 0) -> None:
    """Randomize every parameter AND buffer so the parity check can catch
    any mapping swap. Torch's defaults would mask whole bug classes:
    running_mean=0/var=1 hides a mean<->var swap, BN weight=1/bias=0 hides
    a scale<->bias swap."""
    gen = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for name, p in model.named_parameters():
            if p.ndim >= 2:  # conv / linear weights, fan-in scaled so
                # activations stay O(1) — unscaled noise compounds to ~1e6
                # by layer4 and f32 accumulation noise then swamps tight
                # tolerances
                fan_in = p.numel() // p.shape[0]
                p.normal_(0.0, fan_in ** -0.5, generator=gen)
            elif "weight" in name:  # BN scale
                p.uniform_(0.5, 1.5, generator=gen)
            else:  # biases
                p.normal_(0.0, 0.1, generator=gen)
        for name, b in model.named_buffers():
            if name.endswith("running_mean"):
                b.normal_(0.0, 0.2, generator=gen)
            elif name.endswith("running_var"):
                b.uniform_(0.5, 2.0, generator=gen)
