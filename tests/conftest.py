"""Force an 8-device CPU topology before any JAX backend initializes.

This makes every test exercise the real jit + NamedSharding + collective code
paths on a virtual 8-device mesh — the TPU-native answer to "test multi-node
without a cluster" (the reference has no tests at all; SURVEY §4).

Note: the container's sitecustomize imports jax and registers the TPU (axon)
PJRT plugin before pytest starts, so JAX_PLATFORMS in os.environ is already
captured. `jax.config.update` still works at any point before first backend
use, and XLA_FLAGS is read lazily at CPU-client creation.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (already imported by sitecustomize; harmless)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _jit_registration_guard():
    """Every `jax.jit` site in train/steps.py must be reachable from a
    factory registered in jaxpr_audit.build_registry (or a documented
    delegate/exempt): an unregistered jit site is a hot program the
    donation/collective/dtype audits silently never see. Session-wide so
    the guard trips on ANY test run, not just the analysis file's."""
    from ddp_classification_pytorch_tpu.analysis.lint import lint_jit_sites

    findings = lint_jit_sites()
    assert not findings, (
        "unregistered jax.jit site(s) in train/steps.py — register the "
        "factory in jaxpr_audit.build_registry() or document it in "
        "analysis.lint._JIT_EXEMPT:\n"
        + "\n".join(str(f) for f in findings))
