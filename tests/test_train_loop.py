"""End-to-end training tests on the virtual 8-device CPU mesh.

These run the REAL sharded code path — jit over a NamedSharding'd global batch
on 8 devices — which is the test strategy the reference lacks entirely
(SURVEY §4): its DDP scripts cannot even start without CUDA+NCCL.
"""

import numpy as np

import jax

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.train.loop import Trainer


def tiny_cfg(workload: str, epochs: int = 2):
    cfg = get_preset(workload)
    cfg.data.dataset = "synthetic"
    cfg.data.image_size = 32
    cfg.data.num_classes = 4
    cfg.data.synthetic_size = 256
    cfg.data.batch_size = 32
    cfg.data.num_workers = 2
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.run.epochs = epochs
    cfg.run.log_every = 4
    cfg.run.write_records = False
    cfg.run.save_every_epoch = False
    cfg.run.save_best_only = False
    cfg.optim.warmup_iters = 0
    return cfg


def test_baseline_e2e_loss_drops(tmp_path):
    # 6 epochs: the last few train at near-zero loss so the BN running
    # statistics converge to the (now stable) activation distribution —
    # eval mode then matches train mode
    cfg = tiny_cfg("baseline", epochs=6)
    cfg.run.out_dir = str(tmp_path)
    cfg.run.write_records = True
    cfg.optim.lr = 0.05
    tr = Trainer(cfg)
    assert len(jax.devices()) == 8

    first = tr.train_epoch(0)
    for e in range(1, cfg.run.epochs):
        last = tr.train_epoch(e)
    assert last["loss"] < first["loss"], (first, last)

    val = tr.evaluate()
    # 4-class synthetic with strong class means: should be far above chance
    assert val["val_top1"] > 0.5, val
    assert 0.0 <= val["val_top3"] <= 1.0


def test_baseline_records_written(tmp_path):
    cfg = tiny_cfg("baseline", epochs=1)
    cfg.data.synthetic_size = 64
    cfg.run.out_dir = str(tmp_path / "run")
    cfg.run.write_records = True
    tr = Trainer(cfg)
    tr.run()
    assert (tmp_path / "run" / "output.txt").exists()
    assert (tmp_path / "run" / "history.json").exists()
    # the observability scrape file: host 0 rewrites $OUT/metrics.prom
    # atomically at the log cadence and each epoch boundary — a complete
    # Prometheus exposition with the trainer/sentinel instrument families
    prom = (tmp_path / "run" / "metrics.prom").read_text()
    assert "# TYPE train_steps_total counter" in prom
    assert "# TYPE train_epochs_total counter" in prom
    assert "train_epochs_total 1" in prom
    assert "# TYPE sentinel_streak gauge" in prom
    steps_line = [ln for ln in prom.splitlines()
                  if ln.startswith("train_steps_total ")]
    assert steps_line and float(steps_line[0].split()[1]) >= 1


def test_arcface_e2e_smoke(tmp_path):
    cfg = tiny_cfg("arcface", epochs=1)
    cfg.data.synthetic_size = 64
    cfg.run.out_dir = str(tmp_path)
    tr = Trainer(cfg)
    m = tr.train_epoch(0)
    assert np.isfinite(m["loss"])
    val = tr.evaluate()
    assert 0.0 <= val["val_top1"] <= 1.0


def test_nested_e2e_smoke_and_all_k_eval(tmp_path):
    cfg = tiny_cfg("nested", epochs=1)
    cfg.data.synthetic_size = 64
    cfg.optim.warmup_iters = 0
    cfg.run.out_dir = str(tmp_path)
    tr = Trainer(cfg)
    m = tr.train_epoch(0)
    assert np.isfinite(m["loss"])
    val = tr.evaluate()
    assert "best_k" in val and 0 <= val["best_k"] < 512
    assert 0.0 <= val["val_top1"] <= 1.0


def test_cdr_e2e_smoke(tmp_path):
    cfg = tiny_cfg("cdr", epochs=1)
    cfg.data.synthetic_size = 64
    cfg.data.num_classes = 4  # preset sets 100; tiny test overrides
    cfg.data.max_classes = 0
    cfg.run.out_dir = str(tmp_path)
    tr = Trainer(cfg)
    m = tr.train_epoch(0)
    assert np.isfinite(m["loss"])


def test_profiler_window_captures_trace(tmp_path):
    """--profile_steps on a non-tunneled backend (CPU here) captures a real
    jax.profiler trace into <out>/profile and deactivates cleanly — the
    SURVEY §5 tracing subsystem, untestable on the tunneled chip where the
    Trainer auto-gates it off."""
    import os

    cfg = tiny_cfg("baseline", epochs=1)
    cfg.run.out_dir = str(tmp_path)
    cfg.run.profile_steps = 2
    tr = Trainer(cfg)
    tr.run()
    assert tr._prof_active is False
    assert tr._prof_steps == 0  # window closed inside epoch 0
    prof_dir = str(tmp_path / "profile")
    trace_files = [os.path.join(r, f) for r, _, fs in os.walk(prof_dir) for f in fs]
    assert any(f.endswith((".trace.json.gz", ".xplane.pb")) for f in trace_files), (
        f"no trace artifacts under {prof_dir}: {trace_files}")


def test_checkpoint_save_and_resume(tmp_path):
    cfg = tiny_cfg("baseline", epochs=1)
    cfg.data.synthetic_size = 64
    cfg.run.out_dir = str(tmp_path / "ck")
    cfg.run.save_every_epoch = True
    tr = Trainer(cfg)
    tr.run()
    ckpt = tmp_path / "ck" / "ckpt_e0.msgpack"
    assert ckpt.exists()

    # resume into a fresh trainer; params must match bitwise
    cfg2 = tiny_cfg("baseline", epochs=1)
    cfg2.run.out_dir = str(tmp_path / "ck2")
    cfg2.run.resume = str(ckpt)
    tr2 = Trainer(cfg2)
    a = jax.tree_util.tree_leaves(jax.device_get(tr.state.params))
    b = jax.tree_util.tree_leaves(jax.device_get(tr2.state.params))
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    assert tr2.start_epoch == 1
