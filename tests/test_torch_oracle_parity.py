"""Full-model torch-vs-flax forward parity through the weight converters.

The strongest available proxy for "pretrained torchvision/timm checkpoints
load correctly" in a zero-egress sandbox (VERDICT r2 missing #2): build
each architecture in torch with its upstream parameter naming
(tests/torch_resnet_oracle.py), randomize every parameter and buffer, push
the real `state_dict()` through the matching converter +
`merge_into_variables`, and require the flax model to reproduce the torch
forward end to end in f32 — stride-2 paths, downsample branches, BN eval
statistics, pooling, flatten orderings and heads included. Any drift in
layer mapping, transpose convention, padding choice, or BN epsilon fails
these tests.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddp_classification_pytorch_tpu.models import resnet as R
from ddp_classification_pytorch_tpu.models.import_torch import (
    convert_resnet_state_dict,
    merge_into_variables,
)

torch = pytest.importorskip("torch")

from torch_resnet_oracle import (  # noqa: E402
    make_torch_resnet,
    make_torch_tresnet_m,
    make_torch_vgg19_bn,
    randomize_,
)


def _forward_pair(make_oracle, make_flax, converter, image_size, seed,
                  init_rngs=None):
    """Shared harness: randomized torch oracle → state_dict → converter →
    flax forward, both in f32 eval mode on the same input."""
    tmodel = make_oracle()
    randomize_(tmodel, seed=seed)
    tmodel.eval()

    rng = np.random.default_rng(seed + 100)
    x = rng.normal(size=(2, 3, image_size, image_size)).astype(np.float32)
    with torch.no_grad():
        ref = tmodel(torch.from_numpy(x)).numpy()

    fmodel = make_flax()
    variables = fmodel.init(init_rngs or jax.random.PRNGKey(0),
                            jnp.zeros((1, image_size, image_size, 3)),
                            train=False)
    merged = merge_into_variables(variables, converter(tmodel.state_dict()))
    out = fmodel.apply(merged, jnp.asarray(x.transpose(0, 2, 3, 1)),
                       train=False)
    return np.asarray(out), ref


def _assert_close(got, ref, tol):
    np.testing.assert_allclose(got, ref, rtol=tol, atol=tol)
    # logits must be non-degenerate for the comparison to mean anything
    assert np.std(ref) > 1e-3


@pytest.mark.parametrize("arch,image_size", [
    ("resnet18", 64),   # BasicBlock path, every stride-2 stage transition
    ("resnet50", 64),   # Bottleneck path incl. the stride-1 layer1 downsample
    ("resnet18", 75),   # odd size: the asymmetric-SAME-padding trap
])
def test_resnet_full_model_forward_matches_torch(arch, image_size):
    got, ref = _forward_pair(
        lambda: make_torch_resnet(arch, 37),
        lambda: getattr(R, arch)(num_classes=37, dtype=jnp.float32),
        convert_resnet_state_dict, image_size,
        seed={"resnet18": 0, "resnet50": 1}[arch] + (2 if image_size == 75 else 0))
    _assert_close(got, ref, 2e-4)


def test_feature_extractor_matches_torch_prepool():
    """num_classes=0 (the NESTED NetFeat role) must equal the torch pooled
    feature — proves the backbone alone, independent of the fc mapping."""
    tmodel = make_torch_resnet("resnet18", 5)
    randomize_(tmodel, seed=3)
    tmodel.eval()
    rng = np.random.default_rng(103)
    x = rng.normal(size=(2, 3, 64, 64)).astype(np.float32)
    with torch.no_grad():
        h = torch.relu(tmodel.bn1(tmodel.conv1(torch.from_numpy(x))))
        h = tmodel.maxpool(h)
        h = tmodel.layer4(tmodel.layer3(tmodel.layer2(tmodel.layer1(h))))
        ref = h.mean(dim=(2, 3)).numpy()

    fmodel = R.resnet18(num_classes=0, dtype=jnp.float32)
    variables = fmodel.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)),
                            train=False)
    converted = convert_resnet_state_dict(tmodel.state_dict(), include_fc=False)
    merged = merge_into_variables(variables, converted)
    got = fmodel.apply(merged, jnp.asarray(x.transpose(0, 2, 3, 1)),
                       train=False)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)


def test_vgg19bn_full_model_forward_matches_torch():
    """Same end-to-end contract for the VGG importer — including the
    CHW-vs-HWC fc1 flatten permutation — at 224px (the 7x7 pre-flatten grid
    both models assume)."""
    from ddp_classification_pytorch_tpu.models.import_torch import (
        convert_vgg_state_dict,
    )
    from ddp_classification_pytorch_tpu.models.vgg import vgg19_bn

    got, ref = _forward_pair(
        lambda: make_torch_vgg19_bn(num_classes=9),
        lambda: vgg19_bn(num_classes=9, dtype=jnp.float32),
        convert_vgg_state_dict, 224, seed=4,
        init_rngs={"params": jax.random.PRNGKey(0),
                   "dropout": jax.random.PRNGKey(1)})
    _assert_close(got, ref, 5e-4)


@pytest.mark.parametrize("image_size", [64, 104])  # 104: odd grids mid-net
def test_tresnet_m_full_model_forward_matches_torch(image_size):
    """End-to-end contract for the TResNet importer — the most intricate
    mapping (aa-wrapped stride-2 convs, SE 1x1-conv squeeze, avg-pool
    shortcut, space-to-depth stem channel order). 104px drives odd spatial
    grids through the blur/ceil-mode-avg-pool pair, pinning their padding
    parity."""
    from ddp_classification_pytorch_tpu.models.import_torch import (
        convert_tresnet_state_dict,
    )
    from ddp_classification_pytorch_tpu.models.tresnet import tresnet_m

    got, ref = _forward_pair(
        lambda: make_torch_tresnet_m(num_classes=6),
        lambda: tresnet_m(num_classes=6, dtype=jnp.float32),
        convert_tresnet_state_dict, image_size, seed=5)
    _assert_close(got, ref, 5e-4)
