"""Full-model torch-vs-flax forward parity through the weight converter.

The strongest available proxy for "pretrained torchvision checkpoints load
correctly" in a zero-egress sandbox (VERDICT r2 missing #2): build the
torchvision architecture in torch (tests/torch_resnet_oracle.py), randomize
every parameter and buffer, push its real `state_dict()` through
`convert_resnet_state_dict` + `merge_into_variables`, and require the flax
model to reproduce the torch forward end to end in f32 — stride-2 paths,
downsample branches, BN eval statistics, pooling and the fc head included.
Any drift in layer mapping, transpose convention, padding choice, or BN
epsilon fails these tests.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddp_classification_pytorch_tpu.models import resnet as R
from ddp_classification_pytorch_tpu.models.import_torch import (
    convert_resnet_state_dict,
    merge_into_variables,
)

torch = pytest.importorskip("torch")

from torch_resnet_oracle import make_torch_resnet, randomize_  # noqa: E402


def _forward_pair(arch: str, num_classes: int, image_size: int, seed: int):
    tmodel = make_torch_resnet(arch, num_classes)
    randomize_(tmodel, seed=seed)
    tmodel.eval()

    rng = np.random.default_rng(seed + 100)
    x = rng.normal(size=(2, 3, image_size, image_size)).astype(np.float32)
    with torch.no_grad():
        ref = tmodel(torch.from_numpy(x)).numpy()

    fmodel = getattr(R, arch)(num_classes=num_classes, dtype=jnp.float32)
    variables = fmodel.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, image_size, image_size, 3)),
                            train=False)
    converted = convert_resnet_state_dict(tmodel.state_dict())
    merged = merge_into_variables(variables, converted)
    out = fmodel.apply(merged, jnp.asarray(x.transpose(0, 2, 3, 1)),
                       train=False)
    return np.asarray(out), ref


@pytest.mark.parametrize("arch,image_size", [
    ("resnet18", 64),   # BasicBlock path, every stride-2 stage transition
    ("resnet50", 64),   # Bottleneck path incl. the stride-1 layer1 downsample
])
def test_full_model_forward_matches_torch(arch, image_size):
    got, ref = _forward_pair(arch, num_classes=37, image_size=image_size,
                             seed={"resnet18": 0, "resnet50": 1}[arch])
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
    # logits must be non-degenerate for the comparison to mean anything
    assert np.std(ref) > 1e-3


def test_full_model_forward_matches_torch_odd_input():
    """Odd spatial size exercises the asymmetric-padding trap: SAME padding
    would shift the stride-2 grids; the explicit k//2 padding must not."""
    got, ref = _forward_pair("resnet18", num_classes=11, image_size=75, seed=2)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_feature_extractor_matches_torch_prepool():
    """num_classes=0 (the NESTED NetFeat role) must equal the torch pooled
    feature — proves the backbone alone, independent of the fc mapping."""
    tmodel = make_torch_resnet("resnet18", 5)
    randomize_(tmodel, seed=3)
    tmodel.eval()
    rng = np.random.default_rng(103)
    x = rng.normal(size=(2, 3, 64, 64)).astype(np.float32)
    with torch.no_grad():
        h = torch.relu(tmodel.bn1(tmodel.conv1(torch.from_numpy(x))))
        h = tmodel.maxpool(h)
        h = tmodel.layer4(tmodel.layer3(tmodel.layer2(tmodel.layer1(h))))
        ref = h.mean(dim=(2, 3)).numpy()

    fmodel = R.resnet18(num_classes=0, dtype=jnp.float32)
    variables = fmodel.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)),
                            train=False)
    converted = convert_resnet_state_dict(tmodel.state_dict(), include_fc=False)
    merged = merge_into_variables(variables, converted)
    got = fmodel.apply(merged, jnp.asarray(x.transpose(0, 2, 3, 1)),
                       train=False)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=2e-4, atol=2e-4)
