"""RecordWriter resume semantics.

A resumed run must APPEND to the pre-preemption history curve: round 2
shipped a run (runs/digits_plc_fixed) whose history.json covered only
epochs 16-24 because the writer started empty and overwrote the file.
`resume_at` reloads and truncates to the restored epoch.
"""

import json
import os

from ddp_classification_pytorch_tpu.utils.logging import RecordWriter


def _write_history(out_dir, n):
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "history.json"), "w") as f:
        json.dump({"loss": [float(10 - e) for e in range(n)],
                   "val_top1": [float(e) for e in range(n)]}, f)


def test_resume_appends_to_prior_history(tmp_path):
    out = str(tmp_path / "run")
    _write_history(out, 5)

    w = RecordWriter(out)
    w.resume_at(3)  # checkpoint restored at epoch 3 → epochs 3,4 are stale
    assert w.history["loss"] == [10.0, 9.0, 8.0]

    w.log_epoch(3, loss=7.5, val_top1=3.5)
    w.log_epoch(4, loss=7.0, val_top1=4.5)
    with open(os.path.join(out, "history.json")) as f:
        hist = json.load(f)
    assert hist["loss"] == [10.0, 9.0, 8.0, 7.5, 7.0]
    assert hist["val_top1"] == [0.0, 1.0, 2.0, 3.5, 4.5]


def test_resume_without_prior_history_is_noop(tmp_path):
    w = RecordWriter(str(tmp_path / "fresh"))
    w.resume_at(4)
    assert w.history == {}


def test_resume_with_torn_history_survives(tmp_path):
    """A torn prior file must not raise, and the resumed epochs must land
    at their TRUE indices (nulls mark the lost head) — epoch 1's value
    masquerading as epoch 0's would corrupt every downstream curve."""
    out = str(tmp_path / "run")
    os.makedirs(out, exist_ok=True)
    with open(os.path.join(out, "history.json"), "w") as f:
        f.write('{"loss": [1.0, ')  # torn write mid-dump
    w = RecordWriter(out)
    w.resume_at(1)  # must not raise
    w.log_epoch(1, loss=0.5)
    with open(os.path.join(out, "history.json")) as f:
        assert json.load(f)["loss"] == [None, 0.5]


def test_resume_with_short_history_pads_to_true_epoch(tmp_path):
    """Prior history that already lost its head (the runs/digits_plc_fixed
    damage shape: epochs 16-24 stored at indices 0-8) must not be re-labeled
    as epochs 0..N — lists shorter than the resume epoch keep their entries
    and the new epochs land at their true indices behind null padding."""
    out = str(tmp_path / "run")
    _write_history(out, 2)  # only epochs 0-1 survive on disk
    w = RecordWriter(out)
    w.resume_at(5)
    w.log_epoch(5, loss=0.25, val_top1=5.5)
    with open(os.path.join(out, "history.json")) as f:
        hist = json.load(f)
    assert hist["loss"] == [10.0, 9.0, None, None, None, 0.25]
    assert hist["val_top1"] == [0.0, 1.0, None, None, None, 5.5]


def test_relogged_epoch_overwrites_in_place(tmp_path):
    w = RecordWriter(str(tmp_path / "run"))
    w.log_epoch(0, loss=1.0)
    w.log_epoch(1, loss=0.8)
    w.log_epoch(1, loss=0.7)  # e.g. a re-run epoch after a partial resume
    assert w.history["loss"] == [1.0, 0.7]
