"""bench.py metadata consistency.

LAST_KNOWN_GOOD is the outage-window fallback artifact; its numbers must
stay bit-identical to the committed live capture in docs/performance.md or
the two records drift apart silently (each looks authoritative).
"""

import json
import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_last_known_good_matches_committed_capture():
    import bench

    with open(os.path.join(REPO, "docs", "performance.md")) as f:
        doc = f.read()
    m = re.search(
        r'^(\{"metric": "resnet50_train_images_per_sec_per_chip".*\})$',
        doc, re.M)
    assert m, "committed live-capture JSON line missing from docs/performance.md"
    captured = json.loads(m.group(1))

    lkg = bench.LAST_KNOWN_GOOD
    for key in ("metric", "value", "unit", "step_ms", "mfu", "vs_baseline"):
        assert lkg[key] == captured[key], key
    doc_extra = {r["metric"]: r for r in captured["extra"]}
    for row in lkg["extra"]:
        ref = doc_extra[row["metric"]]
        for key in ("value", "step_ms", "mfu"):
            assert row[key] == ref[key], (row["metric"], key)
