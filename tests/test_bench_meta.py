"""bench.py metadata consistency.

LAST_KNOWN_GOOD is the outage-window fallback artifact; its numbers must
stay bit-identical to the committed live capture in docs/performance.md or
the two records drift apart silently (each looks authoritative).
"""

import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_last_known_good_matches_committed_capture():
    import bench

    with open(os.path.join(REPO, "docs", "performance.md")) as f:
        doc = f.read()
    m = re.search(
        r'^(\{"metric": "resnet50_train_images_per_sec_per_chip".*\})$',
        doc, re.M)
    assert m, "committed live-capture JSON line missing from docs/performance.md"
    captured = json.loads(m.group(1))

    lkg = bench.LAST_KNOWN_GOOD
    for key in ("metric", "value", "unit", "step_ms", "mfu", "vs_baseline"):
        assert lkg[key] == captured[key], key
    doc_extra = {r["metric"]: r for r in captured["extra"]}
    lkg_extra = {r["metric"]: r for r in lkg["extra"]}
    # both directions: a row silently dropped from either side is drift too
    assert set(doc_extra) == set(lkg_extra), (set(doc_extra), set(lkg_extra))
    for metric, row in lkg_extra.items():
        ref = doc_extra[metric]
        for key in ("value", "step_ms", "mfu"):
            assert row[key] == ref[key], (metric, key)


def test_deadline_watchdog_emits_fallback_and_exits_5():
    """A bench run that outlives --deadline + grace must die LOUDLY with
    the self-explaining fallback JSON on stdout (the mid-run-hang path; a
    silent rc=124 from the driver's own timeout is the failure mode this
    guards). Grace is shrunk via the module constant; the hang is a plain
    sleep on the main thread — the watchdog must fire from its own."""
    src = (
        "import time, bench\n"
        "bench.DEADLINE_GRACE_S = 0.2\n"
        "bench._arm_deadline_watchdog(0.1, time.monotonic())\n"
        "time.sleep(30)\n"
    )
    p = subprocess.run([sys.executable, "-c", src], cwd=REPO,
                       capture_output=True, timeout=25)
    assert p.returncode == 5, (p.returncode, p.stderr[-300:])
    line = p.stdout.decode().strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["backend"] == "hung_mid_run"
    assert payload["last_known_good"]["value"] == __import__("bench").LAST_KNOWN_GOOD["value"]


def test_contention_annotation_thresholds():
    """A contended capture must carry the self-explaining annotation (with
    last_known_good) and a fresh one must not — so a low-but-successful
    BENCH_r0N.json never reads as a silent framework regression."""
    import bench

    assert bench._contention_annotation(None) is None
    # fresh window: below 2x the expectation
    expected = bench.PROBE_UNCONTENDED_MS or bench.PROBE_EXPECTED_MS_FALLBACK
    assert bench._contention_annotation(expected * 1.5) is None
    ann = bench._contention_annotation(expected * 4.7)
    assert ann is not None
    assert ann["ratio"] == 4.7
    assert ann["last_known_good"]["value"] == bench.LAST_KNOWN_GOOD["value"]
    assert "contended" in ann["note"] or "loaded" in ann["note"]


def test_e2e_metric_name_schema():
    """Lock the e2e row's metric naming: the TPU capture must emit exactly
    `resnet50_e2e_images_per_sec_per_chip` (regression-guarded next to the
    device-only flagship row), with the standard platform suffix off-accel."""
    import bench

    assert (bench._e2e_metric_name("resnet50", True, "tpu")
            == "resnet50_e2e_images_per_sec_per_chip")
    assert (bench._e2e_metric_name("resnet18", False, "cpu")
            == "resnet18_e2e_images_per_sec_per_chip_cpu")


def test_bench_cli_has_e2e_flags():
    """The --e2e surface must keep parsing (the smoke below drives the row
    builder directly, so argparse drift would otherwise go unseen)."""
    p = subprocess.run([sys.executable, "bench.py", "--help"], cwd=REPO,
                       capture_output=True, timeout=60)
    assert p.returncode == 0, p.stderr[-300:]
    helptext = p.stdout.decode()
    for flag in ("--e2e", "--e2e-dataset", "--e2e-images", "--e2e-root",
                 "--device-prefetch", "--e2e-workers", "--input-dtype",
                 "--trace", "--grad-accum", "--h2d-overlap"):
        assert flag in helptext, flag


def test_bench_e2e_row_smoke_cpu():
    """Run the e2e bench path (the exact `_bench_e2e_row` that `bench.py
    --e2e` calls) for a handful of steps on the CPU backend with a tiny
    synthetic dataset, and lock the emitted row's schema: the driver's
    regression guard keys on these fields."""
    import jax

    import bench
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib

    cfg = get_preset("baseline")
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.data.num_classes = 8
    cfg.data.image_size = 32
    cfg.data.batch_size = 16
    mesh = meshlib.make_mesh()
    n_chips = len(jax.devices())
    metric = bench._e2e_metric_name("resnet18", False, "cpu")
    row = bench._bench_e2e_row(
        cfg, mesh, steps=2, warmup=1, metric=metric, n_chips=n_chips,
        dataset_kind="synthetic", root="", n_images=64, src_size=0,
        device_prefetch=2, num_workers=2)

    assert row["metric"] == "resnet18_e2e_images_per_sec_per_chip_cpu"
    assert row["unit"] == "images/sec/chip"
    assert row["value"] > 0
    assert row["step_ms"] > 0
    assert row["device_prefetch"] == 2
    assert row["input"] == "synthetic"
    # the acceptance evidence: the stager thread, not the timing loop's
    # thread, produced the staged batches
    assert row["staged_batches"] >= 3
    assert row["staged_off_thread"] is True
    # wire-format evidence: the preset default is the uint8 dataplane, and
    # the observed per-step H2D payload is the uint8 arithmetic — 1 B/px
    # images + i32 labels, a ~4× cut vs the float32 wire (4 B/px)
    assert row["input_dtype"] == "uint8"
    uint8_bytes = 16 * 32 * 32 * 3 * 1 + 16 * 4
    float32_bytes = 16 * 32 * 32 * 3 * 4 + 16 * 4
    assert row["h2d_bytes_per_step"] == uint8_bytes
    assert float32_bytes / row["h2d_bytes_per_step"] > 3.9
    # donation-audit evidence (analysis/jaxpr_audit.donation_evidence): the
    # train step's donated state must be FULLY aliased in the executable —
    # the "no step buffer round-trips HBM" claim, carried on the row
    assert row["donated_bytes"] > 10_000_000  # the real resnet18 state
    assert row["aliased_bytes"] == row["donated_bytes"]
    assert row["donation_coverage"] == 1.0

    # dtype evidence from the same AOT window: this smoke pins f32 compute,
    # so the FLOP-weighted bf16 fraction is 0 and the unwaivable numerics
    # contracts (no f64, f32 accumulation/loss head, no round-trip casts)
    # must hold on the exact compiled step
    assert row["bf16_op_fraction"] == 0.0
    assert row["accum_dtype_ok"] is True
    assert row["temp_bytes"] > 0
    # comms/memory evidence from the SAME compile window
    # (analysis/sharding_audit.step_comms_evidence): a dp-sharded train
    # step carries the gradient all-reduce payload, and the executable's
    # peak HBM exceeds the donated state it updates in place
    assert row["collective_bytes_per_step"] > 0
    assert row["peak_hbm_bytes"] > row["donated_bytes"]
    # grad-accum / H2D-overlap schema lock: the defaults report K=1, the
    # per-optimizer-step payload aliases the per-step payload (one
    # optimizer step per compiled program), overlap off, and the
    # consumer-side input wait is measured
    assert row["grad_accum"] == 1
    assert (row["collective_bytes_per_optimizer_step"]
            == row["collective_bytes_per_step"])
    assert row["h2d_overlap"] is False
    assert row["h2d_wait_ms_per_step"] >= 0


def test_bench_row_trace_breakdown_cpu():
    """`--trace` on the device-resident bench row emits a
    `step_breakdown_ms` whose six buckets cover the measured step time —
    the ISSUE's acceptance bound: the bucket sum lands within 15% of the
    row's step_ms (idle is the remainder, so the SpanRecorder layout
    guarantees the per-chunk sum; the 15% slack absorbs chunk-vs-median
    skew). Schema lock for the trace row the worklist captures on TPU."""
    import jax

    import bench
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.obs.trace import BUCKETS
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib

    cfg = get_preset("baseline")
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.data.num_classes = 8
    cfg.data.image_size = 32
    cfg.data.batch_size = 16
    mesh = meshlib.make_mesh()
    row = bench._bench_row(
        cfg, mesh, steps=2, warmup=1,
        metric="resnet18_train_images_per_sec_per_chip_cpu",
        n_chips=len(jax.devices()), peak=None, trace=True)

    assert row["step_ms"] > 0
    assert row["breakdown_source"] in ("probes", "trace+probes")
    agg = row["step_breakdown_ms"]
    for bucket in BUCKETS:
        assert bucket in agg, bucket
        assert agg[bucket] >= 0
    total = sum(agg[b] for b in BUCKETS)
    assert abs(total - row["step_ms"]) <= 0.15 * row["step_ms"], (
        total, row["step_ms"])
    # the probe decomposition attributes real compute to fwd on any backend
    assert agg["fwd"] > 0


def test_bench_e2e_row_float32_wire_bytes():
    """`--input-dtype float32` (the legacy wire) reports 4 B/px payloads —
    the committed-trajectory comparison row for the ~4× claim. Driven
    through the same `_bench_e2e_row` with a prefetch-0 synchronous pass
    (no second compile path; the row builder reuses the uint8 smoke's
    model shape, so the wire is the only variable)."""
    import jax

    import bench
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib

    cfg = get_preset("baseline")
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.data.num_classes = 8
    cfg.data.image_size = 32
    cfg.data.batch_size = 16
    cfg.data.input_dtype = "float32"
    mesh = meshlib.make_mesh()
    row = bench._bench_e2e_row(
        cfg, mesh, steps=1, warmup=1,
        metric=bench._e2e_metric_name("resnet18", False, "cpu"),
        n_chips=len(jax.devices()), dataset_kind="synthetic", root="",
        n_images=64, src_size=0, device_prefetch=0, num_workers=1)
    assert row["input_dtype"] == "float32"
    assert row["h2d_bytes_per_step"] == 16 * 32 * 32 * 3 * 4 + 16 * 4


def test_serve_metric_name_schema():
    """Lock the serving row's metric naming: the TPU capture must emit
    exactly `resnet50_serve_latency`, with the standard platform suffix
    off-accel — same convention as the e2e row."""
    import bench

    assert bench._serve_metric_name("resnet50", True, "tpu") == \
        "resnet50_serve_latency"
    assert bench._serve_metric_name("resnet18", False, "cpu") == \
        "resnet18_serve_latency_cpu"


def test_bench_cli_has_serve_flags():
    """The --serve surface must keep parsing (the smoke below drives the
    row builder directly, so argparse drift would otherwise go unseen)."""
    p = subprocess.run([sys.executable, "bench.py", "--help"], cwd=REPO,
                       capture_output=True, timeout=60)
    assert p.returncode == 0, p.stderr[-300:]
    helptext = p.stdout.decode()
    for flag in ("--serve", "--serve-requests", "--serve-rps",
                 "--serve-buckets", "--serve-max-batch", "--serve-timeout-ms"):
        assert flag in helptext, flag


def test_bench_serve_row_smoke_cpu():
    """Run the serving bench path (the exact `_bench_serve_row` that
    `bench.py --serve` calls) on the CPU backend with a tiny model, and
    lock the emitted row's schema: the driver's regression guard keys on
    these fields, and the bucket evidence must prove the compile-count
    bound held."""
    import bench
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib

    cfg = get_preset("baseline")
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.data.num_classes = 8
    cfg.data.image_size = 32
    # a dp2 serve mesh (conftest forces 8 virtual CPU devices): the row
    # runs the dp-SHARDED predict, and the (2, 4) buckets are already
    # dp-divisible so the requested schema survives the round-up
    mesh = meshlib.serve_mesh(2)
    row = bench._bench_serve_row(
        cfg, mesh, metric=bench._serve_metric_name("resnet18", False, "cpu"),
        n_requests=10, offered_rps=0.0, buckets=(2, 4), max_batch=4,
        timeout_ms=10.0, topk=3)

    assert row["metric"] == "resnet18_serve_latency_cpu"
    assert row["unit"] == "ms"
    assert row["p99_ms"] >= row["p95_ms"] >= row["p50_ms"] > 0
    assert row["requests_per_sec"] > 0
    assert row["n_requests"] == 10 and row["offered_rps"] == 0.0
    assert row["buckets"] == [2, 4] and row["topk"] == 3
    # bucket evidence: only bucket shapes ran (the compile-count bound),
    # and the histogram accounts for every batch
    assert set(row["compiled_buckets"]) <= {2, 4}
    assert row["bucket_hist"] and all(
        int(k) in (2, 4) for k in row["bucket_hist"])
    assert 0 < row["fill_ratio"] <= 1.0
    # replica boot evidence (serve/aot.py): the first engine compiles +
    # banks the bucket executables, the measured engine deserializes them
    # — the warm boot must win, and the hit flag must prove the sidecar
    # (not a shared jit cache) is what made it instant
    assert row["aot_cache_hit"] is True
    assert row["serve_devices"] >= 1
    assert row["cold_start_ms"] > row["warm_start_ms"] > 0


def test_serve_slo_metric_name_schema():
    """Lock the SLO-search row's metric naming: the TPU capture must emit
    exactly `resnet50_max_rps_at_p99_slo`, with the standard platform
    suffix off-accel — same convention as the serve row."""
    import bench

    assert bench._serve_slo_metric_name("resnet50", True, "tpu") == \
        "resnet50_max_rps_at_p99_slo"
    assert bench._serve_slo_metric_name("resnet18", False, "cpu") == \
        "resnet18_max_rps_at_p99_slo_cpu"


def test_bench_cli_has_serve_slo_flags():
    """The SLO-search surface must keep parsing (the smoke below drives
    the row builder directly, so argparse drift would otherwise go
    unseen)."""
    p = subprocess.run([sys.executable, "bench.py", "--help"], cwd=REPO,
                       capture_output=True, timeout=60)
    assert p.returncode == 0, p.stderr[-300:]
    helptext = p.stdout.decode()
    for flag in ("--serve-slo-p99-ms", "--serve-slo-max-rps",
                 "--serve-slo-iters"):
        assert flag in helptext, flag


def test_bench_serve_slo_row_smoke_cpu():
    """Run the closed-loop SLO search (the exact `_bench_serve_slo_row`
    that `bench.py --serve --serve-slo-p99-ms N` calls) on the CPU backend
    with a tiny model, and lock the emitted row's schema. The reported
    value must be a KNOWN-GOOD floor: either 0 (nothing held the SLO) or
    an rps some probe actually sustained — never an extrapolation — and
    the probe ladder must ride along as evidence."""
    import bench
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib

    cfg = get_preset("baseline")
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.data.num_classes = 8
    cfg.data.image_size = 16
    mesh = meshlib.serve_mesh(2)
    row = bench._bench_serve_slo_row(
        cfg, mesh,
        metric=bench._serve_slo_metric_name("resnet18", False, "cpu"),
        slo_p99_ms=60_000.0,  # generous: CPU smoke proves schema, not perf
        max_rps=32.0, iters=2, n_requests=6,
        buckets=(2, 4), max_batch=4, timeout_ms=5.0, topk=3)

    assert row["metric"] == "resnet18_max_rps_at_p99_slo_cpu"
    assert row["unit"] == "rps"
    assert row["p99_slo_ms"] == 60_000.0
    assert row["slo_bound_rps"] == 32.0
    assert row["n_requests_per_probe"] == 6
    assert row["buckets"] == [2, 4] and row["topk"] == 3
    assert row["serve_devices"] >= 1
    # the probe ladder: every probe carries (rps, p99_ms, ok), the first
    # one is the ceiling probe at max_rps
    assert row["probes"], "no probes recorded"
    assert row["probes"][0]["rps"] == 32.0
    for p_ in row["probes"]:
        assert set(p_) == {"rps", "p99_ms", "ok"}
        assert p_["p99_ms"] > 0
    # value is the highest KNOWN-GOOD rps: it must equal some passing
    # probe's rps (or 0.0 when none passed), and with a 60s SLO on 6
    # requests the ceiling probe passes → bound-limited at max_rps
    passing = [p_["rps"] for p_ in row["probes"] if p_["ok"]]
    assert row["value"] == (max(passing) if passing else 0.0)
    assert row["bound_limited"] is True and row["value"] == 32.0
    assert row["p99_at_max_ms"] > 0


def test_watchdog_disarm_prevents_exit():
    src = (
        "import time, bench\n"
        "bench.DEADLINE_GRACE_S = 0.2\n"
        "disarm = bench._arm_deadline_watchdog(0.1, time.monotonic())\n"
        "disarm()\n"
        "time.sleep(1.0)\n"
        "print('survived')\n"
    )
    p = subprocess.run([sys.executable, "-c", src], cwd=REPO,
                       capture_output=True, timeout=25)
    assert p.returncode == 0, p.stderr[-300:]
    assert b"survived" in p.stdout


@pytest.mark.slow
def test_bench_e2e_row_accum_overlap_smoke():
    """The K-accumulation + double-buffered-H2D e2e row (`bench.py --e2e
    --grad-accum 4 --h2d-overlap`): one jitted optimizer step scans K=4
    microbatches, the prefetcher pipelines fetch behind the transfer, and
    the row carries the evidence fields the TPU worklist A/B keys on.
    Slow-marked (full e2e boot + a K=4 scan compile): the fast e2e smoke
    above already locks the new row fields at K=1, and the overlap
    thread mechanics are tier-1 in test_device_prefetch.py."""
    import jax

    import bench
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib

    cfg = get_preset("baseline")
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.data.num_classes = 8
    cfg.data.image_size = 32
    cfg.data.batch_size = 32  # dp=8 -> per-replica 4 -> K=4 x mb=1
    cfg.parallel.grad_accum = 4
    mesh = meshlib.make_mesh()
    row = bench._bench_e2e_row(
        cfg, mesh, steps=2, warmup=1,
        metric=bench._e2e_metric_name("resnet18", False, "cpu"),
        n_chips=len(jax.devices()), dataset_kind="synthetic", root="",
        n_images=64, src_size=0, device_prefetch=2, num_workers=2,
        h2d_overlap=True)

    assert row["value"] > 0
    assert row["grad_accum"] == 4
    assert row["h2d_overlap"] is True
    assert row["h2d_wait_ms_per_step"] >= 0
    assert row["staged_off_thread"] is True
    # the accumulated program still reduces gradients (and fully aliases
    # its donated state) ONCE per optimizer step
    assert row["collective_bytes_per_optimizer_step"] > 0
    assert row["donation_coverage"] == 1.0
