"""A caught TPU window must end up COMMITTED (VERDICT r3 #7).

`scripts/window_catcher.sh` banks bench + VGG artifacts unattended; round
3 left them sitting uncommitted in the work tree, where a workspace reset
could erase a scarce capture. The catcher now git-commits each banked
window immediately — this test drives the REAL catcher + worklist +
vgg_record + supervise chain in a scratch git repo (so no test commits
ever touch the real history), with a scripted stub interpreter standing
in for python (same technique as tests/test_recovery_rc_discipline.py):
probe answers, "bench" succeeds, "training" succeeds, and the assertions
are about git state — two bank commits exist, they contain the window
artifacts, catcher.log stays untracked, and pre-staged operator WIP is
NOT swept into the evidence commits.
"""

import os
import shutil
import stat
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STUB = """#!/usr/bin/env bash
echo "stub-json-line"
exit 0
"""


def _git(cwd, *args):
    return subprocess.run(["git", "-C", str(cwd)] + list(args),
                          capture_output=True, text=True, check=True).stdout


def test_caught_window_is_committed_and_scoped(tmp_path):
    scratch = tmp_path / "scratch_repo"
    scratch.mkdir()
    shutil.copytree(os.path.join(REPO, "scripts"), scratch / "scripts")
    (scratch / ".gitignore").write_text("runs/\n")
    subprocess.run(["git", "init", "-q"], cwd=scratch, check=True)
    _git(scratch, "config", "user.email", "t@t")
    _git(scratch, "config", "user.name", "t")
    _git(scratch, "add", "-A")
    _git(scratch, "commit", "-qm", "init")

    # operator WIP staged before the window opens — must survive untouched
    wip = scratch / "wip.py"
    wip.write_text("# half-finished\n")
    _git(scratch, "add", "wip.py")

    fakebin = tmp_path / "bin"
    fakebin.mkdir()
    stub = fakebin / "python"
    stub.write_text(STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IXUSR)

    env = dict(os.environ)
    env["PATH"] = f"{fakebin}:{env['PATH']}"
    env["DOWN_POLL_S"] = "0"
    env["INTER_WINDOW_S"] = "0"
    p = subprocess.run(
        ["bash", str(scratch / "scripts" / "window_catcher.sh")],
        env=env, capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, (p.stdout, p.stderr)

    log = _git(scratch, "log", "--format=%s")
    bank_commits = [s for s in log.splitlines()
                    if s.startswith("Bank unattended TPU window")]
    assert len(bank_commits) == 2, log  # bench bank + VGG bank

    # the bench artifact is in a bank commit; catcher.log never tracked
    tracked = _git(scratch, "ls-files")
    assert "bench.json" in tracked
    assert "catcher.log" not in tracked

    # operator WIP: still staged, never committed
    assert "wip.py" not in _git(scratch, "log", "--name-only")
    assert "wip.py" in _git(scratch, "diff", "--cached", "--name-only")
