"""CLI → Config mapping tests (no jax backend needed).

The CLI is the reference's whole API surface (SURVEY L6); these pin the
flag→field wiring that e2e tests are too slow to sweep.
"""

import pytest

from ddp_classification_pytorch_tpu.cli.train import build_parser, config_from_args


def _cfg(*argv):
    return config_from_args(build_parser().parse_args(argv))


def test_transform_flag_overrides_preset():
    cfg = _cfg("baseline", "--transform", "cifar", "--image_size", "32")
    assert cfg.data.transform == "cifar"
    assert cfg.data.image_size == 32


def test_transform_default_follows_workload_preset():
    assert _cfg("baseline").data.transform == "baseline"
    assert _cfg("cdr").data.transform == "cdr"


def test_input_dtype_flag():
    assert _cfg("baseline").data.input_dtype == "uint8"  # wire default
    assert _cfg("baseline", "--input_dtype", "float32").data.input_dtype == "float32"
    assert _cfg("baseline", "--input_dtype", "uint8").data.input_dtype == "uint8"
    with pytest.raises(SystemExit):  # argparse choices → usage error rc 2
        _cfg("baseline", "--input_dtype", "bf16")


def test_plc_batch_stat_predictions_flag():
    assert _cfg("plc").plc.batch_stat_predictions is False  # safe default
    assert _cfg("plc", "--plc_batch_stat_predictions").plc.batch_stat_predictions


def test_live_clip_schedule_flag_disables_dead_schedule():
    cfg = _cfg("cdr", "--live_clip_schedule")
    assert cfg.optim.cdr_dead_schedule is False
    assert _cfg("cdr").optim.cdr_dead_schedule is True


def test_lr_schedule_flag_sets_multistep_milestones():
    cfg = _cfg("baseline", "--lrSchedule", "20", "32")
    assert cfg.optim.schedule == "multistep"
    assert tuple(cfg.optim.milestones) == (20, 32)


def test_cifar_dataset_sets_facts_unless_overridden():
    cfg = _cfg("baseline", "--dataset", "cifar10", "--train_dir", "/x")
    assert cfg.data.num_classes == 10
    assert cfg.data.image_size == 32
    assert cfg.model.variant == "cifar"
    cfg = _cfg("baseline", "--dataset", "cifar100", "--num_classes", "100",
               "--image_size", "24")
    assert cfg.data.num_classes == 100
    assert cfg.data.image_size == 24


def test_unknown_transform_rejected_at_build():
    from ddp_classification_pytorch_tpu.data.transforms import build_transform

    with pytest.raises(ValueError, match="unknown transform"):
        build_transform("nope", train=True)


def test_moe_aux_weight_validation():
    # ValueError from config_from_args; cli.main maps it to SystemExit(2)
    # (tests/test_recovery_rc_discipline.py pins the exit code)
    with pytest.raises(ValueError, match="moe_aux_weight"):
        _cfg("baseline", "--model", "vit_t16", "--moe_experts", "4",
             "--moe_aux_weight", "-0.5")


def test_freeze_bn_flag_pair():
    assert _cfg("nested").model.freeze_bn is True  # preset (train.py:529)
    assert _cfg("nested", "--no-freeze-bn").model.freeze_bn is False
    assert _cfg("baseline", "--freeze-bn").model.freeze_bn is True


def test_hang_timeout_flag():
    assert _cfg("baseline").run.hang_timeout_s == 0.0  # off by default
    assert _cfg("baseline", "--hang_timeout_s", "900").run.hang_timeout_s == 900.0


def test_pp_stages_wiring():
    cfg = _cfg("arcface", "--model", "vit_t16", "--dp", "2", "--mp", "2",
               "--pp_stages", "2", "--pp_microbatches", "2")
    assert cfg.parallel.pipeline_stages == 2
    assert cfg.parallel.pipeline_microbatches == 2
    # --pp_stages without microbatches is a config error (maps to exit 2
    # in main(); tests/test_recovery_rc_discipline.py pins the code)
    with pytest.raises(ValueError, match="pp_microbatches"):
        _cfg("arcface", "--model", "vit_t16", "--pp_stages", "2")


def test_ln_bf16_wiring():
    assert _cfg("baseline").model.ln_bf16 is False
    assert _cfg("baseline", "--model", "vit_s16",
                "--ln_bf16").model.ln_bf16 is True


def test_reference_compat_flags_accepted_and_inert():
    """Scripted reference invocations pass --world_size/--local_rank/--gpu
    (BASELINE/train.sh:1, CDR/main.py:51, NESTED/train.py:473); the parser
    must accept them without letting them affect the config."""
    base = _cfg("baseline")
    compat = _cfg("baseline", "--world_size", "2", "--local_rank", "0",
                  "--gpu", "0")
    assert compat == base
