"""uint8 dataplane: wire-format propagation, on-device normalization
equivalence, and device-flip determinism.

The uint8 wire (data.input_dtype == "uint8", the default) ships raw pixels
host→device at ¼ the bytes of the legacy normalized-float32 wire and defers
`(x/255 − μ)/σ` (+ the train flip) to a device-side epilogue in the jitted
step. The acceptance contract: `input_dtype == "float32"` preserves the
host-normalize numerics exactly (the epilogue compiles to a no-op for f32
inputs), and the uint8 path matches it to float tolerance on identical
crops — quantization happens pre-normalize in both modes.
"""

import numpy as np
import pytest
from PIL import Image

import jax
import jax.numpy as jnp

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.data.loader import ShardedLoader
from ddp_classification_pytorch_tpu.data.device_prefetch import DevicePrefetcher
from ddp_classification_pytorch_tpu.data.synthetic import SyntheticDataset
from ddp_classification_pytorch_tpu.data.transforms import (
    build_transform,
    normalize,
    preset_for_dataset,
)
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
from ddp_classification_pytorch_tpu.train.state import create_train_state
from ddp_classification_pytorch_tpu.train.steps import (
    device_input_epilogue,
    make_eval_step,
    make_train_step,
)


def _tiny_cfg(input_dtype: str):
    cfg = get_preset("baseline")
    cfg.data.dataset = "synthetic"  # no transform preset → no device flip
    cfg.data.image_size = 32
    cfg.data.num_classes = 4
    cfg.data.batch_size = 16
    cfg.data.input_dtype = input_dtype
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    return cfg


# ------------------------------------------------------------- transforms --

def test_transform_uint8_mode_same_crops_as_float():
    """Identical rng → identical geometry; the uint8 output IS the pre-
    normalize array of the float output (quantization point unchanged)."""
    img = Image.fromarray(
        np.random.default_rng(0).integers(0, 256, (48, 56, 3)).astype(np.uint8))
    for preset, train in [("baseline", False), ("baseline", True),
                          ("cifar", True), ("cdr", True),
                          ("clothing1m", True)]:
        size = 32 if preset == "cifar" else 24
        t_f = build_transform(preset, train, image_size=size, crop_size=40)
        t_u = build_transform(preset, train, image_size=size, crop_size=40,
                              out_dtype="uint8")
        out_f = t_f(img, np.random.default_rng(7))
        out_u = t_u(img, np.random.default_rng(7))
        assert out_u.dtype == np.uint8, preset
        assert out_f.dtype == np.float32, preset
        # float path may additionally host-flip (uint8 defers it to the
        # device); compare against both orientations of the uint8 crop
        ref, ref_flipped = normalize(out_u), normalize(out_u[:, ::-1])
        assert (np.array_equal(out_f, ref)
                or np.array_equal(out_f, ref_flipped)), preset


def test_build_transform_rejects_unknown_dtype():
    with pytest.raises(ValueError, match="input dtype"):
        build_transform("baseline", True, out_dtype="bfloat16")


def test_preset_for_dataset_map():
    assert preset_for_dataset("synthetic", "baseline") is None
    assert preset_for_dataset("imagefolder", "cdr") == "cdr"
    assert preset_for_dataset("cifar10", "baseline") == "cifar"
    assert preset_for_dataset("plc", "baseline") == "clothing1m"


# ---------------------------------------------------------- wire plumbing --

def test_loader_and_prefetcher_propagate_uint8():
    """dataset uint8 → host batches uint8 → staged global arrays uint8
    (¼ the H2D bytes), labels untouched."""
    ds = SyntheticDataset(64, 16, 4, out_dtype="uint8")
    img, _ = ds.__getitem__(0)
    assert img.dtype == np.uint8
    loader = ShardedLoader(ds, 16, shuffle=True, num_workers=1,
                           host_id=0, num_hosts=1)
    try:
        images, labels = next(iter(loader))
        assert images.dtype == np.uint8 and images.shape == (16, 16, 16, 3)
        assert labels.dtype == np.int32
        mesh = meshlib.make_mesh()
        it = iter(DevicePrefetcher(loader, mesh, depth=1))
        try:
            g_images, g_labels = next(it)
            assert g_images.dtype == jnp.uint8
            assert g_images.nbytes * 4 == g_images.size * 4  # 1 B/px wire
        finally:
            it.close()
    finally:
        loader.close()


def test_float32_wire_unchanged():
    ds = SyntheticDataset(32, 16, 4)  # default out_dtype
    img, _ = ds.__getitem__(0)
    assert img.dtype == np.float32


# ------------------------------------------------------- step equivalence --

def test_uint8_matches_float32_through_real_train_step():
    """Same pixels on both wires → allclose loss/metrics and updated params
    (i.e. gradients) through a REAL jitted train step on the 8-device mesh;
    eval step loss agrees too. Synthetic-config steps have no device flip,
    so the comparison is augmentation-free."""
    rng = np.random.default_rng(0)
    u8 = rng.integers(0, 256, (16, 32, 32, 3)).astype(np.uint8)
    f32 = np.stack([normalize(x) for x in u8])
    labels = rng.integers(0, 4, 16).astype(np.int32)
    valid = np.ones(16, np.float32)
    mesh = meshlib.make_mesh()

    outs = {}
    for wire, imgs in [("uint8", u8), ("float32", f32)]:
        cfg = _tiny_cfg(wire)
        model, tx, state = create_train_state(cfg, mesh, 8)
        step = make_train_step(cfg, model, tx, mesh=mesh)
        ev = make_eval_step(cfg, model, mesh=mesh)
        g = meshlib.make_global_array((imgs, labels, valid), mesh)
        ev_out = jax.device_get(ev(state, *g))
        state, metrics = step(state, g[0], g[1])
        outs[wire] = (jax.device_get(metrics), jax.device_get(state.params),
                      ev_out)

    m_u, p_u, e_u = outs["uint8"]
    m_f, p_f, e_f = outs["float32"]
    for k in m_f:
        np.testing.assert_allclose(m_u[k], m_f[k], rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_u),
                    jax.tree_util.tree_leaves(p_f)):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(e_u["loss_sum"], e_f["loss_sum"],
                               rtol=1e-5, atol=1e-4)
    assert e_u["top1"] == e_f["top1"]


def test_float32_epilogue_is_identity():
    """The f32 wire must compile to exactly the legacy program — the
    epilogue returns the input object untouched."""
    x = jnp.ones((2, 4, 4, 3), jnp.float32)
    assert device_input_epilogue(x, jax.random.PRNGKey(0), flip=True) is x


# ------------------------------------------------------------ device flip --

def test_device_flip_deterministic_under_fixed_key():
    rng = np.random.default_rng(1)
    u8 = rng.integers(0, 256, (64, 8, 8, 3)).astype(np.uint8)
    key = jax.random.PRNGKey(5)
    a = np.asarray(device_input_epilogue(jnp.asarray(u8), key, flip=True))
    b = np.asarray(device_input_epilogue(jnp.asarray(u8), key, flip=True))
    np.testing.assert_array_equal(a, b)
    # a different step key draws a different mask (P[same] = 2^-64)
    c = np.asarray(device_input_epilogue(
        jnp.asarray(u8), jax.random.PRNGKey(6), flip=True))
    assert (a != c).any()
    # every row is the normalized crop or its exact width-mirror, and with
    # 64 samples both orientations occur
    ref = np.stack([normalize(x) for x in u8])
    flipped_rows = 0
    for i in range(len(u8)):
        if np.array_equal(a[i], ref[i]):
            continue
        np.testing.assert_array_equal(a[i], ref[i][:, ::-1])
        flipped_rows += 1
    assert 0 < flipped_rows < len(u8)


def test_train_step_flip_gate_follows_preset():
    """imagefolder configs (a transform preset exists) flip on-device;
    synthetic configs don't — checked via the step's determinism across
    identical states (flip draws from the step key, so same state ⇒ same
    output either way; the uint8/float32 metric agreement above would
    break if the synthetic path flipped only one wire)."""
    from ddp_classification_pytorch_tpu.train.steps import _train_flip_enabled

    assert _train_flip_enabled(_tiny_cfg("uint8")) is False
    cfg = _tiny_cfg("uint8")
    cfg.data.dataset = "imagefolder"
    assert _train_flip_enabled(cfg) is True
    cfg.data.input_dtype = "float32"  # host already flipped
    assert _train_flip_enabled(cfg) is False
