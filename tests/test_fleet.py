"""Pod-level fault-tolerance tests (parallel/fleet.py) — tier-1-lean.

Every pod topology here is SIMULATED in one process: the fleet module's
collective primitives (`_process_index` / `_process_count` /
`_broadcast_host` / `_allgather_host`) are monkeypatched with recorded
payloads, so consensus, abort propagation, rendezvous retry, and the
generation file are all exercised without a second process or a single
jit compile. The real two-process pod drill is scripts/chaos_drill.sh
phase 3+ (`test_pod_chaos_drill`, marked slow).
"""

import os
import signal
import stat
import subprocess

import numpy as np
import pytest

import jax.numpy as jnp

from ddp_classification_pytorch_tpu.parallel import fleet
from ddp_classification_pytorch_tpu.train.checkpoint import CheckpointManager
from ddp_classification_pytorch_tpu.train.state import TrainState
from ddp_classification_pytorch_tpu.utils import chaos as chaoslib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _state(v: float) -> TrainState:
    return TrainState(
        step=jnp.asarray(int(v)),
        params={"w": jnp.full((4,), v)},
        batch_stats={"m": jnp.zeros((2,))},
        opt_state=(),
    )


def _pod(monkeypatch, index: int, count: int = 2):
    monkeypatch.setattr(fleet, "_process_index", lambda: index)
    monkeypatch.setattr(fleet, "_process_count", lambda: count)


# --------------------------------------------------------------- consensus --
def test_consensus_single_process_is_plain_restore_latest(tmp_path, monkeypatch):
    """pcount == 1 must take the existing restore_latest path and touch no
    collective primitive at all."""
    _pod(monkeypatch, 0, count=1)
    monkeypatch.setattr(fleet, "_broadcast_host",
                        lambda p: pytest.fail("collective on single host"))
    monkeypatch.setattr(fleet, "_allgather_host",
                        lambda x: pytest.fail("collective on single host"))
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(2.0), 0)
    mgr.wait()
    restored, next_epoch = fleet.consensus_restore_latest(mgr, _state(-1.0))
    assert next_epoch == 1
    np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                  np.full((4,), 2.0))


def test_consensus_leader_quarantines_follower_restores_exact(tmp_path, monkeypatch):
    """The acceptance shape: corrupt latest on shared storage ⇒ host 0
    quarantines it ONCE, broadcasts the older verified candidate, the
    follower restores that exact file (no second scan, no second rename),
    and the digest agreement passes."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(0.0), 0)
    mgr.save(_state(1.0), 1)
    mgr.wait()
    p = tmp_path / "ckpt_e1.msgpack"
    p.write_bytes(p.read_bytes()[: 20])  # torn latest

    sent = {}

    def record_broadcast(payload):
        sent["payload"] = payload
        return payload

    gathered = []

    def agree_allgather(x):
        gathered.append(np.asarray(x))
        return np.stack([x, x])

    _pod(monkeypatch, 0)
    monkeypatch.setattr(fleet, "_broadcast_host", record_broadcast)
    monkeypatch.setattr(fleet, "_allgather_host", agree_allgather)
    state0, e0 = fleet.consensus_restore_latest(mgr, _state(-1.0))
    assert e0 == 1
    np.testing.assert_array_equal(np.asarray(state0.params["w"]), np.zeros(4))
    assert (tmp_path / "ckpt_e1.msgpack.corrupt").exists()

    # follower: replays host 0's broadcast, restores the same file
    _pod(monkeypatch, 1)
    monkeypatch.setattr(fleet, "_broadcast_host", lambda _: sent["payload"])
    mgr1 = CheckpointManager(str(tmp_path))
    state1, e1 = fleet.consensus_restore_latest(mgr1, _state(-1.0))
    assert e1 == 1
    np.testing.assert_array_equal(np.asarray(state1.params["w"]),
                                  np.asarray(state0.params["w"]))
    # exactly ONE quarantine rename across the pod
    corrupt = [f for f in os.listdir(tmp_path) if f.endswith(".corrupt")]
    assert corrupt == ["ckpt_e1.msgpack.corrupt"]
    # both hosts contributed the SAME non-zero digest to the agreement
    assert len(gathered) == 2
    assert (gathered[0] == gathered[1]).all() and gathered[0].any()


def test_consensus_digest_mismatch_raises_pod_inconsistent(tmp_path, monkeypatch):
    """A follower whose filesystem view lacks (or disagrees with) host 0's
    chosen checkpoint must fail LOUDLY: rc 9, never a silent split-brain
    resume."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(3.0), 0)
    mgr.wait()
    _pod(monkeypatch, 0)
    monkeypatch.setattr(fleet, "_broadcast_host", lambda p: p)
    sent = {}
    monkeypatch.setattr(fleet, "_broadcast_host",
                        lambda p: sent.setdefault("payload", p))
    monkeypatch.setattr(fleet, "_allgather_host", lambda x: np.stack([x, x]))
    fleet.consensus_restore_latest(mgr, _state(-1.0))

    # follower sees a DIFFERENT file at the broadcast name
    (tmp_path / "ckpt_e0.msgpack").write_bytes(b"not the same bytes at all")
    _pod(monkeypatch, 1)
    monkeypatch.setattr(fleet, "_broadcast_host", lambda _: sent["payload"])

    def mismatched_allgather(x):
        buf = np.asarray(sent["payload"], np.uint8)
        leader = buf[fleet.FLAGS_BYTES + fleet.NAME_BYTES:]
        return np.stack([leader, np.asarray(x)])

    monkeypatch.setattr(fleet, "_allgather_host", mismatched_allgather)
    with pytest.raises(fleet.PodInconsistent, match="host\\(s\\) \\[1\\]"):
        fleet.consensus_restore_latest(CheckpointManager(str(tmp_path)),
                                       _state(-1.0))
    assert fleet.PodInconsistent.exit_code == 9


def test_consensus_fresh_start_agrees_on_nothing(tmp_path, monkeypatch):
    """No checkpoints anywhere: found=0 broadcasts, zero digests agree,
    every host starts at epoch 0 from the template."""
    _pod(monkeypatch, 0)
    monkeypatch.setattr(fleet, "_broadcast_host", lambda p: p)
    monkeypatch.setattr(fleet, "_allgather_host", lambda x: np.stack([x, x]))
    mgr = CheckpointManager(str(tmp_path))
    state, next_epoch = fleet.consensus_restore_latest(mgr, _state(-1.0))
    assert next_epoch == 0
    np.testing.assert_array_equal(np.asarray(state.params["w"]),
                                  np.full((4,), -1.0))


# ------------------------------------------------------------- provenance --
def test_restore_latest_with_provenance_reports_path_and_digest(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(1.0), 0)
    mgr.wait()
    state, next_epoch, path, digest = mgr.restore_latest_with_provenance(
        _state(-1.0))
    assert next_epoch == 1 and path == mgr.epoch_path(0)
    sidecar = (tmp_path / "ckpt_e0.msgpack.sha256").read_text().strip()
    assert digest == sidecar
    # fresh dir: no provenance
    empty = CheckpointManager(str(tmp_path / "empty"))
    _, e, p, d = empty.restore_latest_with_provenance(_state(-1.0))
    assert (e, p, d) == (0, None, None)


def test_restore_exact_rejects_wrong_bytes_and_never_quarantines(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(5.0), 0)
    mgr.wait()
    path = mgr.epoch_path(0)
    good = mgr.file_digest(path)
    restored = mgr.restore_exact(_state(-1.0), path, good)
    np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                  np.full((4,), 5.0))
    assert mgr.restore_exact(_state(-1.0), path, "0" * 64) is None
    assert mgr.restore_exact(_state(-1.0), str(tmp_path / "nope"), good) is None
    # follower-side failures must NOT rename anything (host 0's job)
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".corrupt")]


# --------------------------------------------------------- quarantine race --
def test_quarantine_rename_race_second_is_noop(tmp_path):
    """Two hosts quarantining the same shared-filesystem file: the loser's
    rename hits FileNotFoundError and must be a silent no-op."""
    mgr_a = CheckpointManager(str(tmp_path))
    mgr_a.save(_state(0.0), 0)
    mgr_a.wait()
    path = mgr_a.epoch_path(0)
    mgr_b = CheckpointManager(str(tmp_path))
    mgr_a._quarantine(path, "race test")
    mgr_b._quarantine(path, "race test")  # must not raise
    corrupt = [f for f in os.listdir(tmp_path) if f.endswith(".corrupt")]
    assert corrupt == ["ckpt_e0.msgpack.corrupt"]


def test_verify_checkpoint_tolerates_file_vanishing_mid_verify(tmp_path, monkeypatch):
    """Another host renames the candidate between our existence check and
    the hash: verify must report 'corrupt' (failed candidate), not crash
    the restart chain."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(0.0), 0)
    mgr.wait()
    from ddp_classification_pytorch_tpu.train import checkpoint as ckptlib

    def vanishing(path, chunk=1 << 20):
        raise FileNotFoundError(path)

    monkeypatch.setattr(ckptlib, "_sha256_file", vanishing)
    assert mgr.verify_checkpoint(mgr.epoch_path(0)) == "corrupt"


# ------------------------------------------------------- rendezvous retry --
def test_rendezvous_retries_with_deterministic_backoff_then_succeeds(tmp_path):
    calls, slept = [], []

    def flaky(*a):
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("barrier timed out")

    env = {"FLEET_RENDEZVOUS_ATTEMPTS": "5", "FLEET_RENDEZVOUS_BACKOFF_S": "2",
           "FLEET_RENDEZVOUS_BACKOFF_CAP_S": "60",
           "FLEET_RENDEZVOUS_DEADLINE_S": "600"}
    gen = fleet.initialize_with_retry(
        str(tmp_path), initialize=flaky, sleep=slept.append, env=env)
    assert len(calls) == 3 and gen == 0
    assert slept == [2.0, 4.0]  # the shared deterministic schedule


def test_rendezvous_exhaustion_raises_rc6(tmp_path):
    def never(*a):
        raise ConnectionRefusedError("coordinator down")

    env = {"FLEET_RENDEZVOUS_ATTEMPTS": "3", "FLEET_RENDEZVOUS_BACKOFF_S": "1",
           "FLEET_RENDEZVOUS_DEADLINE_S": "600"}
    with pytest.raises(fleet.RendezvousFailed, match="3 attempts"):
        fleet.initialize_with_retry(str(tmp_path), initialize=never,
                                    sleep=lambda s: None, env=env)
    assert fleet.RendezvousFailed.exit_code == 6


def test_rendezvous_deadline_cuts_the_schedule_short():
    calls = []

    def never(*a):
        calls.append(1)
        raise TimeoutError("x")

    env = {"FLEET_RENDEZVOUS_ATTEMPTS": "10",
           "FLEET_RENDEZVOUS_BACKOFF_S": "1000",
           "FLEET_RENDEZVOUS_DEADLINE_S": "1"}
    with pytest.raises(fleet.RendezvousFailed):
        fleet.initialize_with_retry(initialize=never, sleep=lambda s: None,
                                    env=env)
    assert len(calls) == 1  # first sleep would blow the deadline: stop now

    assert fleet.backoff_schedule(4, 5, 60) == [5.0, 10.0, 20.0]
    assert fleet.backoff_schedule(6, 30, 60) == [30.0, 60.0, 60.0, 60.0, 60.0]


def test_rendezvous_reads_generation_for_logging(tmp_path):
    fleet.advance_generation(fleet.generation_path(str(tmp_path)), 4)
    gen = fleet.initialize_with_retry(
        str(tmp_path), initialize=lambda *a: None, sleep=lambda s: None,
        env={})
    assert gen == 4


# --------------------------------------------------------- generation file --
def test_generation_file_monotonicity(tmp_path):
    path = fleet.generation_path(str(tmp_path))
    assert fleet.read_generation(path) == 0  # absent
    assert fleet.advance_generation(path, 2) == 2
    assert fleet.read_generation(path) == 2
    assert fleet.advance_generation(path, 1) == 2  # never regresses
    assert fleet.read_generation(path) == 2
    assert fleet.advance_generation(path, 5) == 5
    with open(path, "w") as f:
        f.write("garbage\n")
    assert fleet.read_generation(path) == 0  # torn write never bricks


# -------------------------------------------------------- abort propagation --
def test_abort_exchange_max_code_wins_on_every_host(monkeypatch):
    # (n, 2) wire: [abort_code, reform_flag] per host, one collective
    recorded = np.asarray([[0, 0], [8, 0]], np.int32)
    monkeypatch.setattr(fleet, "_allgather_host", lambda x: recorded)
    co = fleet.FleetCoordinator(process_index=0, process_count=2)
    code, origin = co.exchange_abort()
    assert (code, origin) == (8, 1)
    with pytest.raises(fleet.PodAbort) as ei:
        co.check()
    assert ei.value.code == 8 and ei.value.origin == 1
    assert "host 1" in str(ei.value)


def test_abort_note_first_intent_wins_and_clean_exchange_is_silent(monkeypatch):
    co = fleet.FleetCoordinator(process_index=1, process_count=2)
    co.note_abort(143, "SIGTERM received")
    co.note_abort(8, "late sentinel")  # first cause wins locally
    assert co.abort_code == 143 and "SIGTERM" in co.abort_reason
    monkeypatch.setattr(
        fleet, "_allgather_host",
        lambda x: np.asarray([[0, 0], [co.abort_code, 0]], np.int32))
    code, origin = co.exchange_abort()
    assert (code, origin) == (143, 1)

    clean = fleet.FleetCoordinator(process_index=0, process_count=2)
    monkeypatch.setattr(fleet, "_allgather_host",
                        lambda x: np.zeros((2, 2), np.int32))
    assert clean.exchange_abort() == (0, -1)
    clean.check()  # no intent anywhere: no raise, training continues


def test_abort_single_process_shortcircuits(monkeypatch):
    monkeypatch.setattr(fleet, "_allgather_host",
                        lambda x: pytest.fail("collective on single host"))
    co = fleet.FleetCoordinator(process_index=0, process_count=1)
    co.check()
    co.note_abort(8, "diverged")
    with pytest.raises(fleet.PodAbort) as ei:
        co.check()
    assert ei.value.code == 8 and "this host" in str(ei.value)


# ------------------------------------------------------------- pod chaos --
def test_peer_fault_parsing_and_step_only_units():
    plan = chaoslib.FaultPlan.parse("peer_dead@step=6,peer_slow@step=3..4")
    assert [f.kind for f in plan.faults] == ["peer_dead", "peer_slow"]
    for bad in ("peer_dead@epoch=1", "peer_slow@batch=2"):
        with pytest.raises(ValueError, match="keyed by the host-side step"):
            chaoslib.FaultPlan.parse(bad)


def test_chaos_host_gate_aims_faults_at_one_process(monkeypatch):
    spec = "peer_dead@step=6,nan_loss@step=1..2,sigterm@step=9"
    monkeypatch.setenv(chaoslib.ENV_HOST, "1")
    miss = chaoslib.FaultPlan.parse(spec, process_index=0)
    assert miss.host_gated()
    assert miss.should_fire("peer_dead", step=6) is None
    assert miss.should_fire("sigterm", step=9) is None
    assert miss.windows("nan_loss") == []  # peers compile the clean step
    hit = chaoslib.FaultPlan.parse(spec, process_index=1)
    assert not hit.host_gated()
    assert hit.windows("nan_loss") == [(1, 2)]
    assert hit.should_fire("peer_dead", step=6) is not None
    monkeypatch.delenv(chaoslib.ENV_HOST)
    # unset ⇒ every host (bit-identical to the pre-pod behavior)
    any_host = chaoslib.FaultPlan.parse(spec, process_index=3)
    assert not any_host.host_gated()
    assert any_host.windows("nan_loss") == [(1, 2)]


def test_peer_dead_sigkills_self_once(monkeypatch):
    kills = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: kills.append((pid, sig)))
    plan = chaoslib.FaultPlan.parse("peer_dead@step=6", process_index=0)
    plan.maybe_peer_dead(step=5)
    assert kills == []
    plan.maybe_peer_dead(step=6)
    assert kills == [(os.getpid(), signal.SIGKILL)]
    plan.maybe_peer_dead(step=6)  # one-shot
    assert len(kills) == 1


def test_host_lost_sigkills_own_process_group_once(monkeypatch):
    """host_lost must take out the WHOLE process group (supervisor and
    trainer — a machine loss, not a process loss), exactly once."""
    kills = []
    monkeypatch.setattr(os, "getpgid", lambda pid: 4242)
    monkeypatch.setattr(os, "killpg",
                        lambda pg, sig: kills.append((pg, sig)))
    plan = chaoslib.FaultPlan.parse("host_lost@step=6", process_index=0)
    plan.maybe_host_lost(step=5)
    assert kills == []
    plan.maybe_host_lost(step=6)
    assert kills == [(4242, signal.SIGKILL)]
    plan.maybe_host_lost(step=6)  # one-shot
    assert len(kills) == 1
    # step-keyed only, like the other pod faults
    with pytest.raises(ValueError, match="keyed by the host-side step"):
        chaoslib.FaultPlan.parse("host_lost@epoch=1")


def test_peer_slow_stalls_configured_seconds(monkeypatch):
    import time as timelib

    stalls = []
    monkeypatch.setattr(timelib, "sleep", lambda s: stalls.append(s))
    monkeypatch.setenv(chaoslib.ENV_PEER_SLOW_S, "2.5")
    plan = chaoslib.FaultPlan.parse("peer_slow@step=3")
    plan.maybe_peer_slow(step=3)
    assert stalls == [2.5]
    plan.maybe_peer_slow(step=3)  # one-shot
    assert stalls == [2.5]


def test_peer_fault_markers_are_per_host(tmp_path):
    """Shared state_dir on a pod: host 0 firing must not consume host 1's
    one shot."""
    spec = "peer_slow@step=3"
    p0 = chaoslib.FaultPlan.parse(spec, state_dir=str(tmp_path),
                                  process_index=0)
    assert p0.should_fire("peer_slow", step=3) is not None
    p1 = chaoslib.FaultPlan.parse(spec, state_dir=str(tmp_path),
                                  process_index=1)
    assert p1.should_fire("peer_slow", step=3) is not None
    # but the SAME host's restart does not re-fire
    p0b = chaoslib.FaultPlan.parse(spec, state_dir=str(tmp_path),
                                   process_index=0)
    assert p0b.should_fire("peer_slow", step=3) is None


# ------------------------------------------------------ elastic membership --
# Minimal explicit-pod env for the elastic path: host 0 of a configured
# 2-host world, instant settle, generous TTL (tests backdate mtimes to
# expire leases deterministically instead of sleeping).
ELASTIC_ENV = {
    "FLEET_ELASTIC": "1",
    "FLEET_COORDINATOR": "localhost:1",
    "FLEET_NUM_PROCESSES": "2",
    "FLEET_PROCESS_ID": "0",
    "FLEET_HOST_ID": "0",
    "FLEET_LEASE_TTL_S": "100",
    "FLEET_LEASE_SETTLE_S": "0",
    "FLEET_RENDEZVOUS_ATTEMPTS": "2",
    "FLEET_RENDEZVOUS_BACKOFF_S": "0",
}


def _expire_lease(out_dir, host_id):
    p = fleet.lease_path(str(out_dir), host_id)
    os.utime(p, (os.stat(p).st_mtime - 1000,) * 2)


def test_lease_write_scan_and_stale_expiry(tmp_path):
    out = str(tmp_path)
    fleet.write_lease(out, 0, generation=3, coordinator="h0:12")
    fleet.write_lease(out, 1, generation=3, coordinator="h1:12")
    assert fleet.scan_leases(out, ttl_s=100) == {0: "h0:12", 1: "h1:12"}
    # a lease past its TTL (mtime) is a dead host
    _expire_lease(tmp_path, 1)
    assert fleet.scan_leases(out, ttl_s=100) == {0: "h0:12"}
    # re-writing IS the heartbeat: the lease comes back fresh
    fleet.write_lease(out, 1, generation=4, coordinator="h1:12")
    assert sorted(fleet.scan_leases(out, ttl_s=100)) == [0, 1]
    # junk files in the fleet dir never brick the scan
    (tmp_path / "fleet" / "lease.pX").write_text("not a lease\n")
    (tmp_path / "fleet" / "membership").write_text("gen=1 world=0,1\n")
    assert sorted(fleet.scan_leases(out, ttl_s=100)) == [0, 1]


def test_membership_file_roundtrip_and_garbled(tmp_path):
    out = str(tmp_path)
    assert fleet.read_membership(out) == (0, [])  # absent
    fleet.write_membership(out, 4, [0, 2])
    assert fleet.read_membership(out) == (4, [0, 2])
    with open(fleet.membership_path(out), "w") as f:
        f.write("gen=x world=banana\n")  # torn/garbled ⇒ (0, []) not a crash
    assert fleet.read_membership(out) == (0, [])


def test_validate_fleet_env_malformed_is_rc2():
    assert fleet.FleetConfigError.exit_code == 2
    assert issubclass(fleet.FleetConfigError, ValueError)
    with pytest.raises(fleet.FleetConfigError, match="FLEET_NUM_PROCESSES"):
        fleet.validate_fleet_env({"FLEET_COORDINATOR": "localhost:1",
                                  "FLEET_NUM_PROCESSES": "two",
                                  "FLEET_PROCESS_ID": "0"})
    with pytest.raises(fleet.FleetConfigError, match="host:port"):
        fleet.validate_fleet_env({"FLEET_COORDINATOR": "localhost",
                                  "FLEET_NUM_PROCESSES": "2",
                                  "FLEET_PROCESS_ID": "0"})
    with pytest.raises(fleet.FleetConfigError, match="all three"):
        fleet.validate_fleet_env({"FLEET_COORDINATOR": "localhost:1"})
    with pytest.raises(fleet.FleetConfigError, match="outside the world"):
        fleet.validate_fleet_env({"FLEET_COORDINATOR": "localhost:1",
                                  "FLEET_NUM_PROCESSES": "2",
                                  "FLEET_PROCESS_ID": "5"})
    with pytest.raises(fleet.FleetConfigError, match="FLEET_HOST_ID"):
        fleet.validate_fleet_env({"FLEET_HOST_ID": "-3"})


def test_elastic_first_boot_full_world_is_not_a_reform(tmp_path):
    """Both configured hosts alive at first boot: the derived world equals
    the configured one, generation stays put, and no re-formation is
    recorded — elastic must be bit-identical to static when nothing died."""
    out = str(tmp_path)
    fleet.write_lease(out, 1, generation=0, coordinator="")
    calls = []
    gen = fleet.initialize_with_retry(
        out, initialize=lambda c, n, p: calls.append((c, n, p)),
        sleep=lambda s: None, env=dict(ELASTIC_ENV))
    assert calls == [("localhost:1", 2, 0)]
    assert gen == 0
    assert fleet.read_membership(out) == (0, [0, 1])
    assert fleet._CURRENT_MEMBERSHIP == (0, (0, 1))


def test_elastic_survivor_reforms_shrunken_world_at_next_generation(tmp_path):
    """Host 1's lease expired while membership records [0, 1]: host 0
    re-forms alone — rank 0 of a 1-process world, generation bumped, new
    membership cached (the single writer is the lowest survivor)."""
    out = str(tmp_path)
    fleet.write_membership(out, 1, [0, 1])
    fleet.write_lease(out, 1, generation=1, coordinator="h1:9")
    _expire_lease(tmp_path, 1)
    calls = []
    gen = fleet.initialize_with_retry(
        out, initialize=lambda c, n, p: calls.append((c, n, p)),
        sleep=lambda s: None, env=dict(ELASTIC_ENV))
    assert calls == [("localhost:1", 1, 0)]
    assert gen == 2  # stored gen 1 + re-formation
    assert fleet.read_membership(out) == (2, [0])
    # the generation file was advanced so every supervisor paces gen 2
    assert fleet.read_generation(fleet.generation_path(out)) == 2


def test_elastic_rejoin_restores_full_world_at_later_generation(tmp_path):
    """The recovered host wrote a fresh lease while membership records the
    shrunken [0]: the next round re-forms [0, 1] at a LATER generation —
    a rejoin is a re-formation, never a rewind."""
    out = str(tmp_path)
    fleet.write_membership(out, 2, [0])
    fleet.write_lease(out, 1, generation=2, coordinator="h1:9")
    calls = []
    gen = fleet.initialize_with_retry(
        out, initialize=lambda c, n, p: calls.append((c, n, p)),
        sleep=lambda s: None, env=dict(ELASTIC_ENV))
    assert calls == [("localhost:1", 2, 0)]
    assert gen == 3
    assert fleet.read_membership(out) == (3, [0, 1])


def test_elastic_rejoiner_waits_for_survivors_to_reform(tmp_path):
    """A recovered host whose fresh lease is NOT yet in the cached
    membership must WAIT in the retry loop — connecting would abort
    against a coordinator sized for the old world — and join as a
    follower only once the writer records a world containing it."""
    out = str(tmp_path)
    env = dict(ELASTIC_ENV)
    env["FLEET_PROCESS_ID"] = "1"
    env["FLEET_HOST_ID"] = "1"
    fleet.write_membership(out, 2, [0])
    fleet.write_lease(out, 0, generation=2, coordinator="h0:9")
    calls = []
    with pytest.raises(fleet.RendezvousFailed, match="re-form"):
        fleet.initialize_with_retry(
            out, initialize=lambda *a: calls.append(a),
            sleep=lambda s: None, env=env)
    assert calls == []  # never connected into the old world
    # only the writer (lowest survivor) records the new membership
    assert fleet.read_membership(out) == (2, [0])
    # the survivors re-formed around us: join as rank 1 of their world
    fleet.write_membership(out, 3, [0, 1])
    gen = fleet.initialize_with_retry(
        out, initialize=lambda *a: calls.append(a),
        sleep=lambda s: None, env=env)
    assert calls == [("h0:9", 2, 1)]
    assert gen == 3


def test_elastic_unviable_below_min_processes_is_rc10_not_a_hang(tmp_path):
    """A survivor set below FLEET_MIN_PROCESSES must raise PodUnviable
    (rc 10) immediately — never burn the rendezvous retry budget waiting
    for a world that cannot form."""
    assert fleet.PodUnviable.exit_code == 10
    env = dict(ELASTIC_ENV)
    env["FLEET_MIN_PROCESSES"] = "2"
    attempts = []
    with pytest.raises(fleet.PodUnviable, match="rc 10"):
        fleet.initialize_with_retry(
            str(tmp_path), initialize=lambda *a: attempts.append(a),
            sleep=lambda s: None, env=env)
    assert attempts == []  # failed the viability gate, not the rendezvous


def test_elastic_unviable_mesh_is_rc10(tmp_path):
    """A survivor world whose device count cannot cover the configured
    mesh is equally unviable — the gate consults mesh.viable_world."""
    from ddp_classification_pytorch_tpu.parallel.mesh import MeshSpec

    with pytest.raises(fleet.PodUnviable, match="mesh"):
        fleet.initialize_with_retry(
            str(tmp_path), initialize=lambda *a: None,
            sleep=lambda s: None, env=dict(ELASTIC_ENV),
            mesh_spec=MeshSpec(model_parallel=3))
    # the same 1-host world with a coverable mesh rendezvouses fine
    fleet.initialize_with_retry(
        str(tmp_path), initialize=lambda *a: None, sleep=lambda s: None,
        env=dict(ELASTIC_ENV), mesh_spec=MeshSpec())


def test_confirm_membership_split_brain_is_rc9(monkeypatch):
    """Two hosts rendezvoused with different derived worlds: the digest
    agreement must kill BOTH (rc 9), never train split-brained."""
    _pod(monkeypatch, 0, count=2)
    a = fleet._encode_fixed(fleet.membership_digest([0, 1]),
                            fleet.DIGEST_BYTES)
    b = fleet._encode_fixed(fleet.membership_digest([0]),
                            fleet.DIGEST_BYTES)
    monkeypatch.setattr(fleet, "_allgather_host",
                        lambda x: np.stack([a, b]))
    with pytest.raises(fleet.PodInconsistent, match="split-brain"):
        fleet.confirm_membership([0, 1])
    # agreement passes silently
    monkeypatch.setattr(fleet, "_allgather_host",
                        lambda x: np.stack([a, a]))
    fleet.confirm_membership([0, 1])
    # single process: no collective at all
    _pod(monkeypatch, 0, count=1)
    monkeypatch.setattr(fleet, "_allgather_host",
                        lambda x: pytest.fail("collective on single host"))
    fleet.confirm_membership([0])


def _elastic_environ(monkeypatch, tmp_path):
    for k, v in ELASTIC_ENV.items():
        monkeypatch.setenv(k, v)
    monkeypatch.setenv("FLEET_MIN_PROCESSES", "1")


def test_coordinator_detects_membership_change_as_rc11(tmp_path, monkeypatch):
    """A running 1-host pod whose membership was (0,): a recovered host's
    fresh lease flips the epoch-boundary exchange into PodReform (rc 11)
    — and an abort intent outranks the reform."""
    assert fleet.PodReform.exit_code == 11
    _elastic_environ(monkeypatch, tmp_path)
    monkeypatch.setattr(fleet, "_CURRENT_MEMBERSHIP", (2, (0,)))
    co = fleet.FleetCoordinator(process_index=0, process_count=1,
                                out_dir=str(tmp_path))
    assert co.elastic and co.membership == (2, (0,))
    co.check()  # world still {0}: no abort, no reform
    fleet.write_lease(str(tmp_path), 1, generation=2, coordinator="h1:9")
    with pytest.raises(fleet.PodReform, match="rc 11"):
        co.check()
    co.note_abort(8, "diverged")
    with pytest.raises(fleet.PodAbort) as ei:
        co.check()  # abort wins over reform
    assert ei.value.code == 8


def test_coordinator_refresh_lease_heartbeats_mtime(tmp_path, monkeypatch):
    _elastic_environ(monkeypatch, tmp_path)
    monkeypatch.setattr(fleet, "_CURRENT_MEMBERSHIP", (1, (0,)))
    co = fleet.FleetCoordinator(process_index=0, process_count=1,
                                out_dir=str(tmp_path))
    co.refresh_lease()
    _expire_lease(tmp_path, 0)
    assert fleet.scan_leases(str(tmp_path), ttl_s=100) == {}
    co.refresh_lease()  # the heartbeat resurrects the mtime
    assert sorted(fleet.scan_leases(str(tmp_path), ttl_s=100)) == [0]
    # non-elastic coordinators are inert (no fleet dir ever created)
    inert = fleet.FleetCoordinator(process_index=0, process_count=1)
    assert not inert.elastic
    inert.refresh_lease()


# --------------------------------------------------- supervise.sh discipline --
STUB = """#!/usr/bin/env bash
state="${FAKE_STATE:?}"
n=$(cat "$state" 2>/dev/null || echo 0)
n=$((n+1)); echo "$n" > "$state"
rc=$(echo "${FAKE_RCS:?}" | tr ',' '\\n' | sed -n "${n}p")
[ -z "$rc" ] && rc=$(echo "$FAKE_RCS" | tr ',' '\\n' | tail -1)
exit "$rc"
"""


def _stub_env(tmp_path, rcs):
    fakebin = tmp_path / "bin"
    fakebin.mkdir(exist_ok=True)
    stub = fakebin / "python"
    stub.write_text(STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IXUSR)
    env = dict(os.environ)
    env["PATH"] = f"{fakebin}:{env['PATH']}"
    env["FAKE_STATE"] = str(tmp_path / "calls")
    env["FAKE_RCS"] = rcs
    return env


def test_supervise_rc6_rendezvous_gets_outage_backoff_and_host_fields(tmp_path):
    out = tmp_path / "out"
    env = _stub_env(tmp_path, "6,0")
    env["OUTAGE_BACKOFF_S"] = "0"
    env["FLEET_PROCESS_ID"] = "1"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"),
         "baseline", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 0, p.stderr
    lines = (out / "restarts.log").read_text().strip().splitlines()
    assert len(lines) == 2  # the rc-6 restart + the final clean exit
    assert "rc=6" in lines[0] and "action=restart" in lines[0]
    assert "backoff=0s" in lines[0]  # OUTAGE_BACKOFF_S was honored
    assert "host=" in lines[0] and "proc=1" in lines[0]
    # gen=/world= fields ride every line; "-" when no membership file
    assert "gen=- world=-" in lines[0]
    assert "rc=0" in lines[1] and "action=exit" in lines[1]
    # the restart wave max-wrote its attempt into the shared generation file
    assert (out / "generation").read_text().strip() == "1"


def test_supervise_rc9_pod_inconsistent_is_retried(tmp_path):
    out = tmp_path / "out"
    env = _stub_env(tmp_path, "9,0")
    env["RUNTIME_BACKOFF_S"] = "0"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"),
         "baseline", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 0, p.stderr
    log = (out / "restarts.log").read_text()
    assert "rc=9" in log and "action=restart" in log


def test_supervise_generation_is_monotonic_across_waves(tmp_path):
    out = tmp_path / "out"
    out.mkdir()
    (out / "generation").write_text("7\n")  # a peer is already at wave 7
    env = _stub_env(tmp_path, "143,143,0")
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"),
         "baseline", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, p.stderr
    # our attempts (1, 2) never regress the shared file below the peer's 7
    assert (out / "generation").read_text().strip() == "7"


def test_supervise_rc10_pod_unviable_gets_outage_backoff(tmp_path):
    out = tmp_path / "out"
    env = _stub_env(tmp_path, "10,0")
    env["OUTAGE_BACKOFF_S"] = "0"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"),
         "baseline", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 0, p.stderr
    log = (out / "restarts.log").read_text()
    assert "rc=10" in log and "action=restart" in log
    assert "backoff=0s" in log  # pod-unviable waits the OUTAGE backoff


# a stub that also records the FLEET_* world each (re)spawn saw, so the
# re-export into a re-formed membership is observable from outside
ENV_STUB = """#!/usr/bin/env bash
state="${FAKE_STATE:?}"
n=$(cat "$state" 2>/dev/null || echo 0)
n=$((n+1)); echo "$n" > "$state"
echo "pid=${FLEET_PROCESS_ID:-?}/${FLEET_NUM_PROCESSES:-?}" >> "${FAKE_ENVLOG:?}"
rc=$(echo "${FAKE_RCS:?}" | tr ',' '\\n' | sed -n "${n}p")
[ -z "$rc" ] && rc=0
exit "$rc"
"""


def _env_stub_env(tmp_path, rcs):
    env = _stub_env(tmp_path, rcs)
    (tmp_path / "bin" / "python").write_text(ENV_STUB)
    env["FAKE_ENVLOG"] = str(tmp_path / "envlog")
    return env


def test_supervise_rc11_respawns_into_reformed_world(tmp_path):
    """rc 11 restarts FAST and re-exports this host's rank/size from the
    cached membership; restarts.log carries the gen=/world= fields."""
    out = tmp_path / "out"
    (out / "fleet").mkdir(parents=True)
    (out / "fleet" / "membership").write_text("gen=3 world=0,2\n")
    env = _env_stub_env(tmp_path, "11,0")
    env["REFORM_BACKOFF_S"] = "0"
    env["FLEET_ELASTIC"] = "1"
    env["FLEET_HOST_ID"] = "2"
    env["FLEET_PROCESS_ID"] = "2"
    env["FLEET_NUM_PROCESSES"] = "3"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"),
         "baseline", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 0, p.stderr
    lines = (out / "restarts.log").read_text().strip().splitlines()
    assert "rc=11" in lines[0] and "action=restart" in lines[0]
    assert "backoff=0s" in lines[0]  # REFORM_BACKOFF_S: fast restart
    assert "gen=3 world=0,2" in lines[0] and "proc=2" in lines[0]
    # launch env 2/3; respawn re-exported as rank 1 of the 2-host world
    envlog = (tmp_path / "envlog").read_text().splitlines()
    assert envlog == ["pid=2/3", "pid=1/2"]


def test_supervise_rejoiner_outside_cached_world_keeps_launch_env(tmp_path):
    """A recovered host NOT (yet) in the cached membership must respawn
    with its launch env — it rejoins when the survivors re-form around
    its fresh lease, not by guessing a rank in a world it isn't in."""
    out = tmp_path / "out"
    (out / "fleet").mkdir(parents=True)
    (out / "fleet" / "membership").write_text("gen=4 world=0\n")
    env = _env_stub_env(tmp_path, "11,0")
    env["REFORM_BACKOFF_S"] = "0"
    env["FLEET_ELASTIC"] = "1"
    env["FLEET_HOST_ID"] = "1"
    env["FLEET_PROCESS_ID"] = "1"
    env["FLEET_NUM_PROCESSES"] = "2"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"),
         "baseline", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 0, p.stderr
    envlog = (tmp_path / "envlog").read_text().splitlines()
    assert envlog == ["pid=1/2", "pid=1/2"]


# ---------------------------------------------------------- full pod drill --
@pytest.mark.slow
def test_pod_chaos_drill(tmp_path):
    """The real thing: scripts/chaos_drill.sh phases 3-5 drive TWO
    supervised hosts (4 virtual CPU devices each, gloo for DCN) through
    peer_dead, a corrupt shared checkpoint, and a one-host sustained NaN —
    asserting coordinated restart into one generation, consensus resume
    with exactly one quarantine, and symmetric rc 8 with no spurious
    rc 7."""
    env = {k: v for k, v in os.environ.items()
           if k not in (chaoslib.ENV_SPEC, chaoslib.ENV_STATE_DIR,
                        chaoslib.ENV_HOST)}
    env["CHAOS_PHASES"] = "3 4 5"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "chaos_drill.sh"),
         str(tmp_path / "drill")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=2400)
    assert p.returncode == 0, (p.stdout[-5000:], p.stderr[-2000:])
    assert "CHAOS DRILL PASS" in p.stdout


@pytest.mark.slow
def test_pod_elastic_drill(tmp_path):
    """Elastic acceptance (chaos_drill.sh phases 6-7): SIGKILL of host 1's
    whole process group mid-run ⇒ host 0 re-forms as a 1-host pod within
    one generation and keeps training; host 1 relaunches, rejoins at a
    later generation, and the 2-host pod converges rc 0 from the last
    verified checkpoint. Then the same loss under FLEET_MIN_PROCESSES=2
    ⇒ deterministic rc 10 on the survivor — never a hang."""
    env = {k: v for k, v in os.environ.items()
           if k not in (chaoslib.ENV_SPEC, chaoslib.ENV_STATE_DIR,
                        chaoslib.ENV_HOST)}
    env["CHAOS_PHASES"] = "6 7"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "chaos_drill.sh"),
         str(tmp_path / "drill")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=2400)
    assert p.returncode == 0, (p.stdout[-5000:], p.stderr[-2000:])
    assert "CHAOS DRILL PASS" in p.stdout
