"""Pod-level fault-tolerance tests (parallel/fleet.py) — tier-1-lean.

Every pod topology here is SIMULATED in one process: the fleet module's
collective primitives (`_process_index` / `_process_count` /
`_broadcast_host` / `_allgather_host`) are monkeypatched with recorded
payloads, so consensus, abort propagation, rendezvous retry, and the
generation file are all exercised without a second process or a single
jit compile. The real two-process pod drill is scripts/chaos_drill.sh
phase 3+ (`test_pod_chaos_drill`, marked slow).
"""

import os
import signal
import stat
import subprocess

import numpy as np
import pytest

import jax.numpy as jnp

from ddp_classification_pytorch_tpu.parallel import fleet
from ddp_classification_pytorch_tpu.train.checkpoint import CheckpointManager
from ddp_classification_pytorch_tpu.train.state import TrainState
from ddp_classification_pytorch_tpu.utils import chaos as chaoslib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _state(v: float) -> TrainState:
    return TrainState(
        step=jnp.asarray(int(v)),
        params={"w": jnp.full((4,), v)},
        batch_stats={"m": jnp.zeros((2,))},
        opt_state=(),
    )


def _pod(monkeypatch, index: int, count: int = 2):
    monkeypatch.setattr(fleet, "_process_index", lambda: index)
    monkeypatch.setattr(fleet, "_process_count", lambda: count)


# --------------------------------------------------------------- consensus --
def test_consensus_single_process_is_plain_restore_latest(tmp_path, monkeypatch):
    """pcount == 1 must take the existing restore_latest path and touch no
    collective primitive at all."""
    _pod(monkeypatch, 0, count=1)
    monkeypatch.setattr(fleet, "_broadcast_host",
                        lambda p: pytest.fail("collective on single host"))
    monkeypatch.setattr(fleet, "_allgather_host",
                        lambda x: pytest.fail("collective on single host"))
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(2.0), 0)
    mgr.wait()
    restored, next_epoch = fleet.consensus_restore_latest(mgr, _state(-1.0))
    assert next_epoch == 1
    np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                  np.full((4,), 2.0))


def test_consensus_leader_quarantines_follower_restores_exact(tmp_path, monkeypatch):
    """The acceptance shape: corrupt latest on shared storage ⇒ host 0
    quarantines it ONCE, broadcasts the older verified candidate, the
    follower restores that exact file (no second scan, no second rename),
    and the digest agreement passes."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(0.0), 0)
    mgr.save(_state(1.0), 1)
    mgr.wait()
    p = tmp_path / "ckpt_e1.msgpack"
    p.write_bytes(p.read_bytes()[: 20])  # torn latest

    sent = {}

    def record_broadcast(payload):
        sent["payload"] = payload
        return payload

    gathered = []

    def agree_allgather(x):
        gathered.append(np.asarray(x))
        return np.stack([x, x])

    _pod(monkeypatch, 0)
    monkeypatch.setattr(fleet, "_broadcast_host", record_broadcast)
    monkeypatch.setattr(fleet, "_allgather_host", agree_allgather)
    state0, e0 = fleet.consensus_restore_latest(mgr, _state(-1.0))
    assert e0 == 1
    np.testing.assert_array_equal(np.asarray(state0.params["w"]), np.zeros(4))
    assert (tmp_path / "ckpt_e1.msgpack.corrupt").exists()

    # follower: replays host 0's broadcast, restores the same file
    _pod(monkeypatch, 1)
    monkeypatch.setattr(fleet, "_broadcast_host", lambda _: sent["payload"])
    mgr1 = CheckpointManager(str(tmp_path))
    state1, e1 = fleet.consensus_restore_latest(mgr1, _state(-1.0))
    assert e1 == 1
    np.testing.assert_array_equal(np.asarray(state1.params["w"]),
                                  np.asarray(state0.params["w"]))
    # exactly ONE quarantine rename across the pod
    corrupt = [f for f in os.listdir(tmp_path) if f.endswith(".corrupt")]
    assert corrupt == ["ckpt_e1.msgpack.corrupt"]
    # both hosts contributed the SAME non-zero digest to the agreement
    assert len(gathered) == 2
    assert (gathered[0] == gathered[1]).all() and gathered[0].any()


def test_consensus_digest_mismatch_raises_pod_inconsistent(tmp_path, monkeypatch):
    """A follower whose filesystem view lacks (or disagrees with) host 0's
    chosen checkpoint must fail LOUDLY: rc 9, never a silent split-brain
    resume."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(3.0), 0)
    mgr.wait()
    _pod(monkeypatch, 0)
    monkeypatch.setattr(fleet, "_broadcast_host", lambda p: p)
    sent = {}
    monkeypatch.setattr(fleet, "_broadcast_host",
                        lambda p: sent.setdefault("payload", p))
    monkeypatch.setattr(fleet, "_allgather_host", lambda x: np.stack([x, x]))
    fleet.consensus_restore_latest(mgr, _state(-1.0))

    # follower sees a DIFFERENT file at the broadcast name
    (tmp_path / "ckpt_e0.msgpack").write_bytes(b"not the same bytes at all")
    _pod(monkeypatch, 1)
    monkeypatch.setattr(fleet, "_broadcast_host", lambda _: sent["payload"])

    def mismatched_allgather(x):
        buf = np.asarray(sent["payload"], np.uint8)
        leader = buf[fleet.FLAGS_BYTES + fleet.NAME_BYTES:]
        return np.stack([leader, np.asarray(x)])

    monkeypatch.setattr(fleet, "_allgather_host", mismatched_allgather)
    with pytest.raises(fleet.PodInconsistent, match="host\\(s\\) \\[1\\]"):
        fleet.consensus_restore_latest(CheckpointManager(str(tmp_path)),
                                       _state(-1.0))
    assert fleet.PodInconsistent.exit_code == 9


def test_consensus_fresh_start_agrees_on_nothing(tmp_path, monkeypatch):
    """No checkpoints anywhere: found=0 broadcasts, zero digests agree,
    every host starts at epoch 0 from the template."""
    _pod(monkeypatch, 0)
    monkeypatch.setattr(fleet, "_broadcast_host", lambda p: p)
    monkeypatch.setattr(fleet, "_allgather_host", lambda x: np.stack([x, x]))
    mgr = CheckpointManager(str(tmp_path))
    state, next_epoch = fleet.consensus_restore_latest(mgr, _state(-1.0))
    assert next_epoch == 0
    np.testing.assert_array_equal(np.asarray(state.params["w"]),
                                  np.full((4,), -1.0))


# ------------------------------------------------------------- provenance --
def test_restore_latest_with_provenance_reports_path_and_digest(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(1.0), 0)
    mgr.wait()
    state, next_epoch, path, digest = mgr.restore_latest_with_provenance(
        _state(-1.0))
    assert next_epoch == 1 and path == mgr.epoch_path(0)
    sidecar = (tmp_path / "ckpt_e0.msgpack.sha256").read_text().strip()
    assert digest == sidecar
    # fresh dir: no provenance
    empty = CheckpointManager(str(tmp_path / "empty"))
    _, e, p, d = empty.restore_latest_with_provenance(_state(-1.0))
    assert (e, p, d) == (0, None, None)


def test_restore_exact_rejects_wrong_bytes_and_never_quarantines(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(5.0), 0)
    mgr.wait()
    path = mgr.epoch_path(0)
    good = mgr.file_digest(path)
    restored = mgr.restore_exact(_state(-1.0), path, good)
    np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                  np.full((4,), 5.0))
    assert mgr.restore_exact(_state(-1.0), path, "0" * 64) is None
    assert mgr.restore_exact(_state(-1.0), str(tmp_path / "nope"), good) is None
    # follower-side failures must NOT rename anything (host 0's job)
    assert not [f for f in os.listdir(tmp_path) if f.endswith(".corrupt")]


# --------------------------------------------------------- quarantine race --
def test_quarantine_rename_race_second_is_noop(tmp_path):
    """Two hosts quarantining the same shared-filesystem file: the loser's
    rename hits FileNotFoundError and must be a silent no-op."""
    mgr_a = CheckpointManager(str(tmp_path))
    mgr_a.save(_state(0.0), 0)
    mgr_a.wait()
    path = mgr_a.epoch_path(0)
    mgr_b = CheckpointManager(str(tmp_path))
    mgr_a._quarantine(path, "race test")
    mgr_b._quarantine(path, "race test")  # must not raise
    corrupt = [f for f in os.listdir(tmp_path) if f.endswith(".corrupt")]
    assert corrupt == ["ckpt_e0.msgpack.corrupt"]


def test_verify_checkpoint_tolerates_file_vanishing_mid_verify(tmp_path, monkeypatch):
    """Another host renames the candidate between our existence check and
    the hash: verify must report 'corrupt' (failed candidate), not crash
    the restart chain."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(0.0), 0)
    mgr.wait()
    from ddp_classification_pytorch_tpu.train import checkpoint as ckptlib

    def vanishing(path, chunk=1 << 20):
        raise FileNotFoundError(path)

    monkeypatch.setattr(ckptlib, "_sha256_file", vanishing)
    assert mgr.verify_checkpoint(mgr.epoch_path(0)) == "corrupt"


# ------------------------------------------------------- rendezvous retry --
def test_rendezvous_retries_with_deterministic_backoff_then_succeeds(tmp_path):
    calls, slept = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TimeoutError("barrier timed out")

    env = {"FLEET_RENDEZVOUS_ATTEMPTS": "5", "FLEET_RENDEZVOUS_BACKOFF_S": "2",
           "FLEET_RENDEZVOUS_BACKOFF_CAP_S": "60",
           "FLEET_RENDEZVOUS_DEADLINE_S": "600"}
    gen = fleet.initialize_with_retry(
        str(tmp_path), initialize=flaky, sleep=slept.append, env=env)
    assert len(calls) == 3 and gen == 0
    assert slept == [2.0, 4.0]  # the shared deterministic schedule


def test_rendezvous_exhaustion_raises_rc6(tmp_path):
    def never():
        raise ConnectionRefusedError("coordinator down")

    env = {"FLEET_RENDEZVOUS_ATTEMPTS": "3", "FLEET_RENDEZVOUS_BACKOFF_S": "1",
           "FLEET_RENDEZVOUS_DEADLINE_S": "600"}
    with pytest.raises(fleet.RendezvousFailed, match="3 attempts"):
        fleet.initialize_with_retry(str(tmp_path), initialize=never,
                                    sleep=lambda s: None, env=env)
    assert fleet.RendezvousFailed.exit_code == 6


def test_rendezvous_deadline_cuts_the_schedule_short():
    calls = []

    def never():
        calls.append(1)
        raise TimeoutError("x")

    env = {"FLEET_RENDEZVOUS_ATTEMPTS": "10",
           "FLEET_RENDEZVOUS_BACKOFF_S": "1000",
           "FLEET_RENDEZVOUS_DEADLINE_S": "1"}
    with pytest.raises(fleet.RendezvousFailed):
        fleet.initialize_with_retry(initialize=never, sleep=lambda s: None,
                                    env=env)
    assert len(calls) == 1  # first sleep would blow the deadline: stop now

    assert fleet.backoff_schedule(4, 5, 60) == [5.0, 10.0, 20.0]
    assert fleet.backoff_schedule(6, 30, 60) == [30.0, 60.0, 60.0, 60.0, 60.0]


def test_rendezvous_reads_generation_for_logging(tmp_path):
    fleet.advance_generation(fleet.generation_path(str(tmp_path)), 4)
    gen = fleet.initialize_with_retry(
        str(tmp_path), initialize=lambda: None, sleep=lambda s: None, env={})
    assert gen == 4


# --------------------------------------------------------- generation file --
def test_generation_file_monotonicity(tmp_path):
    path = fleet.generation_path(str(tmp_path))
    assert fleet.read_generation(path) == 0  # absent
    assert fleet.advance_generation(path, 2) == 2
    assert fleet.read_generation(path) == 2
    assert fleet.advance_generation(path, 1) == 2  # never regresses
    assert fleet.read_generation(path) == 2
    assert fleet.advance_generation(path, 5) == 5
    with open(path, "w") as f:
        f.write("garbage\n")
    assert fleet.read_generation(path) == 0  # torn write never bricks


# -------------------------------------------------------- abort propagation --
def test_abort_exchange_max_code_wins_on_every_host(monkeypatch):
    recorded = np.asarray([[0], [8]], np.int32)
    monkeypatch.setattr(fleet, "_allgather_host", lambda x: recorded)
    co = fleet.FleetCoordinator(process_index=0, process_count=2)
    code, origin = co.exchange_abort()
    assert (code, origin) == (8, 1)
    with pytest.raises(fleet.PodAbort) as ei:
        co.check()
    assert ei.value.code == 8 and ei.value.origin == 1
    assert "host 1" in str(ei.value)


def test_abort_note_first_intent_wins_and_clean_exchange_is_silent(monkeypatch):
    co = fleet.FleetCoordinator(process_index=1, process_count=2)
    co.note_abort(143, "SIGTERM received")
    co.note_abort(8, "late sentinel")  # first cause wins locally
    assert co.abort_code == 143 and "SIGTERM" in co.abort_reason
    monkeypatch.setattr(
        fleet, "_allgather_host",
        lambda x: np.asarray([[0], [co.abort_code]], np.int32))
    code, origin = co.exchange_abort()
    assert (code, origin) == (143, 1)

    clean = fleet.FleetCoordinator(process_index=0, process_count=2)
    monkeypatch.setattr(fleet, "_allgather_host",
                        lambda x: np.zeros((2, 1), np.int32))
    assert clean.exchange_abort() == (0, -1)
    clean.check()  # no intent anywhere: no raise, training continues


def test_abort_single_process_shortcircuits(monkeypatch):
    monkeypatch.setattr(fleet, "_allgather_host",
                        lambda x: pytest.fail("collective on single host"))
    co = fleet.FleetCoordinator(process_index=0, process_count=1)
    co.check()
    co.note_abort(8, "diverged")
    with pytest.raises(fleet.PodAbort) as ei:
        co.check()
    assert ei.value.code == 8 and "this host" in str(ei.value)


# ------------------------------------------------------------- pod chaos --
def test_peer_fault_parsing_and_step_only_units():
    plan = chaoslib.FaultPlan.parse("peer_dead@step=6,peer_slow@step=3..4")
    assert [f.kind for f in plan.faults] == ["peer_dead", "peer_slow"]
    for bad in ("peer_dead@epoch=1", "peer_slow@batch=2"):
        with pytest.raises(ValueError, match="keyed by the host-side step"):
            chaoslib.FaultPlan.parse(bad)


def test_chaos_host_gate_aims_faults_at_one_process(monkeypatch):
    spec = "peer_dead@step=6,nan_loss@step=1..2,sigterm@step=9"
    monkeypatch.setenv(chaoslib.ENV_HOST, "1")
    miss = chaoslib.FaultPlan.parse(spec, process_index=0)
    assert miss.host_gated()
    assert miss.should_fire("peer_dead", step=6) is None
    assert miss.should_fire("sigterm", step=9) is None
    assert miss.windows("nan_loss") == []  # peers compile the clean step
    hit = chaoslib.FaultPlan.parse(spec, process_index=1)
    assert not hit.host_gated()
    assert hit.windows("nan_loss") == [(1, 2)]
    assert hit.should_fire("peer_dead", step=6) is not None
    monkeypatch.delenv(chaoslib.ENV_HOST)
    # unset ⇒ every host (bit-identical to the pre-pod behavior)
    any_host = chaoslib.FaultPlan.parse(spec, process_index=3)
    assert not any_host.host_gated()
    assert any_host.windows("nan_loss") == [(1, 2)]


def test_peer_dead_sigkills_self_once(monkeypatch):
    kills = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: kills.append((pid, sig)))
    plan = chaoslib.FaultPlan.parse("peer_dead@step=6", process_index=0)
    plan.maybe_peer_dead(step=5)
    assert kills == []
    plan.maybe_peer_dead(step=6)
    assert kills == [(os.getpid(), signal.SIGKILL)]
    plan.maybe_peer_dead(step=6)  # one-shot
    assert len(kills) == 1


def test_peer_slow_stalls_configured_seconds(monkeypatch):
    import time as timelib

    stalls = []
    monkeypatch.setattr(timelib, "sleep", lambda s: stalls.append(s))
    monkeypatch.setenv(chaoslib.ENV_PEER_SLOW_S, "2.5")
    plan = chaoslib.FaultPlan.parse("peer_slow@step=3")
    plan.maybe_peer_slow(step=3)
    assert stalls == [2.5]
    plan.maybe_peer_slow(step=3)  # one-shot
    assert stalls == [2.5]


def test_peer_fault_markers_are_per_host(tmp_path):
    """Shared state_dir on a pod: host 0 firing must not consume host 1's
    one shot."""
    spec = "peer_slow@step=3"
    p0 = chaoslib.FaultPlan.parse(spec, state_dir=str(tmp_path),
                                  process_index=0)
    assert p0.should_fire("peer_slow", step=3) is not None
    p1 = chaoslib.FaultPlan.parse(spec, state_dir=str(tmp_path),
                                  process_index=1)
    assert p1.should_fire("peer_slow", step=3) is not None
    # but the SAME host's restart does not re-fire
    p0b = chaoslib.FaultPlan.parse(spec, state_dir=str(tmp_path),
                                   process_index=0)
    assert p0b.should_fire("peer_slow", step=3) is None


# --------------------------------------------------- supervise.sh discipline --
STUB = """#!/usr/bin/env bash
state="${FAKE_STATE:?}"
n=$(cat "$state" 2>/dev/null || echo 0)
n=$((n+1)); echo "$n" > "$state"
rc=$(echo "${FAKE_RCS:?}" | tr ',' '\\n' | sed -n "${n}p")
[ -z "$rc" ] && rc=$(echo "$FAKE_RCS" | tr ',' '\\n' | tail -1)
exit "$rc"
"""


def _stub_env(tmp_path, rcs):
    fakebin = tmp_path / "bin"
    fakebin.mkdir(exist_ok=True)
    stub = fakebin / "python"
    stub.write_text(STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IXUSR)
    env = dict(os.environ)
    env["PATH"] = f"{fakebin}:{env['PATH']}"
    env["FAKE_STATE"] = str(tmp_path / "calls")
    env["FAKE_RCS"] = rcs
    return env


def test_supervise_rc6_rendezvous_gets_outage_backoff_and_host_fields(tmp_path):
    out = tmp_path / "out"
    env = _stub_env(tmp_path, "6,0")
    env["OUTAGE_BACKOFF_S"] = "0"
    env["FLEET_PROCESS_ID"] = "1"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"),
         "baseline", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 0, p.stderr
    lines = (out / "restarts.log").read_text().strip().splitlines()
    assert len(lines) == 1
    assert "rc=6" in lines[0] and "action=restart" in lines[0]
    assert "backoff=0s" in lines[0]  # OUTAGE_BACKOFF_S was honored
    assert "host=" in lines[0] and "proc=1" in lines[0]
    # the restart wave max-wrote its attempt into the shared generation file
    assert (out / "generation").read_text().strip() == "1"


def test_supervise_rc9_pod_inconsistent_is_retried(tmp_path):
    out = tmp_path / "out"
    env = _stub_env(tmp_path, "9,0")
    env["RUNTIME_BACKOFF_S"] = "0"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"),
         "baseline", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 0, p.stderr
    log = (out / "restarts.log").read_text()
    assert "rc=9" in log and "action=restart" in log


def test_supervise_generation_is_monotonic_across_waves(tmp_path):
    out = tmp_path / "out"
    out.mkdir()
    (out / "generation").write_text("7\n")  # a peer is already at wave 7
    env = _stub_env(tmp_path, "143,143,0")
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"),
         "baseline", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, p.stderr
    # our attempts (1, 2) never regress the shared file below the peer's 7
    assert (out / "generation").read_text().strip() == "7"


# ---------------------------------------------------------- full pod drill --
@pytest.mark.slow
def test_pod_chaos_drill(tmp_path):
    """The real thing: scripts/chaos_drill.sh phases 3-5 drive TWO
    supervised hosts (4 virtual CPU devices each, gloo for DCN) through
    peer_dead, a corrupt shared checkpoint, and a one-host sustained NaN —
    asserting coordinated restart into one generation, consensus resume
    with exactly one quarantine, and symmetric rc 8 with no spurious
    rc 7."""
    env = {k: v for k, v in os.environ.items()
           if k not in (chaoslib.ENV_SPEC, chaoslib.ENV_STATE_DIR,
                        chaoslib.ENV_HOST)}
    env["CHAOS_PHASES"] = "3 4 5"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "chaos_drill.sh"),
         str(tmp_path / "drill")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=2400)
    assert p.returncode == 0, (p.stdout[-5000:], p.stderr[-2000:])
    assert "CHAOS DRILL PASS" in p.stdout
