"""Mid-run hang watchdog (utils/backend_probe.py::StepHeartbeat).

Motivated by a hang observed live (2026-08-01): a tunnel lease churn froze
a trainer mid-step forever — zero CPU, no exception. supervise.sh restarts
on EXIT only, so a hang that never exits defeats the whole
failure-detection chain (SURVEY §5); the heartbeat converts the hang into
exit code 7, which supervise.sh + --auto_resume then recover exactly like
a preemption (tests/test_preemption_recovery.py proves that half).

os._exit in a daemon thread cannot be tested in-process — each case runs
in a subprocess, same pattern as the bench deadline-watchdog tests.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(src: str, timeout: float = 30.0) -> subprocess.CompletedProcess:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, "-c", src], cwd=REPO,
                          capture_output=True, timeout=timeout, env=env)


def test_hang_exits_7_with_diagnostic():
    p = _run(
        "import time\n"
        "from ddp_classification_pytorch_tpu.utils.backend_probe import StepHeartbeat\n"
        "StepHeartbeat(0.3, where='trainer[test]').start()\n"
        "time.sleep(20)\n"  # the simulated hang: no touch ever lands
    )
    assert p.returncode == 7, (p.returncode, p.stderr[-300:])
    assert b"no progress" in p.stderr and b"trainer[test]" in p.stderr


def test_touches_keep_it_alive_and_stop_disarms():
    p = _run(
        "import time\n"
        "from ddp_classification_pytorch_tpu.utils.backend_probe import StepHeartbeat\n"
        "hb = StepHeartbeat(0.4).start()\n"
        "for _ in range(10):\n"
        "    time.sleep(0.1); hb.touch()\n"  # slow but alive: must not fire
        "hb.stop()\n"
        "time.sleep(1.0)\n"  # disarmed: silence past the timeout is fine
        "print('survived')\n"
    )
    assert p.returncode == 0, p.stderr[-300:]
    assert b"survived" in p.stdout


def test_zero_timeout_is_inert():
    p = _run(
        "import time\n"
        "from ddp_classification_pytorch_tpu.utils.backend_probe import StepHeartbeat\n"
        "hb = StepHeartbeat(0.0).start()\n"  # the default: watchdog off
        "assert hb._thread is None\n"
        "time.sleep(0.5); hb.touch()\n"  # touch on an inert heartbeat is safe
        "print('inert')\n"
    )
    assert p.returncode == 0, p.stderr[-300:]
    assert b"inert" in p.stdout
