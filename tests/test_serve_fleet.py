"""Serve-fleet control plane (serve/fleet.py): replica registry leases,
rolling-wave drain token, admission control, and the autoscaler policy.

Everything here is deterministic and in-process: three FleetMembers
share one tmp run dir, staleness is simulated by `os.utime` into the
past or explicit `now=` arguments, and no sockets or subprocesses are
involved. The subprocess fleet drill lives in chaos_drill.sh phase 9 /
tests/test_scenario.py.
"""

import concurrent.futures
import os
import time

import pytest

from ddp_classification_pytorch_tpu.obs import events as ev
from ddp_classification_pytorch_tpu.obs.registry import Registry
from ddp_classification_pytorch_tpu.serve.fleet import (
    AdmissionController,
    AdmissionShed,
    Autoscaler,
    FleetMember,
    parse_tenants,
    replica_lease_path,
    scan_replica_leases,
    serve_fleet_dir,
    wave_token_path,
)


def _member(tmp_path, rid, ttl_s=5.0):
    # each member gets its OWN registry: the gauges are unlabelled (one
    # process == one replica in production), so sharing one registry
    # across in-process members would alias their instruments
    return FleetMember(str(tmp_path), rid, ttl_s=ttl_s, registry=Registry())


# ------------------------------------------------------------ registry --
def test_fleet_member_ctor_validation(tmp_path):
    with pytest.raises(ValueError, match="run_dir"):
        FleetMember("", 0, registry=Registry())
    with pytest.raises(ValueError, match="replica_id"):
        FleetMember(str(tmp_path), -1, registry=Registry())
    with pytest.raises(ValueError, match="ttl_s"):
        FleetMember(str(tmp_path), 0, ttl_s=0.0, registry=Registry())


def test_fleet_gauges_registered_at_construction(tmp_path):
    reg = Registry()
    FleetMember(str(tmp_path), 0, registry=reg)
    text = reg.expose()
    for family in ("fleet_replicas_alive", "fleet_wave_draining",
                   "fleet_digest_converged", "fleet_lease_generation",
                   "fleet_heartbeats_total", "fleet_wave_swaps_total",
                   "fleet_token_takeovers_total"):
        assert family in text  # 0-valued families expose pre-heartbeat


def test_heartbeat_writes_lease_and_scan_roundtrips(tmp_path):
    m = _member(tmp_path, 3)
    m.heartbeat(digest="abc", generation=7)
    lease = scan_replica_leases(str(tmp_path), ttl_s=5.0)[3]
    assert lease.state == "serving"  # joining + digest -> serving
    assert lease.digest == "abc"
    assert lease.generation == 7
    assert lease.age_s >= 0.0
    assert os.path.exists(replica_lease_path(str(tmp_path), 3))


def test_scan_skips_stale_foreign_and_garbled_names(tmp_path):
    m = _member(tmp_path, 0)
    m.heartbeat(digest="d")
    d = serve_fleet_dir(str(tmp_path))
    with open(os.path.join(d, "lease.rabc"), "w") as f:
        f.write("not a lease\n")  # non-numeric suffix: ignored
    with open(os.path.join(d, "wave.token"), "w") as f:
        f.write("holder=0 digest=d\n")  # token is not a lease
    assert set(scan_replica_leases(str(tmp_path), ttl_s=5.0)) == {0}
    # a lease older than ttl is a dead replica
    future = time.time() + 100.0
    assert scan_replica_leases(str(tmp_path), ttl_s=5.0, now=future) == {}


def test_role_is_lowest_live_id(tmp_path):
    m0, m1 = _member(tmp_path, 0), _member(tmp_path, 1)
    m0.heartbeat(digest="d")
    m1.heartbeat(digest="d")
    assert m0.role() == "leader"
    assert m1.role() == "follower"
    # leader death promotes the next id once the lease ages out
    path = replica_lease_path(str(tmp_path), 0)
    past = time.time() - 60.0
    os.utime(path, (past, past))
    assert m1.role() == "leader"


def test_fleet_converged_requires_one_nonempty_digest(tmp_path):
    m0, m1 = _member(tmp_path, 0), _member(tmp_path, 1)
    m0.heartbeat(digest="aaa")
    m1.heartbeat()  # no digest yet: empty string on the lease
    assert not m0.fleet_converged()
    m1.heartbeat(digest="bbb")
    assert not m0.fleet_converged()  # divergent
    m1.heartbeat(digest="aaa")
    assert m0.fleet_converged()
    assert m1.fleet_converged()


def test_leave_drops_lease_immediately(tmp_path):
    m = _member(tmp_path, 2)
    m.heartbeat(digest="d")
    m.leave()
    assert scan_replica_leases(str(tmp_path), ttl_s=5.0) == {}


# -------------------------------------------------------- rolling wave --
def test_drain_token_is_exclusive_and_wave_converges(tmp_path):
    """The deterministic 3-replica rolling wave: at most one replica
    drains at any instant, and every replica ends on the new digest."""
    members = [_member(tmp_path, i) for i in range(3)]
    for m in members:
        m.heartbeat(digest="old", generation=1)
    order = []
    for m in members:  # a new published digest: everyone wants to swap
        assert m.try_begin_drain("new")
        # invariant (a): the token is singular — both peers are refused
        for other in members:
            if other is not m:
                assert not other.try_begin_drain("new")
        assert m.holds_token
        assert sum(1 for x in members if x.holds_token) == 1
        m.end_drain(digest="new", generation=2)
        assert not m.holds_token
        order.append(m.replica_id)
    assert order == [0, 1, 2]
    # invariant (b): every replica ends on the same digest
    assert all(m.digest == "new" for m in members)
    assert members[0].fleet_converged()
    assert not os.path.exists(wave_token_path(str(tmp_path)))


def test_try_begin_drain_is_idempotent_for_the_holder(tmp_path):
    m = _member(tmp_path, 0)
    m.heartbeat(digest="old")
    assert m.try_begin_drain("new")
    swaps = m._wave_swaps_total.value
    assert m.try_begin_drain("new")  # already draining: cheap True
    m.end_drain(digest="new", generation=1)
    assert m._wave_swaps_total.value == swaps + 1


def test_holder_heartbeat_refreshes_token_mtime(tmp_path):
    m = _member(tmp_path, 0)
    m.heartbeat(digest="old")
    assert m.try_begin_drain("new")
    path = wave_token_path(str(tmp_path))
    past = time.time() - 60.0
    os.utime(path, (past, past))
    m.heartbeat()  # live holder: the poll tick keeps the token fresh
    assert time.time() - os.stat(path).st_mtime < 5.0


def test_stale_token_ttl_takeover_unwedges_the_wave(tmp_path):
    """Invariant (c): a replica killed mid-wave cannot wedge the fleet —
    the token goes stale after ttl_s and the next replica takes over."""
    members = [_member(tmp_path, i) for i in range(3)]
    for m in members:
        m.heartbeat(digest="old", generation=1)
    victim = members[1]
    assert victim.try_begin_drain("new")
    # victim is SIGKILLed: no more heartbeats, so its token and lease age
    past = time.time() - 60.0
    os.utime(wave_token_path(str(tmp_path)), (past, past))
    os.utime(replica_lease_path(str(tmp_path), 1), (past, past))
    # a fresh token is NOT up for grabs...
    fresh = _member(tmp_path, 9)
    fresh.heartbeat(digest="old")
    # ...but the stale one is: replica 2 takes it over and read-back
    # confirms ownership
    assert members[2].try_begin_drain("new")
    assert members[2].holds_token
    assert members[2]._takeovers_total.value == 1.0
    # the dead holder's late release must not steal the live wave:
    # end_drain only removes the token when it is still ours
    victim.end_drain(digest="stale-write", generation=1)
    assert os.path.exists(wave_token_path(str(tmp_path)))
    # the late writer is still dead for membership purposes — age the
    # lease its end_drain heartbeat just rewrote
    os.utime(replica_lease_path(str(tmp_path), 1), (past, past))
    members[2].end_drain(digest="new", generation=2)
    assert not os.path.exists(wave_token_path(str(tmp_path)))
    # survivors finish the wave and converge; the dead lease aged out
    fresh.leave()
    assert members[0].try_begin_drain("new")
    members[0].end_drain(digest="new", generation=2)
    live = members[0].peers()
    assert set(live) == {0, 2}
    assert members[0].fleet_converged()


def test_wave_events_are_emitted_under_scenario(tmp_path, monkeypatch):
    events_path = str(tmp_path / "events.jsonl")
    monkeypatch.setenv(ev.ENV_EVENTS, events_path)
    monkeypatch.setenv(ev.ENV_SOURCE, "replica0")
    m = _member(tmp_path / "run", 0)
    m.heartbeat(digest="old")
    assert m.try_begin_drain("new")
    m.end_drain(digest="new", generation=3)
    kinds = [r["kind"] for r in ev.read_events(events_path)]
    assert "drain_token_acquire" in kinds
    assert "drain_token_release" in kinds
    rel = [r for r in ev.read_events(events_path)
           if r["kind"] == "drain_token_release"][0]
    assert rel["digest"] == "new"
    assert rel["generation"] == 3
    assert rel["source"] == "replica0"


# ----------------------------------------------------------- admission --
class _FakeMetrics:
    def __init__(self):
        self.completed = 0
        self.rejects = 0
        self.registry = Registry()

    def record_reject(self):
        self.rejects += 1


class _FakeEngine:
    def __init__(self, depth=0):
        self.queue_depth = depth
        self.metrics = _FakeMetrics()
        self.submitted = []

    def submit(self, image):
        fut = concurrent.futures.Future()
        self.submitted.append((image, fut))
        return fut

    def submit_image(self, img):
        # mirrors ServingEngine.submit_image: the val Transform takes
        # (img, rng), so the admission layer must delegate rather than
        # call self.transform(img) itself
        return self.submit(self.transform(img, None))


def test_parse_tenants():
    assert parse_tenants("") == {"default": 1.0}
    assert parse_tenants("  ") == {"default": 1.0}
    assert parse_tenants("a:2,b:1") == {"a": 2.0, "b": 1.0}
    assert parse_tenants("solo") == {"solo": 1.0}  # weight defaults to 1
    for bad in (":2", "a:x", "a:0", "a:-1", "a:1,a:2", ",,"):
        with pytest.raises(ValueError):
            parse_tenants(bad)


def test_admission_ctor_validates_and_registers_counters(tmp_path):
    eng = _FakeEngine()
    with pytest.raises(ValueError, match="deadline_ms"):
        AdmissionController(eng, deadline_ms=0.0)
    adm = AdmissionController(eng, tenants="a:2,b:1", deadline_ms=100.0)
    text = adm.registry.expose()
    assert 'admission_admitted_total{tenant="a"}' in text
    assert 'admission_shed_total{tenant="b"}' in text
    assert "admission_est_wait_ms" in text


def test_admission_hard_shed_at_twice_deadline(tmp_path, monkeypatch):
    events_path = str(tmp_path / "events.jsonl")
    monkeypatch.setenv(ev.ENV_EVENTS, events_path)
    eng = _FakeEngine(depth=3)
    # 10 req/s measured -> 3 queued = 300ms wait > 2 x 100ms deadline
    adm = AdmissionController(eng, deadline_ms=100.0, rate_fn=lambda: 10.0)
    with pytest.raises(AdmissionShed) as exc:
        adm.submit(object(), tenant="whoever")
    assert exc.value.queue_depth == 3
    assert exc.value.est_wait_ms == pytest.approx(300.0)
    assert eng.metrics.rejects == 1
    assert not eng.submitted  # never reached the engine queue
    shed = [r for r in ev.read_events(events_path)
            if r["kind"] == "admission_shed"]
    assert shed and shed[0]["tenant"] == "whoever"
    assert shed[0]["queue_depth"] == 3


def test_admission_fairness_shed_spares_under_share_tenant(tmp_path):
    eng = _FakeEngine(depth=0)
    adm = AdmissionController(eng, tenants="a:1,b:1", deadline_ms=100.0,
                              rate_fn=lambda: 10.0)
    # b saturates its share while the queue is still cheap
    futs = [adm.submit(object(), tenant="b") for _ in range(3)]
    # now the measured wait is between 1x and 2x the deadline: fairness
    # territory. b is over its 50% share -> shed; a is under -> admitted.
    eng.queue_depth = 15  # 150ms wait at 100 req/s... use rate 100
    adm._rate_fn = lambda: 100.0
    with pytest.raises(AdmissionShed):
        adm.submit(object(), tenant="b")
    fut_a = adm.submit(object(), tenant="a")
    assert fut_a is eng.submitted[-1][1]
    # completion releases b's in-flight slot via the future callback
    futs[0].set_result(None)
    assert adm._inflight["b"] == 2


def test_admission_queue_full_folds_into_shed(tmp_path):
    class QueueFull(RuntimeError):
        pass

    class FullEngine(_FakeEngine):
        def submit(self, image):
            raise QueueFull("bounded queue at capacity")

    eng = FullEngine(depth=2)
    adm = AdmissionController(eng, deadline_ms=500.0, rate_fn=lambda: 1000.0)
    with pytest.raises(AdmissionShed):  # one 503 surface, not two
        adm.submit(object())
    assert eng.metrics.rejects == 1


def test_admission_cold_start_rate_floor_admits(tmp_path):
    # no completions yet: the floor (1 req per deadline) keeps the wait
    # estimate finite so a cold fleet does not shed everything
    eng = _FakeEngine(depth=0)
    adm = AdmissionController(eng, deadline_ms=100.0)
    fut = adm.submit(object())
    assert fut is eng.submitted[0][1]
    assert adm.est_wait_ms() == 0.0


def test_admission_submit_image_needs_transform(tmp_path):
    eng = _FakeEngine()
    adm = AdmissionController(eng, deadline_ms=100.0, rate_fn=lambda: 10.0)
    with pytest.raises(RuntimeError, match="transform"):
        adm.submit_image(object())
    eng.transform = lambda img, rng: ("transformed", img)
    adm.submit_image("raw")
    assert eng.submitted[0][0] == ("transformed", "raw")


# ---------------------------------------------------------- autoscaler --
def test_autoscaler_ctor_validation():
    with pytest.raises(ValueError, match="min_replicas"):
        Autoscaler(min_replicas=0, max_replicas=2)
    with pytest.raises(ValueError, match="max_replicas"):
        Autoscaler(min_replicas=3, max_replicas=2)
    assert Autoscaler(min_replicas=2, max_replicas=4).replicas == 2


def test_autoscaler_scales_out_on_queue_or_slo_breach():
    sc = Autoscaler(min_replicas=1, max_replicas=3, p99_slo_ms=250.0,
                    queue_high=8)
    now = 1000.0
    assert sc.decide({"queue_depth": 8, "fill_ratio": 1.0, "p99_ms": 10.0},
                     now) == 2
    assert sc.decide({"queue_depth": 0, "fill_ratio": 0.9, "p99_ms": 300.0},
                     now) == 2
    # healthy sample: hold
    assert sc.decide({"queue_depth": 2, "fill_ratio": 0.9, "p99_ms": 10.0},
                     now) == 1
    # capped at max_replicas
    sc.replicas = 3
    assert sc.decide({"queue_depth": 99, "p99_ms": 9999.0}, now) == 3


def test_autoscaler_scale_in_needs_empty_queue_and_cold_fill():
    sc = Autoscaler(min_replicas=1, max_replicas=3, p99_slo_ms=250.0,
                    fill_low=0.25, replicas=3)
    now = 1000.0
    assert sc.decide({"queue_depth": 0, "fill_ratio": 0.1, "p99_ms": 10.0},
                     now) == 2
    # any warm signal holds the fleet
    assert sc.decide({"queue_depth": 1, "fill_ratio": 0.1, "p99_ms": 10.0},
                     now) == 3
    assert sc.decide({"queue_depth": 0, "fill_ratio": 0.5, "p99_ms": 10.0},
                     now) == 3
    assert sc.decide({"queue_depth": 0, "fill_ratio": 0.1, "p99_ms": 400.0},
                     now) == 3
    # floored at min_replicas
    sc.replicas = 1
    assert sc.decide({"queue_depth": 0, "fill_ratio": 0.0, "p99_ms": 0.0},
                     now) == 1


def test_autoscaler_cooldown_gates_consecutive_moves():
    sc = Autoscaler(min_replicas=1, max_replicas=4, queue_high=4,
                    cooldown_s=10.0)
    hot = {"queue_depth": 50, "fill_ratio": 1.0, "p99_ms": 0.0}
    assert sc.decide(hot, 100.0) == 2
    sc.applied(2, 100.0)
    assert sc.decide(hot, 105.0) == 2  # inside cooldown: hold
    assert sc.decide(hot, 111.0) == 3  # cooldown elapsed
    # applied() with no movement must NOT restart the cooldown
    sc.applied(3, 111.0)
    sc.applied(3, 120.0)
    assert sc.decide(hot, 122.0) == 4
