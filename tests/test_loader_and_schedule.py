"""Loader and schedule regression tests for review findings:
- producer exceptions must surface at the iteration site, not truncate epochs;
- valid_mask marks wrap-padding exactly;
- warmup overlays the decay schedule without shifting its milestones;
- 3-tuple datasets (PLC (image, label, index)) load through ShardedLoader.
"""

import numpy as np
import pytest

from ddp_classification_pytorch_tpu.config import OptimConfig
from ddp_classification_pytorch_tpu.data.loader import ShardedLoader
from ddp_classification_pytorch_tpu.train.schedule import build_schedule


class ExplodingDataset:
    def __len__(self):
        return 64

    def __getitem__(self, i, rng=None):
        if i == 40:
            raise RuntimeError("corrupt sample")
        return np.zeros((4, 4, 3), np.float32), 0


def test_loader_surfaces_worker_errors():
    loader = ShardedLoader(ExplodingDataset(), batch_size=8, shuffle=False,
                           num_workers=2, host_id=0, num_hosts=1)
    with pytest.raises(RuntimeError, match="corrupt sample"):
        list(loader)


class TripleDataset:
    """PLC-style (image, label, index) items."""

    def __len__(self):
        return 16

    def __getitem__(self, i, rng=None):
        return np.full((2, 2, 3), i, np.float32), i % 3, i


def test_loader_handles_plc_triples():
    loader = ShardedLoader(TripleDataset(), batch_size=8, shuffle=False,
                           num_workers=1, host_id=0, num_hosts=1)
    batches = list(loader)
    assert len(batches) == 2
    images, labels = batches[0]
    assert images.shape == (8, 2, 2, 3)
    np.testing.assert_array_equal(labels, np.arange(8) % 3)


def test_valid_mask_marks_padding():
    class Tiny:
        def __len__(self):
            return 10

        def __getitem__(self, i, rng=None):
            return np.zeros((2, 2, 3), np.float32), 0

    loader = ShardedLoader(Tiny(), batch_size=4, shuffle=False,
                           host_id=0, num_hosts=1)
    # 10 samples pad to 12 → batches of 4,4,4; last two rows of batch 2 padded
    assert len(loader) == 3
    np.testing.assert_array_equal(loader.valid_mask(0), [1, 1, 1, 1])
    np.testing.assert_array_equal(loader.valid_mask(1), [1, 1, 1, 1])
    np.testing.assert_array_equal(loader.valid_mask(2), [1, 1, 0, 0])


def test_valid_mask_multihost_padding_on_last_host():
    class Tiny:
        def __len__(self):
            return 10

        def __getitem__(self, i, rng=None):
            return np.zeros((2, 2, 3), np.float32), 0

    # 2 hosts × batch 4 → chunk 8, pad 10 → 16, per-host 8 (2 batches each)
    m0 = [ShardedLoader(Tiny(), 4, shuffle=False, host_id=0, num_hosts=2).valid_mask(b)
          for b in range(2)]
    m1 = [ShardedLoader(Tiny(), 4, shuffle=False, host_id=1, num_hosts=2).valid_mask(b)
          for b in range(2)]
    np.testing.assert_array_equal(np.concatenate(m0), [1] * 8)       # rows 0-7
    np.testing.assert_array_equal(np.concatenate(m1), [1, 1] + [0] * 6)  # rows 8-9 real


def test_tiny_dataset_pads_to_full_batch():
    class Tiny:
        def __len__(self):
            return 5

        def __getitem__(self, i, rng=None):
            return np.zeros((2, 2, 3), np.float32), i

    # pad (123) far exceeds n (5): the permutation must tile, not truncate
    loader = ShardedLoader(Tiny(), batch_size=128, shuffle=False,
                           host_id=0, num_hosts=1)
    assert len(loader) == 1
    batches = list(loader)
    assert batches[0][0].shape[0] == 128
    np.testing.assert_array_equal(loader.valid_mask(0)[:5], [1] * 5)
    assert loader.valid_mask(0)[5:].sum() == 0


def test_len_and_valid_mask_skip_the_permutation_and_cache_indices(monkeypatch):
    """__len__/valid_mask used to recompute the full O(n) epoch permutation
    on EVERY call (review finding): derive lengths arithmetically, compute
    the permutation once per epoch, and invalidate on set_epoch."""
    import ddp_classification_pytorch_tpu.data.loader as loader_mod

    class Tiny:
        def __len__(self):
            return 10

        def __getitem__(self, i, rng=None):
            return np.zeros((2, 2, 3), np.float32), 0

    calls = []
    real = loader_mod.shard_indices_for_host
    monkeypatch.setattr(loader_mod, "shard_indices_for_host",
                        lambda *a, **kw: (calls.append(1), real(*a, **kw))[1])

    loader = ShardedLoader(Tiny(), batch_size=4, shuffle=False,
                           host_id=0, num_hosts=1)
    assert len(loader) == 3 and len(loader) == 3
    loader.valid_mask(0)
    loader.valid_mask(2)
    assert calls == []  # pure arithmetic — no permutation materialized

    idx0 = loader._epoch_indices()
    assert loader._epoch_indices() is idx0  # cached within the epoch
    assert calls == [1]
    loader.set_epoch(1)
    idx1 = loader._epoch_indices()
    assert calls == [1, 1]  # set_epoch invalidated the cache
    assert loader._epoch_indices() is idx1
    np.testing.assert_array_equal(idx0, idx1)  # shuffle=False: same order


def test_abandoned_iteration_does_not_deadlock():
    class Slow:
        def __len__(self):
            return 64

        def __getitem__(self, i, rng=None):
            return np.zeros((2, 2, 3), np.float32), 0

    import threading

    loader = ShardedLoader(Slow(), batch_size=8, shuffle=False, prefetch=1,
                           host_id=0, num_hosts=1)
    it = iter(loader)
    next(it)
    del it  # abandon mid-epoch; producer must exit, not hang on a full queue
    for _ in range(50):
        if threading.active_count() <= 2:
            break
        import time
        time.sleep(0.1)
    # no strict assert on thread count (pytest has helpers), but a second
    # full iteration must work — would hang if the producer deadlocked
    assert len(list(loader)) == 8


def test_warmup_does_not_shift_milestones():
    cfg = OptimConfig(lr=1.0, schedule="multistep", milestones=(2, 4),
                      gamma=0.1, warmup_iters=10, warmup_start_lr=0.0)
    sched = build_schedule(cfg, steps_per_epoch=10)
    # milestones anchored at global steps 20 and 40 despite 10-iter warmup
    assert float(sched(5)) == pytest.approx(0.5)      # mid-warmup ramp
    assert float(sched(15)) == pytest.approx(1.0)     # post-warmup, pre-decay
    assert float(sched(20)) == pytest.approx(0.1)     # first milestone on time
    assert float(sched(40)) == pytest.approx(0.01)    # second milestone on time


def test_warmup_rescales_under_grad_accum():
    # warmup_iters counts ITERATIONS; with accumulation k=2 the schedule
    # advances once per optimizer step, so warmup spans warmup_iters/k steps
    cfg = OptimConfig(lr=1.0, schedule="constant", warmup_iters=10,
                      warmup_start_lr=0.0)
    sched = build_schedule(cfg, steps_per_epoch=10, grad_accum=2)
    assert float(sched(4)) == pytest.approx(0.8)   # 4/5 through a 5-step ramp
    assert float(sched(5)) == pytest.approx(1.0)


def test_lr_trace_identical_across_grad_accum():
    """LR-schedule semantics under accumulation: K=4 and K=1 runs with the
    SAME optimizer-step budget produce IDENTICAL LR traces. grad_accum
    slices microbatches out of one loader batch inside the jitted step, so
    steps_per_epoch already counts optimizer steps and milestones need no
    rescaling; only warmup_iters (reference semantics: microbatch
    ITERATIONS) converts ÷K — equal optimizer-step warmups (K=4 ×
    warmup 20 vs K=1 × warmup 5) must then trace identically everywhere.
    A reintroduced per-microbatch schedule step (the classic off-by-K
    accumulation bug) shifts every milestone by K× and fails here."""
    base = dict(lr=1.0, schedule="multistep", milestones=(2, 4), gamma=0.1,
                warmup_start_lr=0.0)
    k4 = build_schedule(OptimConfig(warmup_iters=20, **base),
                        steps_per_epoch=10, grad_accum=4)
    k1 = build_schedule(OptimConfig(warmup_iters=5, **base),
                        steps_per_epoch=10, grad_accum=1)
    trace4 = [float(k4(s)) for s in range(50)]
    trace1 = [float(k1(s)) for s in range(50)]
    assert trace4 == pytest.approx(trace1)
    # and the trace is the REAL one: warmup ramp then on-time milestones
    assert trace4[2] == pytest.approx(0.4)
    assert trace4[20] == pytest.approx(0.1)
    assert trace4[40] == pytest.approx(0.01)


def test_optimizer_applies_schedule_once_per_update_under_grad_accum():
    """The accumulated step hands build_optimizer ONE summed/meaned
    gradient per loader batch — every tx.update IS an optimizer step. A
    resurrected optax.MultiSteps wrapper (which would treat each update
    as a microbatch and only apply every K-th) shifts the whole decay
    trace and fails here."""
    import jax.numpy as jnp

    from ddp_classification_pytorch_tpu.train.schedule import build_optimizer

    params = {"w": jnp.ones((3,))}
    grads = {"w": jnp.ones((3,))}
    cfg = OptimConfig(optimizer="sgd", momentum=0.0, lr=1.0,
                      schedule="multistep", milestones=(1,), gamma=0.1)
    tx = build_optimizer(cfg, steps_per_epoch=2, grad_accum=4)
    opt_state = tx.init(params)
    mags = []
    for _ in range(4):
        updates, opt_state = tx.update(grads, opt_state, params)
        mags.append(float(-updates["w"][0]))
    # milestone (epoch 1 = optimizer step 2) lands after two UPDATES,
    # exactly as in a grad_accum=1 run
    assert mags == pytest.approx([1.0, 1.0, 0.1, 0.1])


def test_head_param_group_hyperparams():
    # The reference's single optimizer spans TWO param groups (backbone, ARC
    # margin head — arc_main.py:248-253). head_lr/head_weight_decay diverge
    # the groups; unset they inherit and the optimizer is one transform.
    import jax.numpy as jnp

    from ddp_classification_pytorch_tpu.train.schedule import build_optimizer

    params = {
        "backbone": {"w": jnp.ones((3,))},
        "margin": {"weight": jnp.ones((3,))},
    }
    grads = {
        "backbone": {"w": jnp.ones((3,))},
        "margin": {"weight": jnp.ones((3,))},
    }

    cfg = OptimConfig(optimizer="sgd", momentum=0.0, lr=0.1, head_lr=0.2,
                      schedule="constant")
    tx = build_optimizer(cfg, steps_per_epoch=10)
    updates, _ = tx.update(grads, tx.init(params), params)
    assert float(updates["backbone"]["w"][0]) == pytest.approx(-0.1)
    assert float(updates["margin"]["weight"][0]) == pytest.approx(-0.2)

    # head_weight_decay=0 while base decays: only backbone feels the decay
    cfg = OptimConfig(optimizer="sgd", momentum=0.0, lr=0.1,
                      weight_decay=0.5, head_weight_decay=0.0,
                      schedule="constant")
    tx = build_optimizer(cfg, steps_per_epoch=10)
    updates, _ = tx.update(grads, tx.init(params), params)
    # base: -(lr·(g + wd·p)) = -0.1·1.5 ; head: -0.1·1.0
    assert float(updates["backbone"]["w"][0]) == pytest.approx(-0.15)
    assert float(updates["margin"]["weight"][0]) == pytest.approx(-0.1)

    # unset → identical hyperparams per group, single-transform path
    cfg = OptimConfig(optimizer="sgd", momentum=0.0, lr=0.1, schedule="constant")
    tx = build_optimizer(cfg, steps_per_epoch=10)
    updates, _ = tx.update(grads, tx.init(params), params)
    assert float(updates["margin"]["weight"][0]) == pytest.approx(-0.1)


def test_head_group_flags_reject_headless_tree():
    # --head_lr on a workload without a margin head must fail loudly, not
    # silently train everything at the base hyperparams
    import jax.numpy as jnp

    from ddp_classification_pytorch_tpu.train.schedule import build_optimizer

    params = {"backbone": {"w": jnp.ones((3,))}}
    cfg = OptimConfig(optimizer="sgd", momentum=0.0, lr=0.1, head_lr=0.2,
                      schedule="constant")
    tx = build_optimizer(cfg, steps_per_epoch=10)
    with pytest.raises(ValueError, match="no head param group"):
        tx.init(params)
