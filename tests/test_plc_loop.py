"""PLC correction-loop tests.

The label-correction *algorithms* are unit-tested in test_labelnoise.py; here
we test the LOOP mechanics deterministically — f(x) collection order, label
write-back, δ carry-over — plus an e2e smoke run. (Whether a net repairs
labels on a given task is a research-dynamics property — early-learning vs
memorization — not a framework invariant, so no accuracy-of-repair assertion
on a live net.)"""

import numpy as np

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.data.synthetic import SyntheticDataset
from ddp_classification_pytorch_tpu.train.plc_loop import PLCTrainer


def _tiny_cfg(tmp_path, epochs=2):
    cfg = get_preset("plc")
    cfg.data.dataset = "synthetic"
    cfg.data.image_size = 32
    cfg.data.num_classes = 4
    cfg.data.synthetic_size = 128
    cfg.data.batch_size = 32
    cfg.data.num_workers = 2
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.optim.lr = 0.01
    cfg.optim.schedule = "constant"
    cfg.run.epochs = epochs
    cfg.run.write_records = False
    cfg.run.save_every_epoch = False
    cfg.run.out_dir = str(tmp_path)
    cfg.plc.warmup_epochs = 0
    cfg.plc.correction = "lrt"
    return cfg


def test_correct_labels_flips_by_oracle_predictions(tmp_path, monkeypatch):
    cfg = _tiny_cfg(tmp_path)
    train_ds = SyntheticDataset(128, 32, 4, seed=999)
    val_ds = SyntheticDataset(32, 32, 4, seed=999, item_offset=128)
    tr = PLCTrainer(cfg, train_ds, val_ds)

    clean = train_ds.labels.copy()
    noisy = clean.copy()
    noisy[:32] = (clean[:32] + 1) % 4  # corrupt the first 32
    train_ds.labels = noisy.astype(np.int32)

    # oracle predictions: fully confident in the CLEAN label
    oracle = np.full((128, 4), -10.0, np.float32)
    oracle[np.arange(128), clean] = 10.0
    monkeypatch.setattr(tr, "predict_train_logits", lambda: oracle)

    changed = tr.correct_labels()
    assert changed == 32
    np.testing.assert_array_equal(np.asarray(train_ds.labels), clean)
    # LRT flipped ≥0.1% of labels → δ must NOT grow
    assert tr.delta == cfg.plc.current_delta


def test_delta_grows_when_nothing_corrected(tmp_path, monkeypatch):
    cfg = _tiny_cfg(tmp_path)
    train_ds = SyntheticDataset(128, 32, 4, seed=999)
    val_ds = SyntheticDataset(32, 32, 4, seed=999, item_offset=128)
    tr = PLCTrainer(cfg, train_ds, val_ds)

    labels = np.asarray(train_ds.labels)
    agree = np.full((128, 4), -10.0, np.float32)
    agree[np.arange(128), labels] = 10.0  # predictions agree with labels
    monkeypatch.setattr(tr, "predict_train_logits", lambda: agree)

    assert tr.correct_labels() == 0
    assert tr.delta == cfg.plc.current_delta + cfg.plc.delta_increment


def test_predict_train_logits_order_and_shape(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    # non-multiple of batch size exercises the wrap-padding slice
    train_ds = SyntheticDataset(100, 32, 4, seed=999)
    val_ds = SyntheticDataset(32, 32, 4, seed=999, item_offset=100)
    tr = PLCTrainer(cfg, train_ds, val_ds)
    f_x = tr.predict_train_logits()
    assert f_x.shape == (100, 4)
    assert np.isfinite(f_x).all()


def test_plc_e2e_smoke(tmp_path):
    cfg = _tiny_cfg(tmp_path, epochs=2)
    train_ds = SyntheticDataset(128, 32, 4, seed=999)
    val_ds = SyntheticDataset(32, 32, 4, seed=999, item_offset=128)
    tr = PLCTrainer(cfg, train_ds, val_ds)
    last = tr.run()
    assert np.isfinite(last["loss"])
    assert "corrected" in last and "delta" in last


def test_noise_injection_at_init(tmp_path):
    cfg = _tiny_cfg(tmp_path)
    cfg.plc.noise_type = 1
    train_ds = SyntheticDataset(128, 32, 4, seed=999)
    val_ds = SyntheticDataset(32, 32, 4, seed=999, item_offset=128)
    clean = train_ds.labels.copy()
    rng = np.random.default_rng(5)
    eta = rng.random((128, 4)) * 0.2
    eta[np.arange(128), clean] += 1.0
    eta /= eta.sum(1, keepdims=True)
    tr = PLCTrainer(cfg, train_ds, val_ds, eta=eta)
    assert int((np.asarray(train_ds.labels) != clean).sum()) > 0


def test_plc_auto_resume_restores_labels_and_delta(tmp_path):
    """Preemption recovery for the PLC workload: --auto_resume must carry the
    corrected labels and δ across the restart, not just the model state."""
    cfg = _tiny_cfg(tmp_path, epochs=1)
    cfg.run.save_every_epoch = True
    cfg.run.auto_resume = True

    train_ds = SyntheticDataset(128, 32, 4, seed=999)
    val_ds = SyntheticDataset(32, 32, 4, seed=999, item_offset=128)
    tr = PLCTrainer(cfg, train_ds, val_ds)
    tr.delta = 0.37  # distinguishable carried state
    tr.run()
    labels_after = np.asarray(train_ds.labels).copy()
    delta_after = tr.delta

    tr2 = PLCTrainer(cfg, SyntheticDataset(128, 32, 4, seed=999), val_ds)
    assert tr2.start_epoch == 1
    assert tr2.delta == delta_after
    np.testing.assert_array_equal(np.asarray(tr2.train_ds.labels), labels_after)


def _write_imagefolder(root, classes=2, per_class=8, size=32):
    from PIL import Image

    rng = np.random.default_rng(0)
    for c in range(classes):
        d = root / f"class{c}"
        d.mkdir(parents=True)
        for i in range(per_class):
            arr = rng.integers(0, 255, (size, size, 3), np.uint8)
            Image.fromarray(arr.astype(np.uint8)).save(d / f"img{i}.png")


def test_predict_pipeline_is_eval_view_with_running_stats(tmp_path):
    """Regression for the round-2 label-collapse bug: f(x) for correction
    must come from the EVAL transform with running BN stats. Measured on a
    97%-val model, the class-sorted scan made batch-stat predictions 63%
    argmax-vs-truth (vs 99% running-stat) and collapsed 19% noise to 74%
    (train/plc_loop.py::_predict_pipeline). Pin the whole contract by
    equivalence: predict_train_logits() must equal a manual eval-mode
    forward over the eval-transformed images in dataset order."""
    _write_imagefolder(tmp_path / "train")
    _write_imagefolder(tmp_path / "val")
    cfg = _tiny_cfg(tmp_path / "out")
    cfg.data.dataset = "imagefolder"
    cfg.data.transform = "cifar"
    cfg.data.train_dir = str(tmp_path / "train")
    cfg.data.val_dir = str(tmp_path / "val")
    cfg.data.num_classes = 2
    cfg.data.batch_size = 8
    tr = PLCTrainer(cfg)

    assert cfg.plc.batch_stat_predictions is False  # running-stat default

    predict_ds, _ = tr._predict_pipeline()
    assert predict_ds is not tr.train_ds  # eval view, not the train dataset
    # the eval view must be deterministic where the train pipeline is not
    img_a = tr.train_ds.__getitem__(0, np.random.default_rng(1))[0]
    img_b = tr.train_ds.__getitem__(0, np.random.default_rng(2))[0]
    assert not np.array_equal(img_a, img_b)  # random crop/flip active
    img_e1 = predict_ds.__getitem__(0, np.random.default_rng(1))[0]
    img_e2 = predict_ds.__getitem__(0, np.random.default_rng(2))[0]
    np.testing.assert_array_equal(img_e1, img_e2)

    f_x = tr.predict_train_logits()
    # manual oracle: eval-transformed images in scan order, eval-mode apply
    # (train=False → running statistics). Any regression to the train
    # transform OR to batch-stat normalization breaks this equivalence.
    # The default uint8 wire defers normalization to the jitted predict
    # step's epilogue, so the oracle applies the same host-side normalize.
    from ddp_classification_pytorch_tpu.data.transforms import normalize

    rng = np.random.default_rng(0)
    imgs = np.stack([predict_ds.__getitem__(i, rng)[0]
                     for i in range(len(predict_ds))])
    if imgs.dtype == np.uint8:
        imgs = np.stack([normalize(x) for x in imgs])
    variables = {"params": tr.state.params, "batch_stats": tr.state.batch_stats}
    manual = tr.model.apply(variables, imgs, train=False)
    np.testing.assert_allclose(f_x, np.asarray(manual), rtol=1e-4, atol=1e-4)


def test_check_bad_images(tmp_path):
    """Corrupt files are reported by relative path; good ones are not
    (reference check_bad_image, PLC/FolderDataset.py:156-184)."""
    import numpy as np
    from PIL import Image

    from ddp_classification_pytorch_tpu.data.plc import check_bad_images

    root = tmp_path / "imgs"
    (root / "cat").mkdir(parents=True)
    Image.fromarray(
        np.zeros((8, 8, 3), np.uint8)).save(root / "cat" / "good.jpg")
    (root / "cat" / "bad.jpg").write_bytes(b"not a jpeg at all")
    bad = check_bad_images(str(root))
    import os
    assert bad == [os.path.join("cat", "bad.jpg")]
