"""AOT executable sidecar (serve/aot.py + ServingEngine.warmup).

The instant-cold-start contract: a cold replica compiles its bucket
programs once and banks the serialized executables in an aot/ sidecar;
the NEXT replica deserializes them and boots without compiling anything
— warmup() itself asserts zero predict compiles after a sidecar load, so
every warm-path test here re-proves the tentpole claim. Every corruption
mode must fall back to the cold path (serving correctness beats cold
start speed): stale fingerprint → recompile, torn payload → quarantine
(*.corrupt, same discipline as a torn checkpoint) + recompile, and a
checkpoint published WITHOUT a sidecar must still hot-reload.

Budget: buckets=(2,) everywhere — one compiled shape per cold engine.
"""

import os
from types import SimpleNamespace

import jax
import numpy as np
import pytest

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
from ddp_classification_pytorch_tpu.serve.engine import ServingEngine
from ddp_classification_pytorch_tpu.serve.metrics import ServeMetrics
from ddp_classification_pytorch_tpu.serve.reload import CheckpointWatcher
from ddp_classification_pytorch_tpu.train.checkpoint import CheckpointManager
from ddp_classification_pytorch_tpu.train.state import create_train_state
from ddp_classification_pytorch_tpu.train.steps import make_topk_predict_step

BUCKETS = (2,)


@pytest.fixture(scope="module")
def sv():
    cfg = get_preset("baseline")
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.data.num_classes = 8
    cfg.data.image_size = 32
    mesh = meshlib.serve_mesh(2)  # dp2 of conftest's 8 forced CPU devices
    model, _, state = create_train_state(cfg, mesh, steps_per_epoch=1)
    rng = np.random.default_rng(11)
    imgs = rng.integers(0, 256, (4, 32, 32, 3)).astype(np.uint8)
    return SimpleNamespace(cfg=cfg, mesh=mesh, model=model, state=state,
                           imgs=imgs)


def _engine(sv, aot_dir):
    """Fresh predict fn per engine: a real joining replica has an empty
    jit cache, so nothing but the sidecar may make its boot warm."""
    predict = make_topk_predict_step(sv.cfg, sv.model, 3, mesh=sv.mesh)
    return ServingEngine(sv.state, predict, image_size=32,
                         input_dtype="uint8", max_batch=2,
                         batch_timeout_ms=40.0, queue_depth=16,
                         buckets=BUCKETS, metrics=ServeMetrics(),
                         mesh=sv.mesh, aot_dir=aot_dir)


def _answer(engine, img):
    f = engine.submit(img)
    assert engine.process_once() == 1
    return f.result(timeout=30)


def test_warm_boot_deserializes_zero_compile_bit_identical(sv, tmp_path):
    """Cold boot banks the sidecar; a second engine boots warm off it —
    warmup() asserts zero predict compiles after the load (the tentpole
    acceptance), and warm answers are BIT-identical to cold ones."""
    aot_dir = str(tmp_path / "aot")
    cold = _engine(sv, aot_dir)
    cold.warmup()
    assert cold.aot_hit is False
    assert sorted(os.listdir(aot_dir)) == ["aot_b2.pkl", "manifest.json"]
    p_cold = _answer(cold, sv.imgs[0])

    warm = _engine(sv, aot_dir)
    warm.warmup()  # raises if ANY predict compile followed the load
    assert warm.aot_hit is True
    # the only sentinel event a warm boot may emit is the sidecar's
    # drift-probe LOWERING of the smallest bucket (jax logs at lowering
    # time); executing the deserialized programs emits none
    assert warm.compile_sentinel.total <= 1
    p_warm = _answer(warm, sv.imgs[0])
    np.testing.assert_array_equal(p_cold.indices, p_warm.indices)
    np.testing.assert_array_equal(p_cold.scores, p_warm.scores)  # bitwise


def test_stale_fingerprint_falls_back_to_compile(sv, tmp_path):
    """A sidecar from a different jax/platform/mesh must NOT load: the
    fingerprint gate rejects it and the replica compiles normally (and
    re-banks a fresh sidecar)."""
    import json

    aot_dir = str(tmp_path / "aot")
    _engine(sv, aot_dir).warmup()  # bank a valid sidecar
    manifest = os.path.join(aot_dir, "manifest.json")
    with open(manifest) as f:
        meta = json.load(f)
    meta["jax_version"] = "0.0.0-stale"
    with open(manifest, "w") as f:
        json.dump(meta, f)

    engine = _engine(sv, aot_dir)
    engine.warmup()  # cold path: compile, then re-bank
    assert engine.aot_hit is False
    assert _answer(engine, sv.imgs[1]).indices.shape == (3,)
    with open(manifest) as f:
        assert json.load(f)["jax_version"] == jax.__version__


def test_torn_payload_quarantined_then_compiles(sv, tmp_path):
    """A truncated executable payload is quarantined like a torn
    checkpoint (*.corrupt) and the boot falls back to compiling — a
    half-written sidecar can slow a boot, never wedge or corrupt it."""
    aot_dir = str(tmp_path / "aot")
    _engine(sv, aot_dir).warmup()
    payload = os.path.join(aot_dir, "aot_b2.pkl")
    with open(payload, "r+b") as f:
        f.truncate(32)

    engine = _engine(sv, aot_dir)
    engine.warmup()
    assert engine.aot_hit is False
    assert os.path.exists(payload + ".corrupt")
    assert os.path.exists(payload)  # re-banked fresh after the fallback
    assert _answer(engine, sv.imgs[2]).indices.shape == (3,)


def test_hot_reload_survives_sidecar_less_publish(sv, tmp_path):
    """A trainer publishes checkpoints, not sidecars: hot-reload onto an
    AOT-warmed engine must swap a verified checkpoint that arrives with
    no aot/ next to it — the warmed executables serve the new params."""
    aot_dir = str(tmp_path / "aot")
    run_dir = str(tmp_path / "run")
    engine = _engine(sv, aot_dir)
    engine.warmup()

    mgr = CheckpointManager(run_dir, async_save=False)
    state2 = sv.state.replace(params=jax.tree_util.tree_map(
        lambda x: x * 1.5, sv.state.params))
    mgr.save(state2, epoch=1)
    watcher = CheckpointWatcher(run_dir, engine, sv.state)
    assert watcher.check_once() is True
    assert watcher.loaded_epoch == 1

    got = _answer(engine, sv.imgs[0])
    ref = np.asarray(
        engine._predict(engine._state, np.stack([sv.imgs[0]] * 2))[0])
    np.testing.assert_array_equal(got.scores, ref[0])


def test_state_compatible_fences_shape_and_dtype_drift(sv):
    """The reload gate: params with the same values-but-different tree
    structure or leaf dtype must be rejected before a swap poisons the
    compiled predict (which is specialized to the old avals)."""
    engine = _engine(sv, "")
    scaled = sv.state.replace(params=jax.tree_util.tree_map(
        lambda x: x * 2.0, sv.state.params))
    assert engine.state_compatible(scaled) is True
    half = sv.state.replace(params=jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float16), sv.state.params))
    assert engine.state_compatible(half) is False
