"""Trainer-level e2e on the 3-axis (data×model×pipe) mesh.

tests/test_three_axis_pipeline.py pins the train-step math; this locks
the rest of the product surface on the same mesh: the Trainer loop
(config → mesh construction from --pp_stages → epoch → EXACT cross-shard
sharded-CE eval) and preemption recovery — a second Trainer auto-resumes
from the checkpoint, which re-places restored leaves onto 3-axis
shardings (blocks P('pipe'), margin weight P('model')) and must then
actually train.
"""

import jax
import numpy as np
import pytest

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
from ddp_classification_pytorch_tpu.train.loop import Trainer


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
def test_trainer_runs_and_resumes_on_three_axis_mesh(tmp_path):
    cfg = get_preset("arcface")
    cfg.data.dataset = "synthetic"
    cfg.data.synthetic_size = 64
    cfg.data.image_size = 32
    cfg.data.num_classes = 16
    cfg.data.batch_size = 16
    cfg.data.num_workers = 1
    cfg.model.arch = "vit_t16"
    cfg.model.dtype = "float32"
    cfg.model.dropout = 0.0
    cfg.parallel.data_axis = 2
    cfg.parallel.model_axis = 2
    cfg.parallel.pipeline_stages = 2
    cfg.parallel.pipeline_microbatches = 2
    cfg.parallel.arcface_sharded_ce = True
    cfg.run.epochs = 2
    cfg.run.out_dir = str(tmp_path)
    cfg.run.write_records = False
    cfg.run.auto_resume = True

    tr = Trainer(cfg)
    assert dict(tr.mesh.shape) == {"data": 2, "model": 2, "pipe": 2}
    m = tr.train_epoch(0)
    assert np.isfinite(m["loss"])
    ev = tr.evaluate()
    assert np.isfinite(ev["val_loss"])  # sharded-CE eval on the 3-axis mesh
    tr.ckpt.save(tr.state, 0, metric=0.5)
    tr.ckpt.wait()
    step_before = int(tr.state.step)

    tr2 = Trainer(cfg)  # restarted process, same command
    assert tr2.start_epoch == 1
    assert int(tr2.state.step) == step_before
    blocks_leaf = jax.tree_util.tree_leaves(
        tr2.state.params["backbone"]["blocks"])[0]
    assert blocks_leaf.sharding.spec[0] == meshlib.PIPE_AXIS
    w = tr2.state.params["margin"]["weight"]
    assert w.sharding.spec[0] == meshlib.MODEL_AXIS
    m2 = tr2.train_epoch(tr2.start_epoch)  # restored state must TRAIN
    assert np.isfinite(m2["loss"])
    assert int(tr2.state.step) > step_before
