"""ImageFolder → native dataplane → sharded train step, end to end.

Builds a real class-directory tree of JPEGs (the reference's data layout,
BASELINE/main.py:97-121), and trains one epoch with the native C++ loader
active, verifying the whole path produces finite metrics and the native
batcher is actually engaged.
"""

import numpy as np
import pytest
from PIL import Image

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.train.loop import Trainer


@pytest.fixture(scope="module")
def image_tree(tmp_path_factory):
    root = tmp_path_factory.mktemp("dataset")
    rng = np.random.default_rng(0)
    means = rng.integers(40, 215, size=(3, 3))
    for split in ("train", "val"):
        for c in range(3):
            d = root / split / f"class{c}"
            d.mkdir(parents=True)
            for i in range(8 if split == "train" else 4):
                img = np.clip(
                    means[c] + rng.normal(0, 25, (48, 48, 3)), 0, 255
                ).astype(np.uint8)
                Image.fromarray(img).save(d / f"{i}.jpg", quality=92)
    return root


def test_imagefolder_native_train(image_tree, tmp_path):
    cfg = get_preset("baseline")
    cfg.data.dataset = "imagefolder"
    cfg.data.train_dir = str(image_tree / "train")
    cfg.data.val_dir = str(image_tree / "val")
    cfg.data.num_classes = 3
    cfg.data.batch_size = 8
    cfg.data.image_size = 32
    cfg.data.train_crop_size = 40
    cfg.data.num_workers = 2
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.run.epochs = 1
    cfg.run.out_dir = str(tmp_path)
    cfg.run.write_records = False
    cfg.run.save_every_epoch = False

    tr = Trainer(cfg)
    assert tr.train_loader.batcher is not None, "native dataplane not engaged"
    m = tr.train_epoch(0)
    assert np.isfinite(m["loss"])
    val = tr.evaluate()
    assert 0.0 <= val["val_top1"] <= 1.0


def test_imagefolder_python_fallback(image_tree, tmp_path):
    cfg = get_preset("baseline")
    cfg.data.dataset = "imagefolder"
    cfg.data.train_dir = str(image_tree / "train")
    cfg.data.val_dir = str(image_tree / "val")
    cfg.data.native_loader = False
    cfg.data.num_classes = 3
    cfg.data.batch_size = 8
    cfg.data.image_size = 32
    cfg.data.train_crop_size = 40
    cfg.data.num_workers = 2
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.run.epochs = 1
    cfg.run.out_dir = str(tmp_path)
    cfg.run.write_records = False
    cfg.run.save_every_epoch = False

    tr = Trainer(cfg)
    assert tr.train_loader.batcher is None
    m = tr.train_epoch(0)
    assert np.isfinite(m["loss"])
