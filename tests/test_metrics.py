"""Metrics vs numpy oracles, incl. the reference getAcc conventions
(BASELINE/main.py:156-168,199-209)."""

import numpy as np
import jax.numpy as jnp

from ddp_classification_pytorch_tpu.utils.metrics import (
    AverageMeter, top1_top3, topk_accuracy,
)


def _oracle_topk(logits, labels, k):
    order = np.argsort(-logits, axis=1, kind="stable")[:, :k]
    return np.mean([labels[i] in order[i] for i in range(len(labels))])


def test_topk_matches_oracle():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(64, 10)).astype(np.float32)
    labels = rng.integers(0, 10, size=64)
    for k in (1, 3, 5):
        (acc,) = topk_accuracy(jnp.asarray(logits), jnp.asarray(labels), (k,))
        assert abs(float(acc) - _oracle_topk(logits, labels, k)) < 1e-6


def test_top1_top3_pair():
    logits = jnp.asarray(
        [[5.0, 1.0, 0.0, -1.0], [0.0, 1.0, 2.0, 3.0], [1.0, 0.9, 0.8, 0.7]]
    )
    labels = jnp.asarray([0, 0, 2])
    a1, a3 = top1_top3(logits, labels)
    assert abs(float(a1) - 1 / 3) < 1e-6  # only sample 0 is top-1 correct
    assert abs(float(a3) - 2 / 3) < 1e-6  # samples 0 and 2 within top-3


def test_topk_k_larger_than_classes():
    logits = jnp.asarray([[1.0, 0.0]])
    labels = jnp.asarray([1])
    (acc,) = topk_accuracy(logits, labels, (3,))
    assert float(acc) == 1.0


def test_average_meter():
    m = AverageMeter()
    m.update(1.0, 2)
    m.update(4.0, 1)
    assert abs(m.avg - 2.0) < 1e-9
    m.reset()
    assert m.avg == 0.0


def test_all_equal_logits_are_misses():
    # dead model (e.g. zero features through a bias-free head): every class
    # logit ties; tie-in-favor ranking would score 100% top-1 — ties must
    # count against the sample
    import jax.numpy as jnp

    from ddp_classification_pytorch_tpu.utils.metrics import topk_hits

    logits = jnp.zeros((6, 10))
    labels = jnp.arange(6)
    assert int(topk_hits(logits, labels, 1).sum()) == 0
    assert int(topk_hits(logits, labels, 3).sum()) == 0
