"""Cross-topology resume (VERDICT r3 weak #4 → next #2).

`train/checkpoint.py` promises that resume works on a DIFFERENT mesh
topology as long as shapes match: saves gather every TP-sharded leaf to a
fully-replicated host copy, and restore re-places the numpy leaves onto
whatever shardings the *template* state carries — so a template built on a
new mesh re-shards the restored values for that mesh. Until now that was a
docstring claim; this test makes it a behavioral one, in the fleet shape
it actually happens: a run is preempted, the replacement allocation has a
different device count or a different dp×tp split, and training must
continue as if nothing happened.

Topologies exercised (8-device virtual CPU mesh, conftest):
- save under data=4 × model=2 (TP-sharded ArcFace partial-FC head — the
  interesting case: a leaf that was 2-way sharded must come back 4-way);
- restore under data=2 × model=4 (same device count, different split);
- restore under data=2 × model=2 on FOUR devices (shrunk allocation).

Continuity is asserted against an uninterrupted control: the post-resume
losses replayed on the new topology must match the control's losses for
the same steps (same data, same step-keyed rng) to float32 reduction
tolerance — partitioning changes the reduction ORDER, so equality is
allclose, not bitwise.
"""

import jax
import numpy as np
import pytest

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
from ddp_classification_pytorch_tpu.train.checkpoint import CheckpointManager
from ddp_classification_pytorch_tpu.train.state import create_train_state
from ddp_classification_pytorch_tpu.train.steps import make_train_step

BATCH, CLASSES, SIZE, STEPS, SAVE_AFTER = 16, 64, 16, 4, 2


def _cfg(mp: int):
    cfg = get_preset("arcface")
    cfg.data.image_size = SIZE
    cfg.data.num_classes = CLASSES
    cfg.data.batch_size = BATCH
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.parallel.model_axis = mp
    cfg.parallel.arcface_sharded_ce = mp > 1
    return cfg


def _batches():
    rng = np.random.default_rng(42)
    return [
        (rng.normal(size=(BATCH, SIZE, SIZE, 3)).astype(np.float32),
         rng.integers(0, CLASSES, BATCH).astype(np.int32))
        for _ in range(STEPS)
    ]


@pytest.fixture(scope="module")
def saved(tmp_path_factory):
    """Control run on data=4×model=2: save at SAVE_AFTER, keep going."""
    assert len(jax.devices()) >= 8, "conftest must provision 8 CPU devices"
    td = tmp_path_factory.mktemp("xtopo")
    mesh_a = meshlib.make_mesh(meshlib.MeshSpec(4, 2), jax.devices()[:8])
    batches = _batches()
    cfg = _cfg(2)
    with mesh_a:
        model, tx, state = create_train_state(cfg, mesh_a, steps_per_epoch=STEPS)
        step = make_train_step(cfg, model, tx, mesh=mesh_a)
        control_losses = []
        ckpt = CheckpointManager(str(td), async_save=False)
        for i, (images, labels) in enumerate(batches):
            images = jax.device_put(images, meshlib.batch_sharding(mesh_a))
            labels = jax.device_put(labels, meshlib.batch_sharding(mesh_a))
            state, metrics = step(state, images, labels)
            control_losses.append(float(metrics["loss"]))
            if i + 1 == SAVE_AFTER:
                ckpt.save(state, epoch=0, metric=-control_losses[-1])
                ckpt.wait()
    assert all(np.isfinite(control_losses))
    return td, batches, control_losses


def _resume_and_replay(saved, mesh, mp):
    td, batches, control_losses = saved
    cfg = _cfg(mp)
    with mesh:
        model, tx, template = create_train_state(cfg, mesh, steps_per_epoch=STEPS)
        ckpt = CheckpointManager(str(td), async_save=False)
        restored = ckpt.restore(template, ckpt.epoch_path(0))
        assert int(restored.step) == SAVE_AFTER
        # the TP-sharded margin weight must carry the NEW mesh's sharding
        w = restored.params["margin"]["weight"]
        if mp > 1:
            assert w.sharding.spec[0] == meshlib.MODEL_AXIS, w.sharding
            assert w.sharding.mesh.shape[meshlib.MODEL_AXIS] == mp
        step = make_train_step(cfg, model, tx, mesh=mesh)
        losses = []
        state = restored
        for images, labels in batches[SAVE_AFTER:]:
            images = jax.device_put(images, meshlib.batch_sharding(mesh))
            labels = jax.device_put(labels, meshlib.batch_sharding(mesh))
            state, metrics = step(state, images, labels)
            losses.append(float(metrics["loss"]))
    np.testing.assert_allclose(
        losses, control_losses[SAVE_AFTER:], rtol=5e-4, atol=1e-5,
        err_msg=f"post-resume curve diverged on {dict(mesh.shape)}")


def test_resume_same_devices_different_split(saved):
    """data=4×model=2 → data=2×model=4: the head shard width halves."""
    mesh_b = meshlib.make_mesh(meshlib.MeshSpec(2, 4), jax.devices()[:8])
    _resume_and_replay(saved, mesh_b, mp=4)


def test_resume_on_fewer_devices(saved):
    """8 devices → 4 devices (data=2×model=2): the preempt-then-resize
    fleet scenario — the replacement allocation is smaller."""
    mesh_c = meshlib.make_mesh(meshlib.MeshSpec(2, 2), jax.devices()[:4])
    _resume_and_replay(saved, mesh_c, mp=2)


def test_resume_collapses_tp_to_pure_dp(saved):
    """data=4×model=2 → data=8×model=1: the sharded head collapses to the
    dense path (no model axis). Values must still restore; the dense
    ArcFace CE must produce the same losses the partial-FC control did —
    the exactness claim of ops/sharded_head.py applied across a resume."""
    mesh_d = meshlib.make_mesh(meshlib.MeshSpec(8, 1), jax.devices()[:8])
    _resume_and_replay(saved, mesh_d, mp=1)
