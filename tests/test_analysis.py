"""Program-invariant analyzer (analysis/, cli.analyze).

Two halves, per the acceptance contract:

1. **Every detector must trip on a known-bad sample** — an undonated dead
   arg, a host callback inside jit, a uint8 input bypassing the normalize
   epilogue, a collective in a host-local program, host-sync idioms in a
   step factory, an uncatalogued CLI exit code, a steady-state recompile.
   The fixtures are 3-line jits/sources, so each proof costs milliseconds.

2. **The real repo passes** — ONE module-scoped run of the full registry
   audit (the only expensive trace/compile in this file; tier-1 budget),
   asserted clean, with the train steps' donation coverage at exactly 1.0
   (the before/after aliased-bytes evidence the MFU item owes).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddp_classification_pytorch_tpu.analysis import Finding
from ddp_classification_pytorch_tpu.analysis.compile_sentinel import (
    CompileSentinel,
    SteadyStateRecompile,
)
from ddp_classification_pytorch_tpu.analysis.jaxpr_audit import (
    AuditContext,
    StepSpec,
    audit_donation,
    audit_entry,
    audit_registry,
    build_registry,
    donation_evidence,
)
from ddp_classification_pytorch_tpu.analysis.lint import (
    lint_factory_source,
    lint_rc_sites,
    lint_rc_source,
    lint_step_factories,
)

# --------------------------------------------------------------- fixtures --


@pytest.fixture(scope="module")
def audit():
    """The one expensive piece: the full registry audit (state inits, six
    jaxpr traces, two donated-step compiles) — shared by every
    real-repo assertion below."""
    from types import SimpleNamespace

    ctx = AuditContext()
    findings, specs = audit_registry(ctx)
    return SimpleNamespace(ctx=ctx, findings=findings,
                           specs={s.name: s for s in specs})


def _fixture_spec(fn, args, **kw):
    return StepSpec(name="fixture", factory="tests:fixture",
                    build=lambda ctx: (fn, args), **kw)


# ------------------------------------------------- detectors must trip --


def test_donation_detector_fires_on_unaliased_donated_arg(audit):
    """A donated buffer with no same-shape output cannot alias — the audit
    must report the gap with byte counts, not stay silent."""
    fn = jax.jit(lambda s: s[:2].sum(), donate_argnums=0)
    findings, ev = audit_donation(fn, (jnp.zeros((8, 8), jnp.float32),),
                                  "fixture")
    assert findings and findings[0].check == "donation"
    assert ev["donated_bytes"] == 8 * 8 * 4
    assert ev["aliased_bytes"] < ev["donated_bytes"]
    assert "bytes" in findings[0].message


def test_donation_detector_fires_on_missing_donation(audit):
    """A registry entry that PROMISES donation must fail when the factory
    jits without donate_argnums (the exact regression the ROADMAP's MFU
    item guards against)."""
    fn = jax.jit(lambda s, x: (s + x.sum(), x * 2))  # state NOT donated
    spec = _fixture_spec(fn, (jnp.zeros((16, 16), jnp.float32),
                              jax.ShapeDtypeStruct((4,), jnp.float32)),
                         donate=(0,))
    findings = audit_entry(spec, audit.ctx)
    assert any(f.check == "donation" for f in findings)


def test_callback_detector_fires_on_debug_print(audit):
    def bad(x):
        jax.debug.print("x = {x}", x=x)
        return x * 2

    spec = _fixture_spec(jax.jit(bad),
                         (jax.ShapeDtypeStruct((4,), jnp.float32),),
                         no_donate_reason="fixture")
    findings = audit_entry(spec, audit.ctx)
    assert any(f.check == "callback" for f in findings)
    assert any("debug_callback" in str(f.evidence) for f in findings)


def test_collective_detector_fires_and_allowlist_clears(audit):
    from jax.sharding import PartitionSpec as P

    from ddp_classification_pytorch_tpu.utils.compat import shard_map_unchecked

    mesh = audit.ctx.mesh
    fn = jax.jit(shard_map_unchecked(
        lambda x: jax.lax.psum(x, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P()))
    args = (jax.ShapeDtypeStruct((8,), jnp.float32),)
    hit = audit_entry(_fixture_spec(fn, args, no_donate_reason="fixture"),
                      audit.ctx)
    assert any(f.check == "collectives" and "psum" in f.message for f in hit)
    clean = audit_entry(_fixture_spec(fn, args, no_donate_reason="fixture",
                                      allow_collectives=True), audit.ctx)
    assert not [f for f in clean if f.check == "collectives"]


def test_uint8_detector_fires_on_epilogue_bypass(audit):
    """Raw pixels converted to float WITHOUT the /255 normalize = the uint8
    dataplane contract broken (PR 3's NOTE: every new step must call
    device_input_epilogue)."""
    fn = jax.jit(lambda x: x.astype(jnp.float32).sum())
    spec = _fixture_spec(fn, (jax.ShapeDtypeStruct((4, 8, 8, 3), jnp.uint8),),
                         no_donate_reason="fixture", uint8_input=True)
    findings = audit_entry(spec, audit.ctx)
    assert any(f.check == "uint8-epilogue" for f in findings)


def test_uint8_detector_fires_on_direct_consumption(audit):
    """uint8 fed straight into arithmetic (no convert at all) must flag."""
    fn = jax.jit(lambda x: (x * 2).sum())
    spec = _fixture_spec(fn, (jax.ShapeDtypeStruct((4, 8, 8, 3), jnp.uint8),),
                         no_donate_reason="fixture", uint8_input=True)
    findings = audit_entry(spec, audit.ctx)
    assert any(f.check == "uint8-epilogue" for f in findings)


def test_uint8_detector_passes_the_real_epilogue(audit):
    from ddp_classification_pytorch_tpu.train.steps import device_input_epilogue

    fn = jax.jit(lambda x: device_input_epilogue(x).sum())
    spec = _fixture_spec(fn, (jax.ShapeDtypeStruct((4, 8, 8, 3), jnp.uint8),),
                         no_donate_reason="fixture", uint8_input=True)
    findings = audit_entry(spec, audit.ctx)
    assert not [f for f in findings if f.check == "uint8-epilogue"]


_BAD_FACTORY = '''
import time
import numpy as np

def make_bad_step(model):
    def step(state, images):
        t0 = time.time()
        print("loss so far")
        host = np.asarray(images)
        return float(state.loss) + state.loss.item() + host.mean() + t0
    return step
'''


def test_host_sync_lint_fires_on_every_idiom():
    findings = lint_factory_source(_BAD_FACTORY, function="make_bad_step")
    msgs = " | ".join(f.message for f in findings)
    for idiom in (".item()", "print", "np.asarray", "time.time", "float()"):
        assert idiom in msgs, (idiom, msgs)
    assert len(findings) == 5


def test_host_sync_lint_flags_stale_provenance():
    findings = lint_factory_source("x = 1\n", function="make_missing")
    assert findings and "not found" in findings[0].message


def test_rc_lint_fires_on_uncatalogued_codes():
    assert lint_rc_source("import sys\nsys.exit(13)\n")
    assert lint_rc_source("raise SystemExit(99)\n")
    assert lint_rc_source("import sys\nsys.exit(compute_rc())\n")


def test_rc_lint_passes_catalogued_patterns():
    src = (
        "import sys\n"
        "sys.exit(2)\n"
        "raise SystemExit(0 if ok else 1)\n"
        "raise SystemExit(SentinelDiverged.exit_code)\n"
        "raise SystemExit(e.code)\n"
    )
    assert lint_rc_source(src) == []


# --------------------------------------------------- the real repo passes --


def test_registry_names_every_step_program():
    names = {s.name for s in build_registry()}
    assert names == {"train_step", "eval_step", "nested_eval_step",
                     "plc_predict", "topk_predict", "shard_map_train_step",
                     "train_step_survivor"}
    for spec in build_registry():
        # every entry either donates or documents why it must not
        assert spec.donate or spec.no_donate_reason, spec.name


def test_self_audit_repo_is_clean(audit):
    assert audit.findings == [], [str(f) for f in audit.findings]


def test_train_steps_donation_fully_aliased(audit):
    """The MFU item's donation audit: every donated state byte is aliased
    in BOTH train-step executables — no buffer round-trips HBM."""
    for name in ("train_step", "shard_map_train_step"):
        don = audit.specs[name].evidence["donation"]
        assert don["donated_bytes"] > 10_000_000, (name, don)  # real state
        assert don["donation_coverage"] == 1.0, (name, don)
        assert don["unaliased"] == [], (name, don)


def test_step_factories_lint_clean():
    assert lint_step_factories() == []


def test_cli_rc_sites_lint_clean():
    assert lint_rc_sites() == []


def test_analyze_cli_rc2_on_bad_pass():
    from ddp_classification_pytorch_tpu.cli.analyze import main

    with pytest.raises(SystemExit) as e:
        main(["--passes", "bogus"])
    assert e.value.code == 2


def test_analyze_cli_rc1_on_findings(tmp_path):
    """Findings → rc 1, proven via an explicit rc-lint target (the same
    surface the CLI uses for the cli/ package)."""
    from ddp_classification_pytorch_tpu.cli.analyze import main

    bad = tmp_path / "bad_cli.py"
    bad.write_text("import sys\nsys.exit(13)\n")
    with pytest.raises(SystemExit) as e:
        main(["--passes", "lint", "--rc-paths", str(bad)])
    assert e.value.code == 1


def test_analyze_cli_lint_pass_clean(capsys):
    from ddp_classification_pytorch_tpu.cli.analyze import main

    main(["--passes", "lint"])  # returns (rc 0) or raises SystemExit
    assert "clean" in capsys.readouterr().out


# ------------------------------------------------------- compile sentinel --


def test_compile_sentinel_counts_compiles_not_cache_hits():
    sent = CompileSentinel(tag="t").arm()
    try:
        @jax.jit
        def fresh_fn(x):
            return x * 3 + 1

        fresh_fn(np.ones(3, np.float32))
        assert any(e.name == "fresh_fn" for e in sent.take())
        fresh_fn(np.ones(3, np.float32))  # cache hit: silent
        assert [e for e in sent.take() if e.name == "fresh_fn"] == []
        fresh_fn(np.ones(5, np.float32))  # new shape: recompile
        with pytest.raises(SteadyStateRecompile):
            sent.check(strict=True)
        assert sent.violations >= 1
    finally:
        sent.disarm()
    assert not sent.armed


def test_compile_sentinel_event_carries_signature():
    sent = CompileSentinel(tag="t").arm()
    try:
        @jax.jit
        def sig_fn(x):
            return x + 1

        sig_fn(np.ones((2, 7), np.float32))
        events = [e for e in sent.take() if e.name == "sig_fn"]
        assert events and "2,7" in events[0].signature
    finally:
        sent.disarm()


def _fake_predict():
    """A tiny jitted predict with the serve signature — the engine's
    compile accounting doesn't care that it isn't a model."""

    @jax.jit
    def step(state, images):
        x = images.astype(jnp.float32).mean(axis=(1, 2, 3)) * state["w"]
        scores = jnp.stack([x, -x], axis=1)
        idx = jnp.broadcast_to(jnp.arange(2, dtype=jnp.int32), scores.shape)
        return scores, idx

    return step


def _engine(predict, **kw):
    from ddp_classification_pytorch_tpu.serve.engine import ServingEngine

    kw.setdefault("image_size", 8)
    kw.setdefault("input_dtype", "uint8")
    kw.setdefault("max_batch", 4)
    kw.setdefault("batch_timeout_ms", 5.0)
    kw.setdefault("buckets", (2, 4))
    return ServingEngine({"w": jnp.ones(())}, predict, **kw)


def test_serve_warmup_asserts_exact_compile_count_and_stays_armed():
    predict = _fake_predict()
    engine = _engine(predict)
    try:
        engine.warmup()  # cold predict: exactly len(buckets) programs
        assert engine.compiled_programs() == 2
        assert engine.compile_sentinel is not None
        assert engine.compile_sentinel.armed
        # a second engine over the now-warm predict must not false-positive
        engine2 = _engine(predict)
        engine2.warmup()
        engine2.close()
    finally:
        engine.close()


def test_serve_steady_state_recompile_counted_and_strict_fatal():
    from ddp_classification_pytorch_tpu.serve.metrics import ServeMetrics

    predict = _fake_predict()
    metrics = ServeMetrics()
    engine = _engine(predict, metrics=metrics, strict_compile=True)
    try:
        engine.warmup()
        # steady state: a bucket-shaped batch is a cache hit, no violation
        engine.submit(np.zeros((8, 8, 3), np.uint8))
        assert engine.process_once() == 1
        assert metrics.snapshot()["recompiles"] == 0
        # someone sneaks a non-bucket shape through the shared predict:
        # the NEXT batch boundary must catch the compile
        predict({"w": jnp.ones(())}, np.zeros((3, 8, 8, 3), np.uint8))
        engine.submit(np.zeros((8, 8, 3), np.uint8))
        with pytest.raises(SteadyStateRecompile):
            engine.process_once()
        assert engine.fatal_error is not None
        assert metrics.snapshot()["recompiles"] >= 1
        assert engine.closed  # intake stopped
    finally:
        engine.close()


def test_donation_evidence_fields():
    """bench.py's e2e evidence rides this helper: the fields must exist and
    a fully-aliasable donated arg must report coverage 1.0."""
    fn = jax.jit(lambda s, x: (s + x.sum(), x * 2), donate_argnums=0)
    ev = donation_evidence(fn, (jnp.zeros((32, 32), jnp.float32),
                                jax.ShapeDtypeStruct((4,), jnp.float32)))
    assert ev["donated_bytes"] == 32 * 32 * 4
    assert ev["donation_coverage"] == 1.0
    assert ev["unaliased"] == []
    assert isinstance(ev["temp_bytes"], int)


def test_finding_renders_as_one_line():
    f = Finding("donation", "train_step", "gap", {"bytes": 4})
    assert str(f) == "[donation] train_step: gap"
