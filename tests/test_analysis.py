"""Program-invariant analyzer (analysis/, cli.analyze).

Two halves, per the acceptance contract:

1. **Every detector must trip on a known-bad sample** — an undonated dead
   arg, a host callback inside jit, a uint8 input bypassing the normalize
   epilogue, a collective in a host-local program, host-sync idioms in a
   step factory, an uncatalogued CLI exit code, a steady-state recompile.
   The fixtures are 3-line jits/sources, so each proof costs milliseconds.

2. **The real repo passes** — ONE module-scoped run of the full registry
   audit (the only expensive trace/compile in this file; tier-1 budget),
   asserted clean, with the train steps' donation coverage at exactly 1.0
   (the before/after aliased-bytes evidence the MFU item owes).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddp_classification_pytorch_tpu.analysis import Finding
from ddp_classification_pytorch_tpu.analysis.compile_sentinel import (
    CompileSentinel,
    SteadyStateRecompile,
)
from ddp_classification_pytorch_tpu.analysis.jaxpr_audit import (
    AuditContext,
    StepSpec,
    audit_donation,
    audit_entry,
    audit_registry,
    build_registry,
    donation_evidence,
)
from ddp_classification_pytorch_tpu.analysis import baseline as baselib
from ddp_classification_pytorch_tpu.analysis.lint import (
    lint_factory_source,
    lint_rc_sites,
    lint_rc_source,
    lint_step_factories,
)
from ddp_classification_pytorch_tpu.analysis.sharding_audit import (
    EVAL_COMMS,
    TRAIN_COMMS,
    _param_bytes,
    _spans_data,
    audit_collectives,
    audit_sharded_case,
    audit_sharding_table,
    collective_inventory,
    parse_replica_groups,
    sharded_registry,
    step_comms_evidence,
)

# --------------------------------------------------------------- fixtures --


@pytest.fixture(scope="module")
def audit():
    """The one expensive piece: the full registry audit (state inits, the
    jaxpr traces incl. the dp×tp entries, two donated-step compiles) —
    shared by every real-repo assertion below."""
    from types import SimpleNamespace

    ctx = AuditContext()
    findings, specs = audit_registry(ctx)
    return SimpleNamespace(ctx=ctx, findings=findings,
                           specs={s.name: s for s in specs})


@pytest.fixture(scope="module")
def sharded(audit):
    """Tier-1-lean sharded matrix subset: ONE lower+compile per composed
    mesh — the dp2 train cell (the acceptance cell: gradient all-reduce
    set + donation coverage under a ≥2-device mesh) and the dp2tp2 eval
    cell (the model-axis layout). The full 8-cell matrix runs in the
    slow-marked CLI test and in scripts/lint.sh."""
    from types import SimpleNamespace

    want = {"train_step@dp2", "eval_step@dp2tp2"}
    findings, records = [], {}
    for case in sharded_registry():
        if case.key not in want:
            continue
        f, rec = audit_sharded_case(case, audit.ctx)
        findings += f
        records[case.key] = rec
    assert set(records) == want  # the registry must keep both cells
    return SimpleNamespace(findings=findings, records=records)


def _fixture_spec(fn, args, **kw):
    return StepSpec(name="fixture", factory="tests:fixture",
                    build=lambda ctx: (fn, args), **kw)


# ------------------------------------------------- detectors must trip --


def test_donation_detector_fires_on_unaliased_donated_arg(audit):
    """A donated buffer with no same-shape output cannot alias — the audit
    must report the gap with byte counts, not stay silent."""
    fn = jax.jit(lambda s: s[:2].sum(), donate_argnums=0)
    findings, ev = audit_donation(fn, (jnp.zeros((8, 8), jnp.float32),),
                                  "fixture")
    assert findings and findings[0].check == "donation"
    assert ev["donated_bytes"] == 8 * 8 * 4
    assert ev["aliased_bytes"] < ev["donated_bytes"]
    assert "bytes" in findings[0].message


def test_donation_detector_fires_on_missing_donation(audit):
    """A registry entry that PROMISES donation must fail when the factory
    jits without donate_argnums (the exact regression the ROADMAP's MFU
    item guards against)."""
    fn = jax.jit(lambda s, x: (s + x.sum(), x * 2))  # state NOT donated
    spec = _fixture_spec(fn, (jnp.zeros((16, 16), jnp.float32),
                              jax.ShapeDtypeStruct((4,), jnp.float32)),
                         donate=(0,))
    findings = audit_entry(spec, audit.ctx)
    assert any(f.check == "donation" for f in findings)


def test_callback_detector_fires_on_debug_print(audit):
    def bad(x):
        jax.debug.print("x = {x}", x=x)
        return x * 2

    spec = _fixture_spec(jax.jit(bad),
                         (jax.ShapeDtypeStruct((4,), jnp.float32),),
                         no_donate_reason="fixture")
    findings = audit_entry(spec, audit.ctx)
    assert any(f.check == "callback" for f in findings)
    assert any("debug_callback" in str(f.evidence) for f in findings)


def test_collective_detector_fires_and_allowlist_clears(audit):
    from jax.sharding import PartitionSpec as P

    from ddp_classification_pytorch_tpu.utils.compat import shard_map_unchecked

    mesh = audit.ctx.mesh
    fn = jax.jit(shard_map_unchecked(
        lambda x: jax.lax.psum(x, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P()))
    args = (jax.ShapeDtypeStruct((8,), jnp.float32),)
    hit = audit_entry(_fixture_spec(fn, args, no_donate_reason="fixture"),
                      audit.ctx)
    assert any(f.check == "collectives" and "psum" in f.message for f in hit)
    clean = audit_entry(_fixture_spec(fn, args, no_donate_reason="fixture",
                                      allow_collectives=True), audit.ctx)
    assert not [f for f in clean if f.check == "collectives"]


def test_uint8_detector_fires_on_epilogue_bypass(audit):
    """Raw pixels converted to float WITHOUT the /255 normalize = the uint8
    dataplane contract broken (PR 3's NOTE: every new step must call
    device_input_epilogue)."""
    fn = jax.jit(lambda x: x.astype(jnp.float32).sum())
    spec = _fixture_spec(fn, (jax.ShapeDtypeStruct((4, 8, 8, 3), jnp.uint8),),
                         no_donate_reason="fixture", uint8_input=True)
    findings = audit_entry(spec, audit.ctx)
    assert any(f.check == "uint8-epilogue" for f in findings)


def test_uint8_detector_fires_on_direct_consumption(audit):
    """uint8 fed straight into arithmetic (no convert at all) must flag."""
    fn = jax.jit(lambda x: (x * 2).sum())
    spec = _fixture_spec(fn, (jax.ShapeDtypeStruct((4, 8, 8, 3), jnp.uint8),),
                         no_donate_reason="fixture", uint8_input=True)
    findings = audit_entry(spec, audit.ctx)
    assert any(f.check == "uint8-epilogue" for f in findings)


def test_uint8_detector_passes_the_real_epilogue(audit):
    from ddp_classification_pytorch_tpu.train.steps import device_input_epilogue

    fn = jax.jit(lambda x: device_input_epilogue(x).sum())
    spec = _fixture_spec(fn, (jax.ShapeDtypeStruct((4, 8, 8, 3), jnp.uint8),),
                         no_donate_reason="fixture", uint8_input=True)
    findings = audit_entry(spec, audit.ctx)
    assert not [f for f in findings if f.check == "uint8-epilogue"]


_BAD_FACTORY = '''
import time
import numpy as np

def make_bad_step(model):
    def step(state, images):
        t0 = time.time()
        print("loss so far")
        host = np.asarray(images)
        return float(state.loss) + state.loss.item() + host.mean() + t0
    return step
'''


def test_host_sync_lint_fires_on_every_idiom():
    findings = lint_factory_source(_BAD_FACTORY, function="make_bad_step")
    msgs = " | ".join(f.message for f in findings)
    for idiom in (".item()", "print", "np.asarray", "time.time", "float()"):
        assert idiom in msgs, (idiom, msgs)
    assert len(findings) == 5


def test_host_sync_lint_flags_stale_provenance():
    findings = lint_factory_source("x = 1\n", function="make_missing")
    assert findings and "not found" in findings[0].message


def test_rc_lint_fires_on_uncatalogued_codes():
    assert lint_rc_source("import sys\nsys.exit(13)\n")
    assert lint_rc_source("raise SystemExit(99)\n")
    assert lint_rc_source("import sys\nsys.exit(compute_rc())\n")


def test_rc_lint_passes_catalogued_patterns():
    src = (
        "import sys\n"
        "sys.exit(2)\n"
        "raise SystemExit(0 if ok else 1)\n"
        "raise SystemExit(SentinelDiverged.exit_code)\n"
        "raise SystemExit(e.code)\n"
    )
    assert lint_rc_source(src) == []


# --------------------------------------------------- the real repo passes --


def test_registry_names_every_step_program():
    names = {s.name for s in build_registry()}
    assert names == {"train_step", "eval_step", "nested_eval_step",
                     "plc_predict", "topk_predict", "shard_map_train_step",
                     "train_step_survivor",
                     # the bf16-wire gradient-reduction variant of the
                     # shard_map train step (--grad_reduce_dtype bfloat16)
                     "train_step_bf16_reduce",
                     # the same eval-family programs traced under the
                     # composed dp×tp mesh (sharded audit satellites)
                     "eval_step_dp_tp", "nested_eval_step_dp_tp",
                     "plc_predict_dp_tp", "topk_predict_dp_tp",
                     # the dp-sharded serving predict (serve mesh assembles
                     # data-sharded global batches; docs/serving.md)
                     "topk_predict_serve_dp", "topk_predict_serve_dp_tp",
                     # the fleet-width serve predict (dp4 — the autoscaler's
                     # max-replica provisioning shape; docs/serving.md)
                     "topk_predict_serve_fleet",
                     # the K-microbatch accumulated step (--grad_accum 4):
                     # lax.scan over microbatches, ONE deferred data-axis
                     # gradient reduction per optimizer step
                     "train_step_accum4"}
    for spec in build_registry():
        # every entry either donates or documents why it must not
        assert spec.donate or spec.no_donate_reason, spec.name


def test_self_audit_repo_is_clean(audit):
    assert audit.findings == [], [str(f) for f in audit.findings]


def test_train_steps_donation_fully_aliased(audit):
    """The MFU item's donation audit: every donated state byte is aliased
    in BOTH train-step executables — no buffer round-trips HBM."""
    for name in ("train_step", "shard_map_train_step"):
        don = audit.specs[name].evidence["donation"]
        assert don["donated_bytes"] > 10_000_000, (name, don)  # real state
        assert don["donation_coverage"] == 1.0, (name, don)
        assert don["unaliased"] == [], (name, don)


def test_step_factories_lint_clean():
    assert lint_step_factories() == []


def test_cli_rc_sites_lint_clean():
    assert lint_rc_sites() == []


def test_analyze_cli_rc2_on_bad_pass():
    from ddp_classification_pytorch_tpu.cli.analyze import main

    with pytest.raises(SystemExit) as e:
        main(["--passes", "bogus"])
    assert e.value.code == 2


def test_analyze_cli_rc1_on_findings(tmp_path):
    """Findings → rc 1, proven via an explicit rc-lint target (the same
    surface the CLI uses for the cli/ package)."""
    from ddp_classification_pytorch_tpu.cli.analyze import main

    bad = tmp_path / "bad_cli.py"
    bad.write_text("import sys\nsys.exit(13)\n")
    with pytest.raises(SystemExit) as e:
        main(["--passes", "lint", "--rc-paths", str(bad)])
    assert e.value.code == 1


def test_analyze_cli_lint_pass_clean(capsys):
    from ddp_classification_pytorch_tpu.cli.analyze import main

    main(["--passes", "lint"])  # returns (rc 0) or raises SystemExit
    assert "clean" in capsys.readouterr().out


# ------------------------------------------------------- compile sentinel --


def test_compile_sentinel_counts_compiles_not_cache_hits():
    sent = CompileSentinel(tag="t").arm()
    try:
        @jax.jit
        def fresh_fn(x):
            return x * 3 + 1

        fresh_fn(np.ones(3, np.float32))
        assert any(e.name == "fresh_fn" for e in sent.take())
        fresh_fn(np.ones(3, np.float32))  # cache hit: silent
        assert [e for e in sent.take() if e.name == "fresh_fn"] == []
        fresh_fn(np.ones(5, np.float32))  # new shape: recompile
        with pytest.raises(SteadyStateRecompile):
            sent.check(strict=True)
        assert sent.violations >= 1
    finally:
        sent.disarm()
    assert not sent.armed


def test_compile_sentinel_event_carries_signature():
    sent = CompileSentinel(tag="t").arm()
    try:
        @jax.jit
        def sig_fn(x):
            return x + 1

        sig_fn(np.ones((2, 7), np.float32))
        events = [e for e in sent.take() if e.name == "sig_fn"]
        assert events and "2,7" in events[0].signature
    finally:
        sent.disarm()


def _fake_predict():
    """A tiny jitted predict with the serve signature — the engine's
    compile accounting doesn't care that it isn't a model."""

    @jax.jit
    def step(state, images):
        x = images.astype(jnp.float32).mean(axis=(1, 2, 3)) * state["w"]
        scores = jnp.stack([x, -x], axis=1)
        idx = jnp.broadcast_to(jnp.arange(2, dtype=jnp.int32), scores.shape)
        return scores, idx

    return step


def _engine(predict, **kw):
    from ddp_classification_pytorch_tpu.serve.engine import ServingEngine

    kw.setdefault("image_size", 8)
    kw.setdefault("input_dtype", "uint8")
    kw.setdefault("max_batch", 4)
    kw.setdefault("batch_timeout_ms", 5.0)
    kw.setdefault("buckets", (2, 4))
    return ServingEngine({"w": jnp.ones(())}, predict, **kw)


def test_serve_warmup_asserts_exact_compile_count_and_stays_armed():
    predict = _fake_predict()
    engine = _engine(predict)
    try:
        engine.warmup()  # cold predict: exactly len(buckets) programs
        assert engine.compiled_programs() == 2
        assert engine.compile_sentinel is not None
        assert engine.compile_sentinel.armed
        # a second engine over the now-warm predict must not false-positive
        engine2 = _engine(predict)
        engine2.warmup()
        engine2.close()
    finally:
        engine.close()


def test_serve_steady_state_recompile_counted_and_strict_fatal():
    from ddp_classification_pytorch_tpu.serve.metrics import ServeMetrics

    predict = _fake_predict()
    metrics = ServeMetrics()
    engine = _engine(predict, metrics=metrics, strict_compile=True)
    try:
        engine.warmup()
        # steady state: a bucket-shaped batch is a cache hit, no violation
        engine.submit(np.zeros((8, 8, 3), np.uint8))
        assert engine.process_once() == 1
        assert metrics.snapshot()["recompiles"] == 0
        # someone sneaks a non-bucket shape through the shared predict:
        # the NEXT batch boundary must catch the compile
        predict({"w": jnp.ones(())}, np.zeros((3, 8, 8, 3), np.uint8))
        engine.submit(np.zeros((8, 8, 3), np.uint8))
        with pytest.raises(SteadyStateRecompile):
            engine.process_once()
        assert engine.fatal_error is not None
        assert metrics.snapshot()["recompiles"] >= 1
        assert engine.closed  # intake stopped
    finally:
        engine.close()


def test_donation_evidence_fields():
    """bench.py's e2e evidence rides this helper: the fields must exist and
    a fully-aliasable donated arg must report coverage 1.0."""
    fn = jax.jit(lambda s, x: (s + x.sum(), x * 2), donate_argnums=0)
    ev = donation_evidence(fn, (jnp.zeros((32, 32), jnp.float32),
                                jax.ShapeDtypeStruct((4,), jnp.float32)))
    assert ev["donated_bytes"] == 32 * 32 * 4
    assert ev["donation_coverage"] == 1.0
    assert ev["unaliased"] == []
    assert isinstance(ev["temp_bytes"], int)


def test_finding_renders_as_one_line():
    f = Finding("donation", "train_step", "gap", {"bytes": 4})
    assert str(f) == "[donation] train_step: gap"


# -------------------------------------------- sharding & comms audit --


def test_sharded_cells_audit_clean(sharded):
    assert sharded.findings == [], [str(f) for f in sharded.findings]


def test_dp_train_step_carries_gradient_allreduce_set(sharded, audit):
    """The acceptance invariant: under a ≥2-device data mesh the ZeRO-1
    train step carries exactly the gradient all-reduce plus the param
    all-gather that re-assembles the shard-local optimizer update (no
    stray kinds), the data-spanning reduce payload covers every parameter
    byte (the gradient set is present, not truncated), and donation
    coverage stays exactly 1.0."""
    rec = sharded.records["train_step@dp2"]
    assert set(rec["collectives"]) == {"all-reduce", "all-gather"}
    ar = rec["collectives"]["all-reduce"]
    got = sum(b for label, b in ar["axes"].items() if _spans_data(label))
    assert got >= _param_bytes(audit.ctx) > 10_000_000
    # the ZeRO param gather is weight-sized, not a stray control gather
    assert rec["collectives"]["all-gather"]["bytes"] > 10_000_000
    assert rec["donation_coverage"] == 1.0


def test_eval_dp_tp_cell_is_collective_lean_and_model_sharded(sharded):
    """Under the composed dp×tp mesh eval stays control-sized on the wire
    (scalar metric reductions only) and GSPMD actually split the fc kernel
    over the model axis while the batch rode the data axis."""
    rec = sharded.records["eval_step@dp2tp2"]
    assert rec["collective_bytes_per_step"] < 16 * 1024
    specs = " | ".join(rec["sharded_leaves"].values())
    assert "'model'" in specs and "'data'" in specs


def test_sharded_records_match_committed_baseline(sharded):
    """The tier-1 fence: the lean cells, recompiled here, must sit within
    the committed baseline's tolerances (subset mode: the full matrix is
    lint.sh's job)."""
    base = baselib.load_baseline()
    diff = baselib.diff_baseline(sharded.records, base, subset=True)
    assert diff == [], [str(f) for f in diff]


def test_zero_detector_fires_on_replicated_buffer(audit):
    """A weight-sized buffer replicated across a >1 data axis must flag —
    and the same buffer sharded over data (or a 1-wide data mesh) must
    not."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    mesh = audit.ctx.composed_mesh("dp2")
    rows = [{"path": ".params.big", "shape": (2048, 2048),
             "dtype": "float32", "bytes": 2048 * 2048 * 4,
             "spec": str(P()), "_sharding": NamedSharding(mesh, P())}]
    findings = audit_sharding_table(rows, mesh, "fixture")
    assert findings and findings[0].check == "sharding"
    assert "replicated" in findings[0].message
    rows[0]["_sharding"] = NamedSharding(mesh, P("data"))
    assert audit_sharding_table(rows, mesh, "fixture") == []
    assert audit_sharding_table(rows, audit.ctx.mesh, "fixture") == []


def test_resharding_detector_fires_on_forced_gather(audit):
    """A data-sharded weight-sized array forced replicated mid-program
    compiles to a big all-gather: the implicit-resharding detector and the
    per-op payload cap must both trip."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    mesh = audit.ctx.composed_mesh("dp2")
    x = jax.ShapeDtypeStruct(
        (1024, 256), jnp.float32,
        sharding=NamedSharding(mesh, P("data")))

    @jax.jit
    def gathered(x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P())) * 2.0

    ev = step_comms_evidence(gathered, (x,), donated_argnums=(), mesh=mesh)
    findings = audit_collectives(ev["collectives"], EVAL_COMMS, "fixture")
    assert any(f.check == "resharding" for f in findings), \
        [str(f) for f in findings]
    assert any(f.check == "comms" for f in findings)


def test_comms_detector_fires_on_policy_violations():
    """Disallowed kind + oversized allowed kind, on a fabricated inventory
    (detector logic is pure — no compile needed)."""
    inv = {"kinds": {"all-reduce": {"count": 1, "bytes": 262144,
                                    "max_op_bytes": 262144,
                                    "axes": {"data": 262144}},
                     "all-to-all": {"count": 1, "bytes": 64,
                                    "max_op_bytes": 64,
                                    "axes": {"data": 64}}},
           "total_bytes": 262208}
    findings = audit_collectives(inv, EVAL_COMMS, "fixture")
    msgs = " | ".join(f.message for f in findings)
    assert "all-to-all" in msgs  # kind outside the policy
    assert "262,144" in msgs     # allowed kind over the per-op cap
    assert all(f.check == "comms" for f in findings)


def test_grad_allreduce_floor_detector():
    """The missing-gradient-set detector: no all-reduce at all fires;
    model-axis-only reduces do NOT satisfy the data-spanning floor;
    full-mesh ('all', XLA's replica_groups={} form) reduces do."""
    empty = {"kinds": {}, "total_bytes": 0}
    findings = audit_collectives(empty, TRAIN_COMMS, "fixture",
                                 min_grad_bytes=1000)
    assert findings and "gradient all-reduce set" in findings[0].message
    inv = {"kinds": {"all-reduce": {"count": 1, "bytes": 2000,
                                    "max_op_bytes": 2000,
                                    "axes": {"model": 2000}}},
           "total_bytes": 2000}
    assert audit_collectives(inv, TRAIN_COMMS, "fixture",
                             min_grad_bytes=1000)
    inv["kinds"]["all-reduce"]["axes"] = {"all": 2000}
    assert audit_collectives(inv, TRAIN_COMMS, "fixture",
                             min_grad_bytes=1000) == []


def test_parse_replica_groups_forms():
    assert parse_replica_groups("replica_groups={{0,2},{1,3}}") == frozenset(
        {frozenset({0, 2}), frozenset({1, 3})})
    assert parse_replica_groups("replica_groups=[2,2]<=[4]") == frozenset(
        {frozenset({0, 1}), frozenset({2, 3})})
    assert parse_replica_groups(
        "replica_groups=[2,2]<=[2,2]T(1,0)") == frozenset(
        {frozenset({0, 2}), frozenset({1, 3})})
    assert parse_replica_groups("replica_groups={}") == frozenset()
    assert parse_replica_groups("no groups here") is None


def test_empty_replica_groups_attributes_to_full_mesh(audit):
    """HLO `replica_groups={}` = every device, one group — the form XLA
    emits for the dp×tp full-mesh gradient reduces. It must land on 'all'
    (which spans the data axis), never on degenerate 'none' — the exact
    misattribution that would false-fire the gradient floor."""
    mesh = audit.ctx.composed_mesh("dp2tp2")
    hlo = ("  %r = f32[100]{0} all-reduce(f32[100]{0} %x), "
           "replica_groups={}, to_apply=%sum\n")
    inv = collective_inventory(hlo, mesh)
    assert inv["kinds"]["all-reduce"]["axes"] == {"all": 400}
    assert _spans_data("all") and _spans_data("data+model")
    assert not _spans_data("model")


def test_step_comms_evidence_fields(audit):
    """bench.py's e2e evidence rides this helper: donation fields plus the
    comms/memory fields, all from ONE compile."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    mesh = audit.ctx.composed_mesh("dp2")
    s = jnp.zeros((64, 64), jnp.float32)
    x = jax.ShapeDtypeStruct((8, 4), jnp.float32,
                             sharding=NamedSharding(mesh, P("data")))
    fn = jax.jit(lambda s, x: (s + x.sum(), x * 2), donate_argnums=0)
    ev = step_comms_evidence(fn, (s, x), mesh=mesh)
    assert ev["donated_bytes"] == 64 * 64 * 4
    assert ev["donation_coverage"] == 1.0
    assert ev["collective_bytes_per_step"] > 0  # the sharded partial sum
    assert ev["peak_hbm_bytes"] > 0
    assert ev["memory"]["peak_hbm_bytes"] == ev["peak_hbm_bytes"]


# ------------------------------------------------------ program baselines --


def _baseline_rec(**over):
    rec = {"collectives": {"all-reduce": {"count": 2, "bytes": 1000,
                                          "max_op_bytes": 800,
                                          "axes": {"data": 1000}}},
           "collective_bytes_per_step": 1000,
           "peak_hbm_bytes": 10_000,
           "sharded_leaves": {
               ".params.fc.kernel": "PartitionSpec(None, 'model')"},
           "donation_coverage": 1.0}
    rec.update(over)
    return rec


def test_baseline_diff_flags_each_drift_class():
    base = {"tolerances": dict(baselib.DEFAULT_TOLERANCES),
            "programs": {"p@dp2": _baseline_rec()}}
    # within tolerance (and shrinkage) is NOT drift
    ok = {"p@dp2": _baseline_rec(collective_bytes_per_step=1050,
                                 peak_hbm_bytes=9_000)}
    assert baselib.diff_baseline(ok, base) == []
    drifted = {"p@dp2": _baseline_rec(
        collectives={"all-reduce": {"count": 2, "bytes": 1000,
                                    "max_op_bytes": 800,
                                    "axes": {"data": 1000}},
                     "all-gather": {"count": 1, "bytes": 200,
                                    "max_op_bytes": 200,
                                    "axes": {"model": 200}}},
        collective_bytes_per_step=1200,             # +20% payload
        peak_hbm_bytes=12_000,                      # +20% peak
        sharded_leaves={},                          # fc now replicated
        donation_coverage=0.9)}                     # regression
    findings = baselib.diff_baseline(drifted, base)
    joined = " | ".join(f.message for f in findings)
    assert "new collective kind" in joined
    assert "payload grew" in joined
    assert "peak HBM grew" in joined
    assert "downgrade" in joined
    assert "coverage regressed" in joined
    assert len(findings) == 5
    assert all(f.check == "baseline" for f in findings)


def test_baseline_diff_flags_missing_and_new_programs():
    base = {"programs": {"gone@dp2": _baseline_rec()}}
    findings = baselib.diff_baseline({"new@dp2": _baseline_rec()}, base)
    joined = " | ".join(f.message for f in findings)
    assert "not in the committed baseline" in joined
    assert "missing from the fresh audit" in joined
    # subset mode (the tier-1 lean cells): absent programs don't flag,
    # an unknown new one still does
    sub = baselib.diff_baseline({"new@dp2": _baseline_rec()}, base,
                                subset=True)
    assert len(sub) == 1 and "not in the committed baseline" in sub[0].message


def test_baseline_roundtrip_and_provenance(tmp_path):
    path = str(tmp_path / "b.json")
    records = {"p@dp2": _baseline_rec()}
    baselib.write_baseline(records, path, context={"arch": "resnet18"})
    base = baselib.load_baseline(path)
    assert base["programs"] == records
    assert base["_provenance"]["config"]["arch"] == "resnet18"
    # the tolerances block is the sharding defaults plus the dtype pass's
    # cast-churn band (one file fences both passes)
    from ddp_classification_pytorch_tpu.analysis.dtype_audit import (
        DTYPE_TOLERANCES,
    )

    assert base["tolerances"] == {**baselib.DEFAULT_TOLERANCES,
                                  **DTYPE_TOLERANCES}
    assert baselib.diff_baseline(records, base) == []
    with pytest.raises(FileNotFoundError, match="--update-baseline"):
        baselib.load_baseline(str(tmp_path / "absent.json"))


def test_analyze_parser_accepts_baseline_flags():
    from ddp_classification_pytorch_tpu.cli.analyze import build_parser

    ns = build_parser().parse_args(["--diff-baseline"])
    assert ns.diff_baseline and not ns.update_baseline
    ns = build_parser().parse_args(["--diff_baseline",
                                    "--baseline", "x.json"])
    assert ns.diff_baseline and ns.baseline == "x.json"
    assert build_parser().parse_args(["--update-baseline"]).update_baseline


@pytest.mark.slow
def test_analyze_cli_diff_baseline_clean(capsys):
    """The acceptance run: the FULL sharded matrix recompiled and diffed
    against the committed baseline exits 0 on a clean tree."""
    from ddp_classification_pytorch_tpu.cli.analyze import main

    main(["--passes", "sharding", "--diff-baseline"])
    assert "clean" in capsys.readouterr().out
