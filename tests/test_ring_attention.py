"""Ring attention vs dense attention — exactness on a real multi-device mesh.

Runs on the 8-device CPU mesh (conftest.py): the same shard_map + ppermute
code path a TPU pod executes over ICI. The reference has no attention at all
(SURVEY §2.2); these tests pin down the long-context mechanism we add on top.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_classification_pytorch_tpu.ops.attention import attention, ring_attention
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib


def _qkv(b=8, t=32, h=4, d=16, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)), dtype)
    return mk(), mk(), mk()


def test_dense_attention_matches_numpy_oracle():
    q, k, v = _qkv(b=1, t=8, h=2, d=4)
    out = attention(q, k, v)
    qn, kn, vn = np.asarray(q), np.asarray(k), np.asarray(v)
    s = np.einsum("bqhd,bkhd->bhqk", qn, kn) / np.sqrt(4)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", p, vn)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)


@pytest.mark.parametrize("ring_size", [2, 4, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(ring_size, causal):
    mesh = meshlib.make_mesh(
        meshlib.MeshSpec(len(jax.devices()) // ring_size, ring_size))
    q, k, v = _qkv(t=32)
    dense = attention(q, k, v, causal=causal)
    ring = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, axis_name=meshlib.MODEL_AXIS, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), atol=1e-5)


def test_ring_falls_back_to_dense_on_size1_axis():
    mesh = meshlib.make_mesh(meshlib.MeshSpec(len(jax.devices()), 1))
    q, k, v = _qkv(t=16)
    out = ring_attention(q, k, v, mesh=mesh, axis_name=meshlib.MODEL_AXIS)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(attention(q, k, v)), atol=1e-6)


def test_ring_rejects_indivisible_sequence():
    mesh = meshlib.make_mesh(meshlib.MeshSpec(len(jax.devices()) // 4, 4))
    q, k, v = _qkv(t=30)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh=mesh, axis_name=meshlib.MODEL_AXIS)


def test_ring_bf16_inputs_close_to_f32_dense():
    """bf16 Q/K/V with f32 accumulators — the TPU production dtype path."""
    mesh = meshlib.make_mesh(meshlib.MeshSpec(2, 4))
    q, k, v = _qkv(t=32, dtype=jnp.bfloat16)
    dense = attention(q.astype(jnp.float32), k.astype(jnp.float32),
                      v.astype(jnp.float32))
    ring = ring_attention(q, k, v, mesh=mesh, axis_name=meshlib.MODEL_AXIS)
    assert ring.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(ring, np.float32), np.asarray(dense), atol=3e-2)


@pytest.mark.parametrize("ring_size", [2, 4])
@pytest.mark.parametrize("causal", [False, True])
def test_flash_ring_matches_dense(ring_size, causal):
    """use_flash=True: each visiting KV shard goes through the Pallas
    streaming kernel and visits merge via (out, lse) — values must equal
    the dense op for both causal and bidirectional attention."""
    mesh = meshlib.make_mesh(
        meshlib.MeshSpec(len(jax.devices()) // ring_size, ring_size))
    q, k, v = _qkv(t=32)
    dense = attention(q, k, v, causal=causal)
    ring = jax.jit(
        lambda q, k, v: ring_attention(
            q, k, v, mesh=mesh, axis_name=meshlib.MODEL_AXIS, causal=causal,
            use_flash=True)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_ring_gradients_match_dense(causal):
    """Backprop crosses the ppermute ring, the lax.cond visit branches, and
    the flash kernels' lse-cotangent path — must equal dense gradients."""
    mesh = meshlib.make_mesh(meshlib.MeshSpec(2, 4))
    q, k, v = _qkv(t=32)

    def loss_ring(q, k, v):
        out = ring_attention(q, k, v, mesh=mesh,
                             axis_name=meshlib.MODEL_AXIS, causal=causal,
                             use_flash=True)
        return (out ** 2).mean()

    def loss_dense(q, k, v):
        return (attention(q, k, v, causal=causal) ** 2).mean()

    gf = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_flash_with_lse_matches_dense_stats():
    """The (out, lse) building block: lse equals logsumexp of scaled scores
    and BOTH outputs carry exact gradients (lse cotangent folds into Δ)."""
    from ddp_classification_pytorch_tpu.ops.flash_attention import (
        flash_attention_with_lse,
    )

    q, k, v = _qkv(b=2, t=64, h=2, d=16)
    sc = 16 ** -0.5

    def dense_pair(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sc
        return (jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, -1), v),
                jax.scipy.special.logsumexp(s, axis=-1))

    o, lse = flash_attention_with_lse(q, k, v)
    od, lsed = dense_pair(q, k, v)
    np.testing.assert_allclose(np.asarray(o), np.asarray(od), atol=1e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lsed), atol=1e-5)

    mix = lambda ol: (ol[0] ** 2).mean() + jnp.sin(ol[1]).mean()
    gf = jax.grad(lambda *a: mix(flash_attention_with_lse(*a)),
                  argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: mix(dense_pair(*a)), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
