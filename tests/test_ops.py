"""Algorithm cores vs numpy/torch-free oracles: ArcFace phi math
(ARCFACE/arc_main.py:157-176), GaussianDist + masks (NESTED/train.py:93-97,
247-250,358-362), nested all-K eval (train.py:103-143), CDR selective
gradients (CDR/main.py:179-215)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddp_classification_pytorch_tpu.ops.arcface import (
    arc_margin_logits, arcface_naive_log_logits,
)
from ddp_classification_pytorch_tpu.ops.cdr import (
    cdr_clip_schedule, cdr_gradient_transform,
)
from ddp_classification_pytorch_tpu.ops.nested import (
    best_k, gaussian_dist, nested_all_k_counts, nested_all_k_logits,
    prefix_mask, sample_mask_dims,
)


# ---------------------------------------------------------------- ArcFace ---

def _numpy_arc_margin(f, w, labels, s, m, easy_margin):
    f = f / np.maximum(np.linalg.norm(f, axis=1, keepdims=True), 1e-12)
    wn = w / np.maximum(np.linalg.norm(w, axis=1, keepdims=True), 1e-12)
    cos = f @ wn.T
    sin = np.sqrt(np.clip(1 - cos**2, 0, 1))
    phi = cos * math.cos(m) - sin * math.sin(m)
    if easy_margin:
        phi = np.where(cos > 0, phi, cos)
    else:
        th, mm = math.cos(math.pi - m), math.sin(math.pi - m) * m
        phi = np.where(cos > th, phi, cos - mm)
    one_hot = np.zeros_like(cos)
    one_hot[np.arange(len(labels)), labels] = 1
    return (one_hot * phi + (1 - one_hot) * cos) * s


@pytest.mark.parametrize("easy_margin", [True, False])
def test_arc_margin_vs_oracle(easy_margin):
    rng = np.random.default_rng(0)
    f = rng.normal(size=(8, 16)).astype(np.float32)
    w = rng.normal(size=(12, 16)).astype(np.float32)
    labels = rng.integers(0, 12, size=8)
    got = arc_margin_logits(jnp.asarray(f), jnp.asarray(w), jnp.asarray(labels),
                            s=30.0, m=0.5, easy_margin=easy_margin)
    want = _numpy_arc_margin(f, w, labels, 30.0, 0.5, easy_margin)
    np.testing.assert_allclose(np.asarray(got), want, atol=1e-5)


def test_arc_margin_true_class_gets_margin_penalty():
    """phi < cos for the true class ⇒ margin logits are strictly harder."""
    rng = np.random.default_rng(1)
    f = rng.normal(size=(4, 8)).astype(np.float32)
    w = rng.normal(size=(6, 8)).astype(np.float32)
    labels = np.array([0, 1, 2, 3])
    with_margin = np.asarray(arc_margin_logits(
        jnp.asarray(f), jnp.asarray(w), jnp.asarray(labels), s=1.0, m=0.5))
    no_margin = np.asarray(arc_margin_logits(
        jnp.asarray(f), jnp.asarray(w), jnp.asarray(labels), s=1.0, m=0.0))
    rows = np.arange(4)
    assert (with_margin[rows, labels] <= no_margin[rows, labels] + 1e-6).all()
    off = ~np.eye(6, dtype=bool)[labels].reshape(4, 6).all(axis=1)
    del off  # off-diagonal entries identical:
    mask = np.ones_like(with_margin, bool)
    mask[rows, labels] = False
    np.testing.assert_allclose(with_margin[mask], no_margin[mask], atol=1e-5)


def test_arcface_naive_shapes():
    f = jnp.asarray(np.random.default_rng(2).normal(size=(4, 8)), jnp.float32)
    w = jnp.asarray(np.random.default_rng(3).normal(size=(8, 5)), jnp.float32)
    out = arcface_naive_log_logits(f, w)
    assert out.shape == (4, 5)
    assert bool(jnp.all(out <= 0.0))  # log of a probability-like ratio


# ----------------------------------------------------------------- Nested ---

def test_gaussian_dist_matches_reference_formula():
    mu, std, n = 0.0, 100.0, 512
    i = np.arange(1, n + 1)
    want = np.exp(-(((i - mu) / std) ** 2))
    want = want / want.sum()
    np.testing.assert_allclose(gaussian_dist(mu, std, n), want, rtol=1e-6)
    assert abs(gaussian_dist(0, 100, 2048).sum() - 1.0) < 1e-6


def test_prefix_mask():
    m = prefix_mask(jnp.asarray(2), 6)
    np.testing.assert_array_equal(np.asarray(m), [1, 1, 1, 0, 0, 0])
    batch = prefix_mask(jnp.asarray([0, 5]), 6)
    assert batch.shape == (2, 6)
    assert batch[0].sum() == 1 and batch[1].sum() == 6


def test_sample_mask_dims_follows_dist():
    dist = jnp.asarray(gaussian_dist(0, 10, 64))
    ks = sample_mask_dims(jax.random.key(0), dist, (2000,))
    # with std=10 over 64 dims, nearly all mass is below k=40
    assert float(jnp.mean(ks < 40)) > 0.99


def test_nested_all_k_logits_oracle():
    rng = np.random.default_rng(4)
    f = rng.normal(size=(3, 8)).astype(np.float32)
    w = rng.normal(size=(5, 8)).astype(np.float32)
    got = np.asarray(nested_all_k_logits(jnp.asarray(f), jnp.asarray(w)))
    for k in range(8):
        mask = np.zeros(8, np.float32)
        mask[: k + 1] = 1
        want = (f * mask) @ w.T
        np.testing.assert_allclose(got[k], want, atol=1e-5)


def test_nested_all_k_counts_matches_dense_path():
    rng = np.random.default_rng(5)
    b, d, c = 16, 32, 7
    f = rng.normal(size=(b, d)).astype(np.float32)
    w = rng.normal(size=(c, d)).astype(np.float32)
    labels = rng.integers(0, c, size=b)
    t1, t3 = nested_all_k_counts(jnp.asarray(f), jnp.asarray(w),
                                 jnp.asarray(labels), block=8)
    dense = np.asarray(nested_all_k_logits(jnp.asarray(f), jnp.asarray(w)))
    for k in range(d):
        order = np.argsort(-dense[k], axis=1, kind="stable")
        want1 = sum(labels[i] == order[i, 0] for i in range(b))
        want3 = sum(labels[i] in order[i, :3] for i in range(b))
        assert int(t1[k]) == want1, k
        assert int(t3[k]) == want3, k


def test_nested_all_k_counts_ties_count_against():
    # Dead units zero every logit at small K (all classes tie); tie-in-favor
    # ranking scored the whole batch as top-1 hits there (observed live:
    # val_top1 0.994 from a 0.21-train-top1 model), corrupting best-K
    # selection. Ties must rank the true class below its peers.
    import jax.numpy as jnp
    rng = np.random.default_rng(6)
    b, d, c = 8, 16, 5
    f = np.zeros((b, d), np.float32)
    f[:, 8:] = rng.normal(size=(b, 8))  # first 8 dims dead, rest alive
    w = rng.normal(size=(c, d)).astype(np.float32)
    labels = rng.integers(0, c, size=b)
    t1, t3 = nested_all_k_counts(jnp.asarray(f), jnp.asarray(w),
                                 jnp.asarray(labels), block=8)
    # all-zero logits for K<=8: no hits at any k there
    assert int(t1[:8].sum()) == 0 and int(t3[:8].sum()) == 0
    # live dims beyond: still matches the dense argsort oracle
    dense = np.asarray(nested_all_k_logits(jnp.asarray(f), jnp.asarray(w)))
    for k in range(8, d):
        order = np.argsort(-dense[k], axis=1, kind="stable")
        assert int(t1[k]) == sum(labels[i] == order[i, 0] for i in range(b))


def test_best_k_tiebreak_prefers_small_k():
    counts = jnp.asarray([5.0, 5.0, 5.0, 4.0])
    acc, k = best_k(counts, jnp.asarray(10.0))
    assert int(k) == 0 and abs(float(acc) - 0.5) < 1e-6


# -------------------------------------------------------------------- CDR ---

def test_cdr_clip_schedule():
    dead = cdr_clip_schedule(0.2, 10, 5, dead_schedule=True)
    np.testing.assert_allclose(dead, 0.8)
    live = cdr_clip_schedule(0.2, 4, 6, dead_schedule=False)
    np.testing.assert_allclose(live[:4], np.linspace(0.8, 1.0, 4)[::-1])
    np.testing.assert_allclose(live[4:], 0.8)


def test_cdr_transform_masks_bottom_gradients():
    params = {
        "w": jnp.asarray(np.arange(1, 11, dtype=np.float32).reshape(2, 5)),
        "b": jnp.ones((5,), jnp.float32),  # 1-D: must pass through untouched
    }
    grads = {
        "w": jnp.ones((2, 5), jnp.float32),
        "b": jnp.full((5,), 7.0, jnp.float32),
    }
    tx = cdr_gradient_transform(nonzero_ratio=0.5)
    state = tx.init(params)
    new, _ = tx.update(grads, state, params)
    # metric |g·v| = v itself here; top-5 of 10 elements ⇒ values ≥ 6 survive,
    # scaled by clip=0.5
    want = (np.arange(1, 11).reshape(2, 5) >= 6) * 0.5
    np.testing.assert_allclose(np.asarray(new["w"]), want, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new["b"]), 7.0)


def test_cdr_live_clip_schedule_ramps_in_jit():
    # noise_rate 0.2, ramp over 4 epochs, 2 optimizer steps per epoch:
    # survivors must be scaled ~1.0 at epoch 0 and ~0.8 from epoch 3 on
    sched = cdr_clip_schedule(0.2, 4, 4, dead_schedule=False)
    params = {"w": jnp.asarray(np.arange(1, 11, dtype=np.float32).reshape(2, 5))}
    grads = {"w": jnp.ones((2, 5), jnp.float32)}
    tx = cdr_gradient_transform(0.5, clip_schedule=sched, steps_per_epoch=2)
    state = tx.init(params)

    update = jax.jit(lambda g, s, p: tx.update(g, s, p))
    seen = []
    for _ in range(10):
        new, state = update(grads, state, params)
        seen.append(float(np.asarray(new["w"]).max()))  # survivor scale
    np.testing.assert_allclose(seen[0:2], 1.0, atol=1e-6)      # epoch 0
    np.testing.assert_allclose(seen[6:], 0.8, atol=1e-6)       # epochs ≥ 3
    assert seen[2] > seen[4] > seen[6]                         # ramp descends
    assert int(state.step) == 10


def test_cdr_live_flag_changes_training_output():
    # build_optimizer wiring: cdr_dead_schedule=False must produce different
    # epoch-0 updates than the dead-schedule constant (the round-1 defect was
    # a silent no-op flag)
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.train.schedule import build_optimizer

    params = {"w": jnp.asarray(np.random.default_rng(3).normal(size=(8, 8)),
                               jnp.float32)}
    grads = {"w": jnp.ones((8, 8), jnp.float32)}
    outs = {}
    for dead in (True, False):
        cfg = get_preset("cdr").optim
        cfg.cdr_dead_schedule = dead
        tx = build_optimizer(cfg, steps_per_epoch=5)
        upd, _ = tx.update(grads, tx.init(params), params)
        outs[dead] = np.asarray(upd["w"])
    survivors = outs[True] != 0
    assert survivors.any()
    # same mask, different scale (1.0 vs 0.8 at epoch 0 ⇒ sgd lr·clip differs)
    np.testing.assert_allclose(outs[False] != 0, survivors)
    assert not np.allclose(outs[True][survivors], outs[False][survivors])


def test_cdr_transform_in_chain_and_jit():
    params = {"w": jnp.asarray(np.random.default_rng(6).normal(size=(4, 4)),
                               jnp.float32)}
    tx = optax.chain(cdr_gradient_transform(0.75), optax.sgd(0.1))
    state = tx.init(params)

    @jax.jit
    def step(g, s, p):
        return tx.update(g, s, p)

    updates, _ = step({"w": jnp.ones((4, 4))}, state, params)
    # 25% of gradient entries zeroed
    assert int(np.sum(np.asarray(updates["w"]) == 0.0)) == 4
