"""CheckpointManager unit tests: async writes, pruning, best policy, resume."""

import numpy as np

import jax.numpy as jnp

from ddp_classification_pytorch_tpu.train.checkpoint import CheckpointManager
from ddp_classification_pytorch_tpu.train.state import TrainState


def _state(v: float) -> TrainState:
    return TrainState(
        step=jnp.asarray(int(v)),
        params={"w": jnp.full((4,), v)},
        batch_stats={"m": jnp.zeros((2,))},
        opt_state=(),
    )


def test_async_save_and_restore(tmp_path):
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    for e in range(3):
        mgr.save(_state(float(e)), e, metric=float(e))
    mgr.wait()
    assert sorted(mgr._epoch_checkpoints()) == [0, 1, 2]

    restored, next_epoch = mgr.restore_latest(_state(-1.0))
    assert next_epoch == 3
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), np.full((4,), 2.0))
    # best tracks the max metric
    meta = mgr.read_meta()
    assert meta["best_epoch"] == 2 and meta["best_metric"] == 2.0


def test_keep_prunes_old_epochs(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=2)
    for e in range(5):
        mgr.save(_state(float(e)), e)
    mgr.wait()
    assert sorted(mgr._epoch_checkpoints()) == [3, 4]


def test_keep_prunes_under_async(tmp_path):
    # pruning must run AFTER the in-flight write lands, or retention is keep+1
    mgr = CheckpointManager(str(tmp_path), keep=2, async_save=True)
    for e in range(5):
        mgr.save(_state(float(e)), e)
    mgr.wait()
    assert sorted(mgr._epoch_checkpoints()) == [3, 4]


def test_best_epoch_writes_identical_bytes_once(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=0)
    mgr.save(_state(3.0), 0, metric=1.0)  # epoch file AND best in one save
    mgr.wait()
    a = (tmp_path / "ckpt_e0.msgpack").read_bytes()
    b = (tmp_path / "ckpt_best.msgpack").read_bytes()
    assert a == b and len(a) > 0


def test_async_write_failure_surfaces(tmp_path):
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    mgr.save(_state(0.0), 0)
    mgr.wait()
    import os
    import shutil

    shutil.rmtree(tmp_path)  # make the next write fail
    mgr.save(_state(1.0), 1)
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="async checkpoint write failed"):
        mgr.wait()
    os.makedirs(tmp_path, exist_ok=True)


def test_async_failure_surfaces_on_next_save_and_then_clears(tmp_path):
    """wait() re-raises an async write failure exactly once — including the
    implicit wait() at the head of the NEXT save — and a later wait() must
    not re-raise a failure that was already surfaced."""
    import shutil

    import pytest as _pytest

    mgr = CheckpointManager(str(tmp_path), async_save=True)
    mgr.save(_state(0.0), 0)
    mgr.wait()
    shutil.rmtree(tmp_path)  # make the next write fail
    mgr.save(_state(1.0), 1)
    mgr._pending.join()  # let the failure land without consuming it
    import os

    os.makedirs(tmp_path, exist_ok=True)
    with _pytest.raises(RuntimeError, match="async checkpoint write failed"):
        mgr.save(_state(2.0), 2)  # the one-in-flight wait() surfaces it
    # surfaced once: the slot is clear, the next save/wait succeed
    mgr.save(_state(3.0), 3)
    mgr.wait()
    assert 3 in mgr._epoch_checkpoints()


def test_read_meta_at_tolerates_any_torn_content(tmp_path):
    """read_meta_at must absorb every torn-file shape — truncated JSON,
    binary garbage (UnicodeDecodeError, not JSONDecodeError), and an empty
    file — or a single bad meta.json crashes every restart identically."""
    meta = tmp_path / "meta.json"
    for content in (b'{"last_epoch": 3, "best_', b"\x80\x81\xfe\xff\x00",
                    b""):
        meta.write_bytes(content)
        assert CheckpointManager.read_meta_at(str(meta)) == {}, content
    assert CheckpointManager.read_meta_at(str(tmp_path / "absent.json")) == {}


def test_meta_lands_after_bytes(tmp_path):
    # meta.json must not claim an epoch whose checkpoint has not hit disk;
    # easiest observable: after wait(), both exist and agree
    mgr = CheckpointManager(str(tmp_path), async_save=True)
    mgr.save(_state(0.0), 7, metric=0.5)
    mgr.wait()
    assert (tmp_path / "ckpt_e7.msgpack").exists()
    assert mgr.read_meta()["last_epoch"] == 7
    assert mgr.read_meta()["best_epoch"] == 7


def test_resume_restores_best_tracking(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(0.0), 0, metric=0.8)
    mgr.save(_state(1.0), 1, metric=0.6)
    mgr.wait()

    mgr2 = CheckpointManager(str(tmp_path))
    _, next_epoch = mgr2.restore_latest(_state(-1.0))
    assert next_epoch == 2
    assert mgr2.best_metric == 0.8
    # a worse metric after resume must NOT become the new best
    assert mgr2.save(_state(2.0), 2, metric=0.55) is False


def test_nan_logits_are_not_hits():
    import jax.numpy as jnp

    from ddp_classification_pytorch_tpu.utils.metrics import topk_hits

    logits = jnp.array([[jnp.nan, jnp.nan, jnp.nan], [3.0, 1.0, 0.0]])
    labels = jnp.array([0, 0])
    hits = topk_hits(logits, labels, 1)
    assert not bool(hits[0])  # diverged row is a miss, not a perfect score
    assert bool(hits[1])


def test_best_only_policy(tmp_path):
    mgr = CheckpointManager(str(tmp_path), save_every_epoch=True, best_only=True)
    assert mgr.save(_state(0.0), 0, metric=0.5) is True
    assert mgr.save(_state(1.0), 1, metric=0.4) is False  # not a new best
    mgr.wait()
    assert mgr._epoch_checkpoints() == []  # best_only: no per-epoch files
    restored, _ = mgr.restore_latest(_state(-1.0))
    np.testing.assert_array_equal(np.asarray(restored.params["w"]), np.zeros((4,)))


def test_auto_resume_trainer_e2e(tmp_path):
    """Preemption recovery: a second Trainer with auto_resume picks up the
    latest checkpoint in out_dir and continues from the next epoch — the
    restart command is identical to the start command (scripts/supervise.sh)."""
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.train.loop import Trainer

    cfg = get_preset("baseline")
    cfg.data.dataset = "synthetic"
    cfg.data.image_size = 32
    cfg.data.num_classes = 4
    cfg.data.synthetic_size = 64
    cfg.data.batch_size = 32
    cfg.data.num_workers = 1
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.run.epochs = 2
    cfg.run.out_dir = str(tmp_path)
    cfg.run.write_records = False
    cfg.run.auto_resume = True

    tr = Trainer(cfg)
    assert tr.start_epoch == 0  # fresh dir: auto_resume is a no-op
    tr.train_epoch(0)
    tr.ckpt.save(tr.state, 0, metric=0.5)
    tr.ckpt.wait()
    step_before = int(tr.state.step)

    tr2 = Trainer(cfg)  # "restarted" process, same command
    assert tr2.start_epoch == 1
    assert int(tr2.state.step) == step_before
    assert tr2.ckpt.best_metric == 0.5  # best tracking survives restart
    # the restored state must actually TRAIN: catches sharding mismatches
    # between restored leaves and the jitted step (opt-state momentum must
    # carry mesh-wide NamedShardings, not jit(tx.init)'s single-device ones)
    m = tr2.train_epoch(tr2.start_epoch)
    assert np.isfinite(m["loss"])
    assert int(tr2.state.step) > step_before


def test_torn_meta_json_does_not_brick_resume(tmp_path):
    """meta.json writes are atomic (tmp+replace), and the reader tolerates a
    legacy torn file: a preemption landing mid-meta-write must not crash
    every subsequent --auto_resume attempt identically (the recovery chain
    would be bricked with MAX_RESTARTS exhausted)."""
    from ddp_classification_pytorch_tpu.train.checkpoint import CheckpointManager

    out = tmp_path / "run"
    out.mkdir()
    (out / "meta.json").write_text('{"last_epoch": 3, "best_')  # torn
    assert CheckpointManager.read_meta_at(str(out / "meta.json")) == {}

    mgr = CheckpointManager(str(out), save_every_epoch=False, best_only=False,
                            keep=0, async_save=False)
    mgr._write_meta(last_epoch=7)  # must replace the torn file atomically
    assert CheckpointManager.read_meta_at(str(out / "meta.json")) == {
        "last_epoch": 7}
    assert not (out / "meta.json.tmp").exists()
