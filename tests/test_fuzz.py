"""Scenario fuzzer: sampler determinism, spec round-trip, coverage
steering, the delta-minimizing shrinker, and the committed regression
corpus (tests/data/scenarios/ replayed through `cli.scenario
--check_only`).

Everything except the `slow` smoke at the bottom is tier-1-lean: pure
in-process stdlib (the sampler, simulator, shrinker and checkers spawn
no subprocesses and never sleep)."""

import json
import os

import pytest

from ddp_classification_pytorch_tpu.scenario import fuzz as fuzzlib
from ddp_classification_pytorch_tpu.scenario.fuzz import (
    CoverageLedger, DrillRunner, Fuzzer, SpecSampler, coverage_keys,
    pair_universe, shrink_spec, sim_runner, simulate_events)
from ddp_classification_pytorch_tpu.scenario.invariants import (
    Violation, check_invariants)
from ddp_classification_pytorch_tpu.scenario.spec import (
    ScenarioSpec, parse_spec, spec_to_raw)

DATA = os.path.join(os.path.dirname(__file__), "data", "scenarios")


# ------------------------------------------------------------- round-trip --

def test_to_json_round_trip_handcrafted():
    """parse → dump → parse identity, including the action-aware timeline
    asymmetry: spike_load carries rps and no replica, wave-kill carries
    neither — a naive field dump would re-parse to rc 2."""
    raw = {
        "trainer": {"hosts": 2,
                    "fault_specs": {"0": "ckpt_io@epoch=0",
                                    "1": "nan_loss@step=2..3"}},
        "serve": {"replicas": 2, "max_replicas": 3,
                  "fault_specs": {"1": "watcher_io@poll=4"}},
        "timeline": [
            {"at": "publish:1", "action": "drain_replica", "replica": 1},
            {"at": "t:30", "action": "spike_load", "rps": 12.0},
            {"at": "t:40", "action": "kill_replica_during_wave"},
        ],
    }
    spec = parse_spec(raw)
    dumped = spec.to_json()
    again = parse_spec(json.loads(dumped))
    assert again == spec
    assert again.to_json() == dumped  # dump is a fixpoint


def test_to_json_round_trip_property_over_generated_specs():
    """The satellite contract, property-tested over the sampler: every
    generated spec survives parse → dump → parse byte-identically."""
    sampler = SpecSampler(seed=11, candidates=1)
    for _ in range(25):
        spec = sampler.sample()
        dumped = spec.to_json()
        again = parse_spec(json.loads(dumped))
        assert again == spec
        assert again.to_json() == dumped


def test_sampler_same_seed_byte_identical_sequence():
    a = SpecSampler(seed=7, candidates=3)
    b = SpecSampler(seed=7, candidates=3)
    la, lb = CoverageLedger(), CoverageLedger()
    seq_a = [a.sample(la).to_json() for _ in range(6)]
    seq_b = [b.sample(lb).to_json() for _ in range(6)]
    assert seq_a == seq_b
    assert SpecSampler(seed=8).sample().to_json() != seq_a[0]


def test_sampler_only_emits_valid_specs():
    sampler = SpecSampler(seed=23, candidates=1)
    for _ in range(40):
        spec = sampler.sample()  # _draw() parses: SpecError would raise
        assert isinstance(spec, ScenarioSpec)
        assert spec.trainer.hosts >= 1 and spec.serve.replicas >= 1


# --------------------------------------------------------------- coverage --

def test_coverage_keys_cross_subsystem_overlap():
    """A watcher_io poll fault overlapping a torn publish covers BOTH
    cross pairs — the watcher-vs-quarantine race the ledger steers at."""
    spec = parse_spec({
        "trainer": {"hosts": 1, "fault_specs": {"0": "publish_corrupt@epoch=0"}},
        "serve": {"replicas": 1, "fault_specs": {"0": "watcher_io@poll=2..8"}},
    })
    keys = coverage_keys(spec)
    assert "publish_corruptxpublish" in keys  # own pair
    assert "watcher_ioxwatcher" in keys
    assert "watcher_ioxpublish" in keys      # cross pair (the race)
    assert "publish_corruptxwatcher" in keys


def test_ledger_persistence_and_uncovered(tmp_path):
    path = str(tmp_path / "ledger.json")
    led = CoverageLedger(path)
    led.record({"nan_lossxsentinel", "watcher_ioxwatcher"})
    led.record({"nan_lossxsentinel"})
    led.save()
    again = CoverageLedger.load(path)
    assert again.pairs == {"nan_lossxsentinel": 2, "watcher_ioxwatcher": 1}
    assert again.specs_run == 2
    assert "nan_lossxsentinel" not in again.uncovered()
    assert "host_lostxelastic" in again.uncovered()
    assert len(pair_universe()) >= 100  # 13 elements x subsystems


def test_steering_prefers_uncovered_pairs():
    """The sampler must pick the candidate touching the most uncovered
    pairs — uncovered pairs visibly steer the next batch."""
    sampler = SpecSampler(seed=5, candidates=6)
    ledger = CoverageLedger()
    chosen = sampler.sample(ledger)
    scored = sampler.last_candidates
    assert len(scored) == 6
    best = max(s for _, s in scored)
    assert len(coverage_keys(chosen) - set(ledger.pairs)) == best
    # saturate the ledger with the chosen spec's pairs: a re-draw of the
    # SAME candidates must now score them lower than before
    ledger.record(coverage_keys(chosen))
    resampler = SpecSampler(seed=5, candidates=6)
    rechosen = resampler.sample(ledger)
    rescored = dict()
    assert max(s for _, s in resampler.last_candidates) <= best
    assert len(coverage_keys(rechosen) - set(ledger.pairs)) \
        == max(s for _, s in resampler.last_candidates)


def test_bounded_budget_covers_twenty_plus_pairs():
    """Acceptance: a bounded fuzz budget demonstrates >= 20 distinct
    (fault kind x subsystem) pairs, with the sim runner green on every
    sampled scenario (a red here = checker/model disagreement)."""
    ledger = CoverageLedger()
    fuzzer = Fuzzer(sim_runner, seed=1, candidates=4, ledger=ledger)
    result = fuzzer.run(budget=12)
    assert not result.found, \
        f"sim runner disagreed with checkers: {result.violations}"
    assert ledger.specs_run == 12
    assert ledger.distinct() >= 20


# -------------------------------------------------------------- simulator --

def _sim_spec(extra=None):
    raw = {
        "trainer": {"hosts": 2, "epochs": 3,
                    "fault_specs": {"0": "ckpt_io@epoch=0"}},
        "serve": {"replicas": 2, "fault_specs": {"0": "watcher_io@poll=2"}},
        "timeline": [{"at": "t:10", "action": "kill_replica", "replica": 1}],
    }
    if extra:
        raw.update(extra)
    return parse_spec(raw)


def test_simulator_deterministic_and_green():
    spec = _sim_spec()
    ev1 = simulate_events(spec)
    ev2 = simulate_events(spec)
    assert ev1 == ev2
    assert check_invariants(ev1, spec, require_lint=True) == []
    kinds = {e["kind"] for e in ev1}
    for want in ("publish", "publish_torn", "quarantine", "verify_ok",
                 "swap", "request", "lint", "watcher_error",
                 "drain_token_acquire", "drain_token_release"):
        assert want in kinds, f"sim never emitted {want}"


def test_simulator_events_pass_schema():
    from ddp_classification_pytorch_tpu.obs.events import validate_events

    assert validate_events(simulate_events(_sim_spec())) == []


def test_simulator_bug_model_adopt_unverified_is_s1_red():
    spec = _sim_spec()
    viols = check_invariants(simulate_events(spec, bugs=("adopt_unverified",)),
                             spec, require_lint=True)
    assert any(v.invariant == "S1" for v in viols)


def test_simulator_bug_model_spike_unanswered_is_s5_red():
    spec = parse_spec({
        "serve": {"replicas": 1, "max_replicas": 2},
        "timeline": [{"at": "t:10", "action": "spike_load", "rps": 12.0}],
    })
    viols = check_invariants(simulate_events(spec, bugs=("spike_unanswered",)),
                             spec, require_lint=True)
    assert any(v.invariant == "S5" for v in viols)
    assert sim_runner(spec) == []  # the correct model answers the spike


def test_simulator_back_to_back_wave_kills_stay_s5_green():
    """Fuzzer-found sim-model fix: the second wave-kill acquires a
    TTL-stale wedged token, which IS a takeover — without emitting it,
    S5(a) sees two concurrent holders."""
    spec = parse_spec({
        "trainer": {"hosts": 1, "epochs": 1},
        "serve": {"replicas": 2},
        "timeline": [{"at": "t:0", "action": "kill_replica_during_wave"},
                     {"at": "t:0", "action": "kill_replica_during_wave"}],
    })
    assert sim_runner(spec) == []
    kinds = [e["kind"] for e in simulate_events(spec)]
    assert kinds.count("drain_token_takeover") >= 1


# --------------------------------------------------------------- shrinker --

def _planted_runner(spec):
    """The planted-bug fixture: red iff a trainer nan_loss and a serve
    watcher_io co-occur anywhere in the spec."""
    tr = ",".join(spec.trainer.fault_specs.values())
    sv = ",".join(spec.serve.fault_specs.values())
    if "nan_loss" in tr and "watcher_io" in sv:
        return [Violation("PLANTED", "nan_loss x watcher_io co-occur")]
    return []


def test_fuzzer_finds_and_minimizes_planted_pair():
    """Acceptance: under a fixed seed the fuzzer finds the planted-bug
    fixture and delta-minimizes it to exactly the 2-element spec —
    one nan_loss atom, one watcher_io atom, everything else floored."""
    fuzzer = Fuzzer(_planted_runner, seed=0, candidates=2)
    result = fuzzer.run(budget=30)
    assert result.found
    m = result.minimized
    assert m.trainer.hosts == 1 and m.serve.replicas == 1
    assert m.trainer.epochs == 1 and m.timeline == []
    tr_atoms = ",".join(m.trainer.fault_specs.values()).split(",")
    sv_atoms = ",".join(m.serve.fault_specs.values()).split(",")
    assert len(tr_atoms) == 1 and tr_atoms[0].startswith("nan_loss@step=")
    assert len(sv_atoms) == 1 and sv_atoms[0].startswith("watcher_io@poll=")
    assert _planted_runner(m)  # still failing, i.e. 1-minimal cuts only


def test_shrinker_deterministic_same_seed():
    r1 = Fuzzer(_planted_runner, seed=0, candidates=2).run(budget=30)
    r2 = Fuzzer(_planted_runner, seed=0, candidates=2).run(budget=30)
    assert r1.found and r2.found
    assert r1.minimized.to_json() == r2.minimized.to_json()
    assert r1.specs_run == r2.specs_run
    assert r1.shrink_runs == r2.shrink_runs


def test_shrinker_rehomes_faults_when_dropping_topology():
    """Shrinking hosts away must re-home the dropped host's fault onto
    host 0, not silently delete it (the failure would vanish and the
    cut would be rejected forever)."""
    spec = parse_spec({
        "trainer": {"hosts": 3, "fault_specs": {"2": "nan_loss@step=4"}},
        "serve": {"replicas": 2, "fault_specs": {"1": "watcher_io@poll=3"}},
    })
    mini, runs = shrink_spec(spec, lambda s: bool(_planted_runner(s)))
    assert mini.trainer.hosts == 1 and mini.serve.replicas == 1
    assert "nan_loss" in mini.trainer.fault_specs.get(0, "")
    assert "watcher_io" in mini.serve.fault_specs.get(0, "")
    assert runs > 0


def test_shrinker_respects_run_cap():
    calls = []

    def counting(s):
        calls.append(1)
        return _planted_runner(s)

    spec = parse_spec({
        "trainer": {"hosts": 3, "fault_specs": {"2": "nan_loss@step=4"}},
        "serve": {"replicas": 2, "fault_specs": {"1": "watcher_io@poll=3"}},
    })
    _, runs = shrink_spec(spec, lambda s: bool(counting(s)), max_runs=5)
    assert runs == 5 and len(calls) == 5


def test_shrink_preserves_failure_label_not_any_red():
    """A cut that trades the original failure for a DIFFERENT invariant's
    red must be rejected: the minimized spec reproduces the bug it was
    found with, not whichever red shrinks best."""
    def runner(spec):
        out = []
        tr = ",".join(spec.trainer.fault_specs.values())
        if "nan_loss" in tr and "host_lost" in tr:
            out.append(Violation("A", "pair bug"))
        if spec.trainer.hosts == 1:
            out.append(Violation("B", "unrelated small-topology red"))
        return out

    spec = parse_spec({
        "trainer": {"hosts": 2,
                    "fault_specs": {"0": "nan_loss@step=2",
                                    "1": "host_lost@step=4"}},
    })
    fuzzer = Fuzzer(runner, seed=0, candidates=1)
    # drive the shrink directly: labels from the original failure
    labels = {v.invariant for v in runner(spec)}
    assert labels == {"A"}
    mini, _ = shrink_spec(
        spec, lambda s: bool(labels & {v.invariant for v in runner(s)}))
    assert any(v.invariant == "A" for v in runner(mini))


# ----------------------------------------------------------------- corpus --

def _corpus_cases():
    return sorted(os.listdir(DATA)) if os.path.isdir(DATA) else []


def test_corpus_exists_with_green_and_red():
    cases = _corpus_cases()
    assert len(cases) >= 2, "regression corpus went missing"
    expects = set()
    for name in cases:
        with open(os.path.join(DATA, name, "expect")) as f:
            expects.add(f.read().strip())
    assert expects == {"0", "1"}, \
        "corpus must exercise both green and red replay paths"


@pytest.mark.parametrize("name", _corpus_cases())
def test_corpus_replay_check_only(name, capsys):
    """Every committed minimized spec replays through the real
    `cli.scenario --check_only` path with its recorded verdict — the
    cheap regression the fuzzer's tentpole promises."""
    from ddp_classification_pytorch_tpu.cli.scenario import main

    d = os.path.join(DATA, name)
    with open(os.path.join(d, "expect")) as f:
        want = int(f.read().strip())
    argv = ["--scenario_spec", os.path.join(d, "spec.json"),
            "--events", os.path.join(d, "events.jsonl"),
            "--check_only", "--out", d]
    if want == 0:
        main(argv)  # green replay must not raise
    else:
        with pytest.raises(SystemExit) as exc:
            main(argv)
        assert exc.value.code == want


def test_corpus_specs_are_canonical_dumps():
    """Committed specs must be `to_json` fixpoints so a re-minimization
    diff is always byte-meaningful."""
    for name in _corpus_cases():
        with open(os.path.join(DATA, name, "spec.json")) as f:
            text = f.read()
        assert parse_spec(json.loads(text)).to_json() == text, name


def test_corpus_spike_at_max_fleet_guards_s5c():
    """The fuzzer-found S5(c) false red: a spike landing with the fleet
    already at max_replicas. The pre-fix checker (no at-max excusal)
    must flag this timeline; the fixed one must not."""
    d = os.path.join(DATA, "spike-at-max-fleet")
    with open(os.path.join(d, "spec.json")) as f:
        spec = parse_spec(json.load(f))
    from ddp_classification_pytorch_tpu.obs.events import read_events

    events = read_events(os.path.join(d, "events.jsonl"))
    assert check_invariants(events, spec, require_lint=True) == []
    spikes = [e for e in events if e["kind"] == "spike_load"]
    scale = [e["ts"] for e in events if e["kind"] == "scale_out"]
    assert spec.serve.max_replicas > spec.serve.replicas
    # the discriminating shape: at least one spike with NO scale_out in
    # its window (the old checker's false red)
    dl = spec.serve.scale_out_deadline_s
    assert any(not any(s["ts"] <= t <= s["ts"] + dl for t in scale)
               for s in spikes)


# -------------------------------------------------------------- slow smoke --

@pytest.mark.slow
def test_fuzz_smoke_short_budget(tmp_path):
    """End-to-end cli.fuzz: a short seeded sim budget runs green and
    persists the coverage ledger; a planted red (bug-model runner)
    writes minimized artifacts and exits 1."""
    from ddp_classification_pytorch_tpu.cli import fuzz as cli_fuzz

    out = str(tmp_path / "fuzz")
    cli_fuzz.main(["--seed", "0", "--budget", "8", "--out", out])
    ledger = CoverageLedger.load(os.path.join(out, "fuzz_ledger.json"))
    assert ledger.specs_run == 8 and ledger.distinct() >= 20

    # red path: a runner that simulates the adopt-unverified bug model
    def buggy(spec):
        return check_invariants(
            simulate_events(spec, bugs=("adopt_unverified",)), spec,
            require_lint=True)

    fuzzer = Fuzzer(buggy, seed=0, candidates=2)
    result = fuzzer.run(budget=10)
    assert result.found
    assert any(v.invariant == "S1" for v in result.violations)
