"""Per-block rematerialization must be numerically transparent."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ddp_classification_pytorch_tpu.models import resnet as R


def test_remat_gradients_match():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 32, 32, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, 4), jnp.int32)

    def grads_for(remat):
        model = R.resnet18(num_classes=4, variant="cifar",
                           dtype=jnp.float32, remat=remat)
        variables = model.init(jax.random.PRNGKey(0), x, train=False)

        def loss(params):
            logits, _ = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                x, train=True, mutable=["batch_stats"])
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        return jax.grad(loss)(variables["params"])

    g0 = grads_for(False)
    g1 = grads_for(True)
    for a, b in zip(jax.tree_util.tree_leaves(g0), jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)
