"""VGG19-BN end-to-end smoke on the 8-device mesh (the reference's VGG
wrapper is dead code, NESTED/model/vgg.py — here it is a live arch)."""

import numpy as np

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.train.loop import Trainer


def test_vgg_trains_one_epoch(tmp_path):
    cfg = get_preset("baseline")
    cfg.data.dataset = "synthetic"
    cfg.data.image_size = 32
    cfg.data.num_classes = 4
    cfg.data.synthetic_size = 32
    cfg.data.batch_size = 16
    cfg.data.num_workers = 1
    cfg.model.arch = "vgg19_bn"
    cfg.model.dtype = "float32"
    cfg.model.dropout = 0.5
    cfg.run.epochs = 1
    cfg.run.write_records = False
    cfg.run.save_every_epoch = False
    cfg.run.out_dir = str(tmp_path)
    cfg.run.eval_first = True  # exercised via run() below

    tr = Trainer(cfg)
    last = tr.run()  # runs initial eval (eval_first), one epoch, final eval
    assert np.isfinite(last["loss"])
    assert 0.0 <= last["val_top1"] <= 1.0
