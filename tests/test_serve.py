"""Serving subsystem (serve/): micro-batching engine, hot-reload, drain.

Everything runs in-process (no sockets) on a tiny resnet18-cifar model —
one module-scoped state + ONE jitted predict shared by every test, so the
bucket programs compile once for the whole file (tier-1 budget: the suite
already outruns its 870 s window; no sleeps beyond the engine's own
~50 ms deadlines).

The acceptance pins:
- concurrent requests through the engine are BIT-identical to the direct
  jitted predict on the same inputs, with at most len(buckets) compiled
  shapes observed;
- a partial batch flushes at the deadline, padded to a bucket, and pad
  rows cannot perturb real rows;
- intake backpressure (bounded queue) rejects loudly;
- hot-reload swaps a newer verified checkpoint and QUARANTINES a corrupt
  candidate while serving continues on the old params;
- SIGTERM drains gracefully: intake stops, queued work completes.
"""

import glob
import json
import os
import signal
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
from ddp_classification_pytorch_tpu.serve.engine import (
    EngineClosed,
    QueueFull,
    ServingEngine,
)
from ddp_classification_pytorch_tpu.serve.metrics import ServeMetrics
from ddp_classification_pytorch_tpu.serve.reload import CheckpointWatcher
from ddp_classification_pytorch_tpu.train.checkpoint import CheckpointManager
from ddp_classification_pytorch_tpu.train.state import create_train_state
from ddp_classification_pytorch_tpu.train.steps import make_topk_predict_step

BUCKETS = (2, 4)  # every engine in this module: at most 2 compiled shapes


@pytest.fixture(scope="module")
def sv():
    cfg = get_preset("baseline")
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.data.num_classes = 8
    cfg.data.image_size = 32
    mesh = meshlib.make_mesh()
    model, _, state = create_train_state(cfg, mesh, steps_per_epoch=1)
    predict = make_topk_predict_step(cfg, model, 3)
    rng = np.random.default_rng(7)
    imgs = rng.integers(0, 256, (8, 32, 32, 3)).astype(np.uint8)
    return SimpleNamespace(cfg=cfg, mesh=mesh, model=model, state=state,
                           predict=predict, imgs=imgs)


def _engine(sv, **kw):
    kw.setdefault("image_size", 32)
    kw.setdefault("input_dtype", "uint8")
    kw.setdefault("max_batch", 4)
    kw.setdefault("batch_timeout_ms", 40.0)
    kw.setdefault("queue_depth", 16)
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("metrics", ServeMetrics())
    return ServingEngine(sv.state, sv.predict, **kw)


def test_concurrent_requests_bit_identical_to_direct_predict(sv):
    """4 requests submitted concurrently batch into ONE full micro-batch
    (max_batch=4, deadline generous) and each response is bit-identical to
    the direct jitted predict on the same 4 images stacked as one batch —
    the engine adds batching, not numerics. Compile-count bound: only
    bucket shapes ran, and the jit cache holds at most len(buckets)."""
    engine = _engine(sv, batch_timeout_ms=2000.0).start()
    try:
        futures = [None] * 4
        threads = [threading.Thread(target=lambda i=i: futures.__setitem__(
            i, engine.submit(sv.imgs[i]))) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        preds = [f.result(timeout=30) for f in futures]
    finally:
        engine.drain()

    scores, indices = sv.predict(sv.state, np.stack(sv.imgs[:4]))
    scores, indices = np.asarray(scores), np.asarray(indices)
    for i, p in enumerate(preds):
        np.testing.assert_array_equal(p.indices, indices[i])
        np.testing.assert_array_equal(p.scores, scores[i])  # bitwise
        assert p.latency_ms > 0
    assert engine.seen_buckets == {4}
    cache = engine.compiled_programs()
    assert cache is None or cache <= len(BUCKETS)
    assert engine.metrics.snapshot()["fill_ratio"] == 1.0


def test_deadline_flushes_partial_batch(sv):
    """3 requests < max_batch must NOT wait forever: the batcher flushes at
    batch_timeout_ms, padded to the smallest covering bucket (4), and the
    fill accounting records 3 real + 1 pad row."""
    metrics = ServeMetrics()
    engine = _engine(sv, batch_timeout_ms=50.0, metrics=metrics).start()
    try:
        futures = [engine.submit(sv.imgs[i]) for i in range(3)]
        preds = [f.result(timeout=30) for f in futures]
    finally:
        engine.drain()
    assert len(preds) == 3 and all(p.indices.shape == (3,) for p in preds)
    snap = metrics.snapshot()
    assert snap["bucket_hist"] == {4: 1}
    assert snap["fill_ratio"] == 0.75  # 3 real rows of a 4-row bucket
    assert snap["p99_ms"] >= snap["p50_ms"] > 0


def test_bucket_padding_does_not_leak_into_real_rows(sv):
    """Validity of the pad scheme: the same image answered alone (1 real +
    1 pad row in bucket 2) and answered next to OTHER traffic (2 real rows,
    same bucket program) must produce bitwise-identical results — pad rows
    are dead weight, not numerics."""
    alone = _engine(sv)
    f = alone.submit(sv.imgs[0])
    assert alone.process_once() == 1  # in-process drive: no thread needed
    p_alone = f.result(timeout=30)
    assert alone.seen_buckets == {2}

    paired = _engine(sv)
    f0 = paired.submit(sv.imgs[0])
    paired.submit(sv.imgs[1])
    assert paired.process_once() == 2
    p_paired = f0.result(timeout=30)

    np.testing.assert_array_equal(p_alone.indices, p_paired.indices)
    np.testing.assert_array_equal(p_alone.scores, p_paired.scores)


def test_queue_full_backpressure(sv):
    """Intake is bounded: queue_depth submits are accepted, the next raises
    QueueFull immediately (no silent latency growth) and is counted; the
    accepted requests still complete on flush."""
    metrics = ServeMetrics()
    engine = _engine(sv, queue_depth=2, metrics=metrics)
    f1, f2 = engine.submit(sv.imgs[0]), engine.submit(sv.imgs[1])
    with pytest.raises(QueueFull):
        engine.submit(sv.imgs[2])
    assert metrics.snapshot()["rejected"] == 1
    engine.drain()  # no thread: drain flushes inline
    assert f1.result(timeout=30).indices.shape == (3,)
    assert f2.result(timeout=30).indices.shape == (3,)
    with pytest.raises(EngineClosed):
        engine.submit(sv.imgs[0])


def test_submit_validates_wire_contract(sv):
    """A mis-shaped or mis-dtyped request fails AT SUBMIT (per-request),
    never inside a shared padded batch at jit time."""
    engine = _engine(sv)
    with pytest.raises(ValueError):
        engine.submit(sv.imgs[0].astype(np.float32))  # wrong wire dtype
    with pytest.raises(ValueError):
        engine.submit(np.zeros((16, 16, 3), np.uint8))  # wrong shape


def test_hot_reload_swaps_and_quarantines_corrupt(sv, tmp_path):
    """A newer verified checkpoint hot-swaps between batches (responses
    change to the new params' outputs, bitwise); a newer-still CORRUPT
    candidate is quarantined (*.corrupt) and serving continues on the last
    verified params."""
    import jax

    run_dir = str(tmp_path)
    mgr = CheckpointManager(run_dir, async_save=False)
    state2 = sv.state.replace(params=jax.tree_util.tree_map(
        lambda x: x * 1.5, sv.state.params))
    mgr.save(state2, epoch=1)

    metrics = ServeMetrics()
    engine = _engine(sv, metrics=metrics)
    watcher = CheckpointWatcher(run_dir, engine, sv.state, metrics=metrics)

    base_scores = np.asarray(sv.predict(sv.state, np.stack(sv.imgs[:2]))[0])
    assert watcher.check_once() is True
    assert watcher.loaded_epoch == 1
    f = engine.submit(sv.imgs[0])
    engine.submit(sv.imgs[1])
    assert engine.process_once() == 2
    got = f.result(timeout=30)
    # the swap took: responses now match the RELOADED params, not the old
    reload_scores = np.asarray(
        sv.predict(engine._state, np.stack(sv.imgs[:2]))[0])
    np.testing.assert_array_equal(got.scores, reload_scores[0])
    assert not np.array_equal(got.scores, base_scores[0])

    # corrupt newer candidate: epoch-2 bytes torn after the sidecar landed
    mgr.save(state2, epoch=2)
    with open(mgr.epoch_path(2), "r+b") as fh:
        fh.seek(100)
        fh.write(b"\xde\xad\xbe\xef")
    assert watcher.check_once() is False  # nothing newer verified
    assert os.path.exists(mgr.epoch_path(2) + ".corrupt")
    assert not os.path.exists(mgr.epoch_path(2))
    assert watcher.loaded_epoch == 1  # still serving the verified params
    snap = metrics.snapshot()
    assert snap["reloads"] == 1 and snap["reloads_rejected"] == 1
    # and the engine still answers (on the epoch-1 params)
    f = engine.submit(sv.imgs[2])
    assert engine.process_once() == 1
    np.testing.assert_array_equal(
        f.result(timeout=30).scores,
        np.asarray(sv.predict(engine._state, np.stack(sv.imgs[2:4]))[0])[0])


def test_swap_racing_drain_never_mixes_params_in_a_batch(sv):
    """swap_state storms from a reloader thread while requests flow and the
    engine finally drains: every answered Prediction must be INTERNALLY
    consistent — its scores bitwise-equal to the direct predict under the
    params its digest names. A batch that adopted new params mid-flight
    (mixing two checkpoints inside one micro-batch) would answer with one
    digest and the other params' numerics, and fail the bitwise check."""
    import jax

    img = sv.imgs[0]
    state_b = sv.state.replace(params=jax.tree_util.tree_map(
        lambda x: x * 1.5, sv.state.params))
    # expected rows per digest at every bucket shape a batch might run;
    # "A" republishes the init params under a named digest, so A/fresh
    # share numerics while B's differ — only B-vs-(A|fresh) mixing exists
    expected = {}
    for name, st in (("fresh", sv.state), ("A", sv.state), ("B", state_b)):
        rows = set()
        for b in BUCKETS:
            out = np.asarray(sv.predict(st, np.stack([img] * b))[0])
            rows.update(out[i].tobytes() for i in range(b))
        expected[name] = rows

    engine = _engine(sv, batch_timeout_ms=5.0, queue_depth=32).start()
    stop = threading.Event()

    def swapper():
        flip = False
        while not stop.is_set():
            if flip:
                engine.swap_state(state_b, digest="B", generation=2)
            else:
                engine.swap_state(sv.state, digest="A", generation=1)
            flip = not flip
            time.sleep(0.002)

    t = threading.Thread(target=swapper)
    t.start()
    futures = []
    try:
        for _ in range(24):
            try:
                futures.append(engine.submit(img))
            except QueueFull:
                pass
            time.sleep(0.003)
        # drain races the still-running swapper: the inline flush must keep
        # the one-params-version-per-batch contract too
        engine.drain()
    finally:
        stop.set()
        t.join()
    preds = [f.result(timeout=30) for f in futures]
    assert preds, "no request was ever accepted"
    for p in preds:
        assert p.digest in expected
        assert p.scores.tobytes() in expected[p.digest], (
            f"scores answered under digest {p.digest!r} do not match that "
            "checkpoint's params — a micro-batch mixed two param versions")


def test_quarantine_double_rename_yields_exactly_one_corrupt(sv, tmp_path):
    """The shared-run-dir race: the serving watcher AND a trainer-side
    manager both find the same corrupt candidate and quarantine it. In
    either order the loser's rename must be a silent no-op — the pod ends
    with exactly ONE *.corrupt file, no crash, serving state untouched."""

    def corrupt_candidate(run_dir, epoch):
        mgr = CheckpointManager(run_dir, async_save=False)
        mgr.save(sv.state, epoch=epoch)
        with open(mgr.epoch_path(epoch), "r+b") as fh:
            fh.seek(100)
            fh.write(b"\xde\xad\xbe\xef")
        return mgr

    stub = SimpleNamespace(swap_state=lambda *a, **k: None)

    # order 1: the trainer-side manager quarantines first
    d1 = str(tmp_path / "a")
    mgr = corrupt_candidate(d1, 1)
    watcher = CheckpointWatcher(d1, stub, sv.state)
    assert mgr.restore_verified(sv.state, mgr.epoch_path(1)) is None
    assert watcher.check_once() is False  # nothing left to scan; no crash
    assert watcher.loaded_epoch == -1
    assert len(glob.glob(os.path.join(d1, "*.msgpack.corrupt"))) == 1

    # order 2: the watcher quarantines first, the manager loses the race
    d2 = str(tmp_path / "b")
    mgr = corrupt_candidate(d2, 1)
    watcher = CheckpointWatcher(d2, stub, sv.state)
    assert watcher.check_once() is False
    assert mgr.restore_verified(sv.state, mgr.epoch_path(1)) is None
    # and a second rename of the SAME path (both sides committed to
    # quarantine before either rename landed) is a no-op, not a crash
    mgr._quarantine(mgr.epoch_path(1), "sha256 mismatch")
    assert len(glob.glob(os.path.join(d2, "*.msgpack.corrupt"))) == 1
    assert watcher.loaded_epoch == -1


def test_http_healthz_and_retry_after(sv, tmp_path):
    """The wire contract of serve/http.py: /healthz reports params
    provenance + watcher liveness, queue-full answers 503 busy with
    Retry-After 1 (same replica, soon), draining answers 503 draining with
    Retry-After 5 (go elsewhere) — the distinction S2 relies on."""
    import io
    import urllib.request
    from urllib.error import HTTPError

    from PIL import Image

    from ddp_classification_pytorch_tpu.serve.http import make_server

    buf = io.BytesIO()
    Image.fromarray(sv.imgs[0]).save(buf, format="PNG")
    png = buf.getvalue()

    engine = _engine(sv, queue_depth=1,
                     transform=lambda img, rng: sv.imgs[0])
    watcher = CheckpointWatcher(str(tmp_path), engine, sv.state, poll_s=0.2)
    server = make_server(engine, 0, watcher=watcher)  # 0 = ephemeral port
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{port}"

    def get(path):
        with urllib.request.urlopen(base + path, timeout=10) as r:
            return json.loads(r.read())

    def post():
        req = urllib.request.Request(base + "/predict", data=png,
                                     method="POST")
        return urllib.request.urlopen(req, timeout=30)

    try:
        health = get("/healthz")
        assert health["ok"] is True
        assert health["digest"] == "fresh" and health["generation"] == -1
        assert health["watcher_alive"] is False  # built but never started
        watcher.start()
        assert get("/healthz")["watcher_alive"] is True

        # bounded queue full (batcher not running) → 503 busy + hint
        engine.submit(sv.imgs[0])
        with pytest.raises(HTTPError) as exc:
            post()
        assert exc.value.code == 503
        assert exc.value.headers["Retry-After"] == "1"
        assert json.loads(exc.value.read())["state"] == "busy"

        engine.start()
        with post() as r:
            body = json.loads(r.read())
        assert body["digest"] == "fresh" and body["generation"] == -1
        assert len(body["topk"]) == 3

        engine.drain()
        with pytest.raises(HTTPError) as exc:
            post()
        assert exc.value.code == 503
        assert exc.value.headers["Retry-After"] == "5"
        assert json.loads(exc.value.read())["state"] == "draining"
        assert get("/healthz")["ok"] is False
    finally:
        watcher.stop()
        server.shutdown()
        server.server_close()


def test_sigterm_drains_gracefully(sv):
    """The cli.serve signal contract, in-process: SIGTERM sets the drain
    event; drain stops intake (EngineClosed), answers everything already
    queued, and joins the batcher — no request accepted before the signal
    is ever dropped."""
    from ddp_classification_pytorch_tpu.cli.serve import _install_signal_handlers

    stop = threading.Event()
    prev = _install_signal_handlers(stop)
    engine = _engine(sv, batch_timeout_ms=20.0).start()
    try:
        futures = [engine.submit(sv.imgs[i]) for i in range(3)]
        os.kill(os.getpid(), signal.SIGTERM)
        assert stop.wait(timeout=5.0), "SIGTERM handler did not fire"
        engine.drain()
        for f in futures:
            assert f.result(timeout=30).indices.shape == (3,)
        with pytest.raises(EngineClosed):
            engine.submit(sv.imgs[0])
    finally:
        for sig, handler in prev.items():
            signal.signal(sig, handler)


def test_drain_flushes_requests_queued_after_batcher_stopped(sv):
    """Requests still in the queue when drain begins (engine never started
    — the worst case) are all answered before drain returns."""
    engine = _engine(sv)
    futures = [engine.submit(sv.imgs[i]) for i in range(5)]
    t0 = time.monotonic()
    engine.drain()
    assert time.monotonic() - t0 < 30
    assert all(f.done() for f in futures)
    assert all(f.result().indices.shape == (3,) for f in futures)


# ------------------------------------------------------- dp-sharded serve --


def test_resolve_buckets_dp_arithmetic():
    """The dp bucket contract (docs/serving.md): explicit buckets that
    cannot shard evenly over 'data' are a config error (the operator asked
    for shapes that cannot run — rc 2 at the CLI), while auto-buckets
    round UP to the next dp multiple and dedup."""
    from ddp_classification_pytorch_tpu.config import (
        ServeConfig,
        dp_round_up_buckets,
    )

    assert ServeConfig(max_batch=8).resolve_buckets(2) == (2, 4, 8)
    assert ServeConfig(max_batch=8).resolve_buckets(1) == (1, 2, 4, 8)
    assert dp_round_up_buckets((1, 3, 4), 4) == (4,)
    assert dp_round_up_buckets((1, 5), 4) == (4, 8)

    explicit = ServeConfig(buckets=(1, 3), max_batch=3)
    assert explicit.resolve_buckets(1) == (1, 3)
    with pytest.raises(ValueError, match="serve-bucket-dp-indivisible"):
        explicit.resolve_buckets(2)


def test_engine_rejects_dp_indivisible_buckets(sv):
    """The same fence at engine construction: a bucket the mesh cannot
    shard must fail loudly at build time, never at assembly time inside a
    live micro-batch."""
    mesh = meshlib.serve_mesh(2)
    with pytest.raises(ValueError, match="serve-bucket-dp-indivisible"):
        ServingEngine(sv.state, sv.predict, image_size=32,
                      input_dtype="uint8", max_batch=3, buckets=(1, 3),
                      mesh=mesh)


def test_dp_sharded_engine_matches_direct_predict(sv):
    """Numerics fence for the tentpole: a dp2 engine (padded batches
    assembled as data-sharded global arrays, dp-sharded predict) answers
    with the SAME top-k indices as the single-device jitted predict and
    scores equal to float tolerance — sharding adds communication, not
    numerics."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = meshlib.serve_mesh(2)
    # the module state lives on the full 8-device mesh; a serving replica
    # holds its params replicated over ITS OWN mesh
    state = jax.device_put(sv.state, NamedSharding(mesh, PartitionSpec()))
    predict_dp = make_topk_predict_step(sv.cfg, sv.model, 3, mesh=mesh)
    engine = ServingEngine(state, predict_dp, image_size=32,
                           input_dtype="uint8", max_batch=4,
                           batch_timeout_ms=40.0, queue_depth=16,
                           buckets=BUCKETS, metrics=ServeMetrics(),
                           mesh=mesh)
    assert engine.dp == 2 and engine.serve_devices == 2
    futures = [engine.submit(sv.imgs[i]) for i in range(4)]
    assert engine.process_once() == 4
    preds = [f.result(timeout=30) for f in futures]

    scores, indices = sv.predict(sv.state, np.stack(sv.imgs[:4]))
    scores, indices = np.asarray(scores), np.asarray(indices)
    for i, p in enumerate(preds):
        np.testing.assert_array_equal(p.indices, indices[i])
        np.testing.assert_allclose(p.scores, scores[i], rtol=1e-5, atol=1e-6)
    assert engine.seen_buckets == {4}


# ------------------------------------------------------------- cli.serve --


def _serve_main_rc(argv, capsys):
    from ddp_classification_pytorch_tpu.cli.serve import main

    with pytest.raises(SystemExit) as exc:
        main(argv)
    return exc.value.code, capsys.readouterr().err


def test_cli_serve_config_errors_exit_2(capsys):
    """Deterministic knob errors exit rc 2 BEFORE any backend work — the
    same discipline as cli.train, so supervisors never replay them."""
    # max_batch beyond the largest bucket: no shape could run a full batch
    rc, err = _serve_main_rc(
        ["baseline", "--ckpt", "/tmp/x.msgpack", "--max_batch", "16",
         "--buckets", "1,2,4"], capsys)
    assert rc == 2 and "config error" in err
    # no weights source at all
    rc, err = _serve_main_rc(["baseline"], capsys)
    assert rc == 2 and "config error" in err
    # topk cannot exceed the class count
    rc, err = _serve_main_rc(
        ["baseline", "--ckpt", "/tmp/x.msgpack", "--num_classes", "4",
         "--topk", "9"], capsys)
    assert rc == 2 and "config error" in err


def test_cli_serve_selfcheck_smoke(tmp_path, capsys):
    """The socket-free end-to-end path: cli.serve --selfcheck builds the
    model, warms every bucket, serves synthetic requests through the real
    batcher thread, drains, and returns cleanly (rc 0)."""
    from ddp_classification_pytorch_tpu.cli.serve import main

    # conftest forces 8 CPU devices; --serve_devices 2 keeps the explicit
    # (2,4) buckets dp-divisible AND makes selfcheck exercise the
    # dp-sharded predict end to end
    main(["baseline", "--model", "resnet18", "--variant", "cifar",
          "--dtype", "float32", "--num_classes", "8", "--image_size", "32",
          "--buckets", "2,4", "--max_batch", "4", "--batch_timeout_ms", "20",
          "--serve_devices", "2",
          "--selfcheck", "5", "--platform", "cpu", "--out", str(tmp_path)])
    out = capsys.readouterr().out
    assert "selfcheck ok: 5 requests" in out
    assert "[serve]" in out and "p50=" in out
