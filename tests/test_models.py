"""Golden shape/dtype tests for the Flax model zoo (reference zoos:
NESTED/model/cifar_resnet.py, imagenet_resnet.py, vgg.py)."""

import jax
import jax.numpy as jnp
import pytest

from ddp_classification_pytorch_tpu.config import ModelConfig
from ddp_classification_pytorch_tpu.models import (
    FEAT_DIMS, build_model, resnet18, resnet50, vgg19_bn,
)
from ddp_classification_pytorch_tpu.models.factory import (
    ArcFaceModel, ClassifierModel, NestedModel,
)


def _init_and_apply(model, x, **apply_kw):
    variables = model.init(jax.random.key(0), x, train=False)
    out = model.apply(variables, x, train=False, **apply_kw)
    return variables, out


@pytest.mark.parametrize("factory,feat", [(resnet18, 512), (resnet50, 2048)])
def test_resnet_imagenet_feature_shapes(factory, feat):
    x = jnp.zeros((2, 64, 64, 3))  # small spatial for test speed
    model = factory(num_classes=0, variant="imagenet", dtype=jnp.float32)
    _, out = _init_and_apply(model, x)
    assert out.shape == (2, feat)
    assert out.dtype == jnp.float32


def test_resnet_cifar_stem_keeps_resolution():
    x = jnp.zeros((2, 32, 32, 3))
    model = resnet18(num_classes=10, variant="cifar", dtype=jnp.float32)
    variables, out = _init_and_apply(model, x)
    assert out.shape == (2, 10)
    # cifar stem: no /2 stem stride and no maxpool → layer1 sees 32×32
    stem_bn = variables["batch_stats"]["bn_stem"]["mean"]
    assert stem_bn.shape == (64,)


def test_resnet_classifier_logits():
    x = jnp.zeros((2, 64, 64, 3))
    model = resnet18(num_classes=7, variant="imagenet", dtype=jnp.float32)
    _, out = _init_and_apply(model, x)
    assert out.shape == (2, 7)


def test_batch_stats_update_in_train_mode():
    x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3))
    model = resnet18(num_classes=0, variant="cifar", dtype=jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    out, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    before = variables["batch_stats"]["bn_stem"]["mean"]
    after = mutated["batch_stats"]["bn_stem"]["mean"]
    assert not jnp.allclose(before, after)


def test_freeze_bn_no_stat_update():
    """NESTED freeze-BN (model/model.py:44-55): train forward must use running
    stats and leave them unchanged."""
    from ddp_classification_pytorch_tpu.models.resnet import resnet18 as r18

    x = jax.random.normal(jax.random.key(1), (4, 32, 32, 3))
    model = r18(num_classes=0, variant="cifar", dtype=jnp.float32, freeze_bn=True)
    variables = model.init(jax.random.key(0), x, train=False)
    _, mutated = model.apply(variables, x, train=True, mutable=["batch_stats"])
    before = variables["batch_stats"]["bn_stem"]["mean"]
    after = mutated.get("batch_stats", {}).get("bn_stem", {}).get("mean", before)
    assert jnp.allclose(before, after)


def test_vgg19_bn_feature_and_logits():
    x = jnp.zeros((2, 32, 32, 3))
    model = vgg19_bn(num_classes=0, dtype=jnp.float32)
    variables = model.init(jax.random.key(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 4096)


def test_build_model_fc_head():
    cfg = ModelConfig(arch="resnet18", dtype="float32")
    model = build_model(cfg, num_classes=11)
    assert isinstance(model, ClassifierModel)
    x = jnp.zeros((2, 64, 64, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 11)


def test_build_model_arcface_head():
    cfg = ModelConfig(arch="resnet18", head="arcface", dtype="float32")
    model = build_model(cfg, num_classes=11)
    assert isinstance(model, ArcFaceModel)
    x = jnp.zeros((2, 64, 64, 3))
    labels = jnp.zeros((2,), jnp.int32)
    variables = model.init(jax.random.key(0), x, labels, train=False)
    out = model.apply(variables, x, labels, train=False)
    assert out.shape == (2, 11)
    scores = model.apply(variables, x, None, train=False)
    assert scores.shape == (2, 11)


def test_build_model_nested_head():
    cfg = ModelConfig(arch="resnet18", head="nested", dtype="float32", freeze_bn=True)
    model = build_model(cfg, num_classes=11)
    assert isinstance(model, NestedModel)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    mask = jnp.ones((1, FEAT_DIMS["resnet18"]))
    out = model.apply(variables, x, mask, train=False)
    assert out.shape == (2, 11)
