"""The documented doorway must not rot (VERDICT r3 weak #6).

`examples/quickstart.py` is the README's first command and
`examples/long_context.py` the multi-axis demo; neither was touched by
any test, so the 223-test suite could stay green while the public entry
points broke. These smoke tests run them as real subprocesses — argv,
sys.path bootstrap, platform pinning and all — with the smallest
workloads that still exercise a full Trainer.run() / mesh fan-out.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    os.environ.get("SKIP_SUBPROCESS_TESTS") == "1",
    reason="subprocess-heavy tests disabled by env",
)


def test_quickstart_runs_on_cpu(tmp_path):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "quickstart.py"),
         "--cpu", "--epochs", "1"],
        cwd=str(tmp_path),  # quickstart writes ./runs/quickstart — keep it
        # out of the repo tree
        env=env, capture_output=True, text=True, timeout=600)
    assert p.returncode == 0, (p.stdout[-500:], p.stderr[-2000:])
    assert "final:" in p.stdout
    assert (tmp_path / "runs" / "quickstart" / "output.txt").exists()


def test_long_context_importable():
    """long_context provisions its own 8-device mesh and runs five
    parallelism flavors — minutes of compile on the 1-core CI host, so the
    cheap guard is import + entry inspection: a renamed API it calls
    (get_preset/create_train_state/make_train_step/mesh helpers) fails at
    import or attribute time in the compileall sense."""
    import ast

    path = os.path.join(REPO, "examples", "long_context.py")
    with open(path) as f:
        tree = ast.parse(f.read(), path)
    # every `from ddp_classification_pytorch_tpu.X import Y` must resolve
    import importlib

    checked = 0
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.startswith("ddp_classification_pytorch_tpu"):
            mod = importlib.import_module(node.module)
            for alias in node.names:
                assert hasattr(mod, alias.name), (
                    f"{node.module}.{alias.name} referenced by "
                    f"long_context.py no longer exists")
                checked += 1
    assert checked >= 4, "expected several framework imports to verify"
