"""Flash-attention kernel (interpret mode on CPU) vs the dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_classification_pytorch_tpu.ops.attention import attention
from ddp_classification_pytorch_tpu.ops.flash_attention import flash_attention


def _qkv(b=2, t=128, h=2, d=32, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)), dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("t", [128, 196, 256])
def test_flash_matches_dense(t):
    """Aligned (128/256) and odd-T single-block fallback (196) forwards.
    Multi-block streaming is pinned below with a shrunken block size."""
    q, k, v = _qkv(t=t)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v)),
        np.asarray(attention(q, k, v)), atol=1e-5)


def test_flash_multiblock_forward_and_backward(monkeypatch):
    """Shrink the block size to 64 so T=256 genuinely streams 4 blocks:
    exercises the forward's online-softmax rescaling across kv steps and
    BOTH backward kernels' scratch init/accumulate/write paths
    (kk==0 / += / kk==nk-1), which full-size blocks only hit at T ≥ 1024."""
    import importlib

    # the ops package re-exports a same-named function, so plain imports
    # resolve to it instead of the module
    fa = importlib.import_module(
        "ddp_classification_pytorch_tpu.ops.flash_attention")
    monkeypatch.setattr(fa, "_block", lambda t, cap=1024: 64)
    q, k, v = _qkv(t=256)
    np.testing.assert_allclose(
        np.asarray(fa.flash_attention(q, k, v)),
        np.asarray(attention(q, k, v)), atol=1e-5)
    gf = jax.grad(lambda q, k, v: (fa.flash_attention(q, k, v) ** 2).mean(),
                  argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: (attention(q, k, v) ** 2).mean(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.parametrize("t", [128, 196])
def test_flash_causal_matches_dense(t):
    q, k, v = _qkv(t=t)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v, causal=True)),
        np.asarray(attention(q, k, v, causal=True)), atol=1e-5)


def test_flash_causal_gradients_multiblock(monkeypatch):
    """Block 64 at T=256 → blocks fully below, straddling, and fully above
    the diagonal all occur, in the forward and BOTH backward kernels."""
    import importlib

    fa = importlib.import_module(
        "ddp_classification_pytorch_tpu.ops.flash_attention")
    monkeypatch.setattr(fa, "_block", lambda t, cap=1024: 64)
    q, k, v = _qkv(t=256)
    np.testing.assert_allclose(
        np.asarray(fa.flash_attention(q, k, v, causal=True)),
        np.asarray(attention(q, k, v, causal=True)), atol=1e-5)
    gf = jax.grad(
        lambda q, k, v: (fa.flash_attention(q, k, v, causal=True) ** 2).mean(),
        argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(
        lambda q, k, v: (attention(q, k, v, causal=True) ** 2).mean(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_flash_unsupported_t_falls_back_to_dense():
    """Prime T above 512 cannot tile cleanly; the public entry point must
    route to the dense op (same values, gradients still defined)."""
    q, k, v = _qkv(b=1, t=521, h=1, d=16)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v)),
        np.asarray(attention(q, k, v)), atol=1e-5)
    g = jax.grad(lambda q: (flash_attention(q, k, v) ** 2).mean())(q)
    assert np.all(np.isfinite(np.asarray(g)))


def test_flash_bf16_close_to_f32_dense():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = attention(q.astype(jnp.float32), k.astype(jnp.float32),
                    v.astype(jnp.float32))
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2)


@pytest.mark.parametrize("t", [128, 196, 256])
def test_flash_gradients_match_dense(t):
    """Single-block backward over aligned (128/256) and odd-T (196) shapes.
    The multi-block accumulation paths are pinned separately below with a
    shrunken block size (full-scale blocks only split at T ≥ 1024, too slow
    for interpret mode)."""
    q, k, v = _qkv(t=t)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v) ** 2).mean()

    def loss_dense(q, k, v):
        return (attention(q, k, v) ** 2).mean()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_flash_bf16_gradients_close_to_f32_dense():
    """The backward kernels keep MXU operands in the input dtype (bf16 in
    the ViT recipe) with f32 accumulation — pin that path against the f32
    dense gradients with a bf16-appropriate tolerance."""
    q, k, v = _qkv(dtype=jnp.bfloat16)
    gf = jax.grad(lambda q, k, v: (flash_attention(q, k, v) ** 2)
                  .astype(jnp.float32).mean(), argnums=(0, 1, 2))(q, k, v)
    q32, k32, v32 = (x.astype(jnp.float32) for x in (q, k, v))
    gd = jax.grad(lambda q, k, v: (attention(q, k, v) ** 2).mean(),
                  argnums=(0, 1, 2))(q32, k32, v32)
    for a, b in zip(gf, gd):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b), atol=5e-2)


def test_flash_under_jit_and_vmap_free_shapes():
    q, k, v = _qkv(b=1, t=128, h=1, d=64)
    out = jax.jit(flash_attention)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(attention(q, k, v)), atol=1e-5)


def test_vit_with_flash_matches_dense_vit():
    """Same params: ViT(use_flash=True) == ViT(use_flash=False)."""
    from ddp_classification_pytorch_tpu.models.vit import build_vit

    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 64, 64, 3)), jnp.float32)
    dense = build_vit("vit_t16", num_classes=5, dtype=jnp.float32)
    flash = build_vit("vit_t16", num_classes=5, dtype=jnp.float32,
                      use_flash=True)
    vs = dense.init(jax.random.PRNGKey(0), x, train=False)
    np.testing.assert_allclose(
        np.asarray(flash.apply(vs, x, train=False)),
        np.asarray(dense.apply(vs, x, train=False)), atol=1e-4)
