"""Shared step-runner for the multi-host test and its single-process oracle.

`run_steps` builds the baseline workload's real train step and runs it on a
fixed, seeded 16-row global batch; callers pass the row slice this host
contributes (`make_global_array` stitches the rest from the other hosts).
The losses must be bit-comparable between a 2-process run and a
single-process 8-device run — multi-host changes WHERE shards live, not the
math.
"""

from typing import List


def run_steps(mesh, host_rows: slice, steps: int = 3) -> List[float]:
    import numpy as np

    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    cfg = get_preset("baseline")
    cfg.data.image_size = 32
    cfg.data.num_classes = 4
    cfg.data.batch_size = 16
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"

    rng = np.random.default_rng(3)
    images = rng.normal(size=(16, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 4, 16).astype(np.int32)

    with mesh:
        model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
        step = make_train_step(cfg, model, tx, mesh=mesh)
        batch = meshlib.make_global_array(
            (images[host_rows], labels[host_rows]), mesh)
        losses = []
        for _ in range(steps):
            state, metrics = step(state, *batch)
            losses.append(float(metrics["loss"]))
    return losses


def run_composed_steps(host_rows: slice, steps: int = 2,
                       spec=None, replicate_batch: bool = False) -> List[float]:
    """dp×tp ArcFace with the class-sharded partial-FC CE — the
    composed-mesh path across whatever process topology the caller's backend
    has (VERDICT r4 next #5: before this, no mesh with a model axis had ever
    crossed a real process boundary). The single-process oracle runs the
    default 4×2 layout; the two-process workers run 1×2 — the TP pair
    itself straddles the REAL process boundary (every partial-FC collective
    crosses it), with the batch replicated (`replicate_batch`: each process
    device_puts the identical seeded global batch; dp=1 means there is no
    per-host shard to stitch). Loss trajectory must equal the
    single-process run of the same global batch to f32 tolerance."""
    import numpy as np

    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    cfg = get_preset("arcface")
    cfg.data.image_size = 32
    cfg.data.num_classes = 64
    cfg.data.batch_size = 16
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.parallel.model_axis = 2
    cfg.parallel.arcface_sharded_ce = True

    rng = np.random.default_rng(5)
    images = rng.normal(size=(16, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 64, 16).astype(np.int32)

    mesh = meshlib.make_mesh(spec or meshlib.MeshSpec(4, 2))
    with mesh:
        model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
        step = make_train_step(cfg, model, tx, mesh=mesh)
        if replicate_batch:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(mesh, P(meshlib.DATA_AXIS))
            batch = tuple(jax.device_put(x, sharding)
                          for x in (images, labels))
        else:
            batch = meshlib.make_global_array(
                (images[host_rows], labels[host_rows]), mesh)
        losses = []
        for _ in range(steps):
            state, metrics = step(state, *batch)
            losses.append(float(metrics["loss"]))
    return losses
