"""Expert-parallel MoE FFN (ops/moe.py) — exactness on the 8-device mesh.

EP is absent from the reference (SURVEY §2.2); these tests pin the
framework's extension: the expert-sharded path must equal the unsharded
mixture bit-for-bit in values AND gradients, and the ViT-MoE model must
train end-to-end on a data×model mesh with expert banks actually sharded.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_classification_pytorch_tpu.ops.moe import (
    moe_mlp,
    router_logits,
    topk_gates,
)
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib


def _params(c=16, e=4, h=16, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda *s: jnp.asarray(rng.normal(size=s) * 0.1, jnp.float32)
    return dict(router_w=mk(c, e), w_in=mk(e, c, h), b_in=mk(e, h),
                w_out=mk(e, h, c), b_out=mk(e, c))


def test_topk_gates_sparse_and_normalized():
    p = _params()
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 16)), jnp.float32)
    g = topk_gates(router_logits(x, p["router_w"]), top_k=2)
    nz = np.count_nonzero(np.asarray(g), axis=-1)
    assert (nz == 2).all()
    np.testing.assert_allclose(np.asarray(g.sum(-1)), 1.0, atol=1e-6)


@pytest.mark.parametrize("mp", [2, 4])
def test_moe_sharded_matches_unsharded(mp):
    mesh = meshlib.make_mesh(meshlib.MeshSpec(len(jax.devices()) // mp, mp))
    p = _params()
    x = jnp.asarray(np.random.default_rng(2).normal(size=(8, 8, 16)), jnp.float32)
    gates = topk_gates(router_logits(x, p["router_w"]), top_k=2)
    ew = {k: v for k, v in p.items() if k != "router_w"}
    dense = moe_mlp(x, gates, **ew, dtype=jnp.float32)
    sharded = jax.jit(lambda x, g: moe_mlp(
        x, g, **ew, dtype=jnp.float32, mesh=mesh,
        axis=meshlib.MODEL_AXIS, batch_axis=meshlib.DATA_AXIS))(x, gates)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(dense), atol=1e-5)


def test_moe_sharded_gradients_match_unsharded():
    mesh = meshlib.make_mesh(meshlib.MeshSpec(2, 4))
    p = _params()
    x = jnp.asarray(np.random.default_rng(3).normal(size=(8, 8, 16)), jnp.float32)

    def loss(kind):
        kw = (dict(mesh=mesh, axis=meshlib.MODEL_AXIS,
                   batch_axis=meshlib.DATA_AXIS) if kind == "sharded" else {})

        def f(x, p):
            gates = topk_gates(router_logits(x, p["router_w"]), top_k=2)
            ew = {k: v for k, v in p.items() if k != "router_w"}
            return (moe_mlp(x, gates, **ew, dtype=jnp.float32, **kw) ** 2).mean()

        return f

    gs = jax.jit(jax.grad(loss("sharded"), argnums=(0, 1)))(x, p)
    gd = jax.grad(loss("dense"), argnums=(0, 1))(x, p)
    for a, b in zip(jax.tree_util.tree_leaves(gs), jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_moe_rejects_indivisible_experts():
    mesh = meshlib.make_mesh(meshlib.MeshSpec(2, 4))
    p = _params(e=6, h=8)
    x = jnp.zeros((4, 4, 16), jnp.float32)
    gates = topk_gates(router_logits(x, p["router_w"]), top_k=2)
    ew = {k: v for k, v in p.items() if k != "router_w"}
    with pytest.raises(ValueError, match="not divisible"):
        moe_mlp(x, gates, **ew, mesh=mesh, axis=meshlib.MODEL_AXIS)


def test_vit_moe_trains_on_expert_parallel_mesh():
    """Full dp×ep train step: loss decreases, expert banks sharded over the
    model axis, router replicated."""
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    mesh = meshlib.make_mesh(meshlib.MeshSpec(4, 2))
    cfg = get_preset("baseline")
    cfg.model.arch = "vit_t16"
    cfg.model.dtype = "float32"
    cfg.model.moe_experts = 4
    cfg.data.image_size = 32
    cfg.data.num_classes = 8
    cfg.data.batch_size = 16
    cfg.parallel.model_axis = 2

    rng = np.random.default_rng(0)
    images = rng.normal(size=(16, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 8, 16).astype(np.int32)
    with mesh:
        model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
        w = state.params["backbone"]["block0"]["moe_w_in"]
        assert w.sharding.spec[0] == meshlib.MODEL_AXIS, w.sharding
        r = state.params["backbone"]["block0"]["moe_router"]
        assert all(s is None for s in r.sharding.spec), r.sharding

        step = make_train_step(cfg, model, tx, mesh=mesh)
        x = jax.device_put(images, meshlib.batch_sharding(mesh))
        y = jax.device_put(labels, meshlib.batch_sharding(mesh))
        losses = []
        for _ in range(4):
            state, metrics = step(state, x, y)
            losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_moe_invalid_configs_fail_loudly():
    """top_k out of range, non-dividing expert count, and the PP/MoE
    model-axis conflict must all raise instead of silently degrading."""
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.models.factory import build_model

    p = _params(e=2, h=32)
    x = jnp.zeros((2, 4, 16), jnp.float32)
    with pytest.raises(ValueError, match="top_k"):
        topk_gates(router_logits(x, p["router_w"]), top_k=3)

    cfg = get_preset("baseline").model
    cfg.arch = "vit_t16"
    cfg.moe_experts = 5  # does not divide 4*192
    model = build_model(cfg, 8)
    with pytest.raises(ValueError, match="divide"):
        model.init(jax.random.PRNGKey(0),
                   jnp.zeros((2, 32, 32, 3), jnp.float32), train=False)

    cfg.moe_experts = 4
    mesh = meshlib.make_mesh(meshlib.MeshSpec(4, 2))
    with pytest.raises(ValueError, match="one role per config"):
        build_model(cfg, 8, mesh=mesh, pipeline_microbatches=2)


def test_load_balance_loss_penalizes_collapse():
    """A router collapsed onto one expert must score higher than a
    near-uniform one; the uniform limit is ≈ top_k (Switch convention)."""
    from ddp_classification_pytorch_tpu.ops.moe import load_balance_loss

    rng = np.random.default_rng(0)
    # feature 0 strictly positive so a router keyed on it collapses every
    # token onto expert 0 (the router is linear in x — no bias term)
    x = jnp.asarray(np.abs(rng.normal(size=(2, 16, 8))) + 0.1, jnp.float32)
    uniform = jnp.zeros((8, 4), jnp.float32)      # logits all equal
    collapsed = jnp.zeros((8, 4), jnp.float32).at[0, 0].set(50.0)
    lu = float(load_balance_loss(router_logits(x, uniform), top_k=2))
    lc = float(load_balance_loss(router_logits(x, collapsed), top_k=2))
    assert lc > lu
    assert lc == pytest.approx(4.0, abs=0.1)      # E·f_0·p_0 = 4·1·1
    assert lu == pytest.approx(2.0, abs=0.3)      # ≈ top_k when uniform


def test_moe_aux_loss_enters_training_loss():
    """The sown per-block penalties must reach the train loss: weight 0 vs
    default weight give different losses from identical state; and the
    remat path must tolerate the 'losses' collection."""
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    mesh = meshlib.make_mesh(meshlib.MeshSpec(len(jax.devices()), 1))
    rng = np.random.default_rng(0)
    images = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 8, 8).astype(np.int32)
    losses = {}
    for w in (0.0, 0.01):
        cfg = get_preset("baseline")
        cfg.model.arch = "vit_t16"
        cfg.model.dtype = "float32"
        cfg.model.moe_experts = 4
        cfg.model.moe_aux_weight = w
        cfg.model.remat = True
        cfg.data.image_size = 32
        cfg.data.num_classes = 8
        cfg.data.batch_size = 8
        with mesh:
            model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
            step = make_train_step(cfg, model, tx, mesh=mesh)
            x = jax.device_put(images, meshlib.batch_sharding(mesh))
            y = jax.device_put(labels, meshlib.batch_sharding(mesh))
            _, metrics = step(state, x, y)
            losses[w] = float(metrics["loss"])
    assert losses[0.01] > losses[0.0]
    # aux ≈ top_k per block × 12 blocks × 0.01 weight ≈ 0.24 at init
    assert losses[0.01] - losses[0.0] == pytest.approx(0.24, abs=0.1)
