"""ArcFace with a class-sharded head through the full Trainer config path
(cfg.parallel.model_axis=2 on the 8-device mesh → data=4 × model=2)."""

import numpy as np


from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.parallel.mesh import MODEL_AXIS
from ddp_classification_pytorch_tpu.train.loop import Trainer


def test_arcface_model_parallel_trainer(tmp_path):
    cfg = get_preset("arcface")
    cfg.data.dataset = "synthetic"
    cfg.data.image_size = 16
    cfg.data.num_classes = 8  # divisible by model axis
    cfg.data.synthetic_size = 64
    cfg.data.batch_size = 16
    cfg.data.num_workers = 1
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.parallel.model_axis = 2
    cfg.run.epochs = 1
    cfg.run.write_records = False
    cfg.run.save_every_epoch = False
    cfg.run.out_dir = str(tmp_path)

    tr = Trainer(cfg)
    assert dict(zip(tr.mesh.axis_names, tr.mesh.devices.shape)) == {
        "data": 4, "model": 2}
    w = tr.state.params["margin"]["weight"]
    assert w.sharding.spec[0] == MODEL_AXIS, w.sharding

    m = tr.train_epoch(0)
    assert np.isfinite(m["loss"])
    val = tr.evaluate()
    assert 0.0 <= val["val_top1"] <= 1.0
    # weight stays sharded after the step (no silent gather)
    w2 = tr.state.params["margin"]["weight"]
    assert w2.sharding.spec[0] == MODEL_AXIS
