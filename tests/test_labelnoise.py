"""PLC noisy-label toolkit tests (reference semantics: PLC/utils.py:149-360)."""

import numpy as np

from ddp_classification_pytorch_tpu.ops.labelnoise import (
    eta_approximation,
    label_noise,
    lrt_correction,
    prob_correction,
)


def _eta_for(labels, num_classes, confidence, rng):
    """Synthetic posterior: extra `confidence` mass on the true class."""
    n = len(labels)
    eta = rng.random((n, num_classes)) * 0.3
    eta[np.arange(n), labels] += confidence
    return eta / eta.sum(1, keepdims=True)


def test_label_noise_binary_flips_only_ones():
    rng = np.random.default_rng(0)
    labels = rng.integers(0, 2, 1000)
    eta = _eta_for(labels, 2, 3.0, rng)
    for t in (0, 1, 2):
        noisy, f_us, count = label_noise(labels, eta, t, rng=np.random.default_rng(t))
        # class-0 samples never change (reference :163: only y==1 redrawn)
        assert (noisy[labels == 0] == 0).all()
        assert f_us.shape == (1000,)
        assert count == int(((labels == 1) & (noisy == 0)).sum())


def test_label_noise_multiclass_targets_top2():
    rng = np.random.default_rng(1)
    labels = rng.integers(0, 10, 2000)
    eta = _eta_for(labels, 10, 2.0, rng)
    order = np.argsort(-eta, axis=1)
    u, s = order[:, 0], order[:, 1]
    for t in (0, 1, 2):
        noisy, f_us, count = label_noise(labels, eta, t, rng=np.random.default_rng(t))
        # every resampled label is one of the top-2 η classes (reference :186)
        assert ((noisy == u) | (noisy == s)).all()
        assert count == int((noisy != labels).sum())
        assert 0 < count < len(labels)  # some noise, not total


def test_label_noise_type0_noise_floor():
    # type 0 noise_level = max(1-f, 0.5): even a perfectly confident η keeps
    # ≥ (0.5/factor) chance of flipping to u (which IS the true class when η
    # is centered on it) — so with η == one-hot, noisy labels stay u or s
    rng = np.random.default_rng(2)
    labels = rng.integers(0, 5, 500)
    eta = np.eye(5)[labels] * 0.9 + 0.02
    noisy, _, _ = label_noise(labels, eta, 0, rng=rng)
    u = np.argmax(eta, 1)
    assert ((noisy == u) | (noisy != u)).all()  # sanity: no out-of-range labels
    assert noisy.min() >= 0 and noisy.max() < 5


def test_lrt_correction_flips_low_ratio():
    # 4 samples, 3 classes; f_x rows: prob-like scores
    f_x = np.array([
        [0.9, 0.05, 0.05],   # y=1 -> LR 0.055 < 0.3 -> flip to 0
        [0.4, 0.5, 0.1],     # y=1 -> LR 1.0 -> keep
        [0.2, 0.3, 0.5],     # y=2 -> LR 1.0 -> keep
        [0.3, 0.35, 0.35],   # y=0 -> LR 0.857 -> keep
    ])
    y = np.array([1, 1, 2, 0])
    out, delta = lrt_correction(y, f_x, current_delta=0.3, delta_increment=0.1)
    assert out.tolist() == [0, 1, 2, 0]
    assert delta == 0.3  # 1 correction ≥ 0.001·4 -> threshold unchanged

    # no corrections -> delta grows, capped at 0.9
    y2 = np.array([0, 1, 2, 1])
    out2, d2 = lrt_correction(y2, f_x, current_delta=0.85, delta_increment=0.1)
    assert d2 == 0.9


def test_prob_correction_reference_k1():
    logits = np.array([
        [5.0, 0.0, 0.0],   # confident; y=1 ratio << delta -> flip to 0
        [0.1, 0.0, 0.0],   # low-confidence if thd high -> argmax flip (k=1)
    ])
    y = np.array([1, 2])
    out, delta = prob_correction(y, logits, current_delta=0.3, thd=0.99)
    # row0: top prob ~0.97 < .99 -> low-conf branch -> argmax 0
    # row1: low-conf -> argmax 0
    assert out.tolist() == [0, 0]
    assert delta == 0.4  # no LRT corrections -> delta += increment (uncapped)

    out2, d2 = prob_correction(np.array([1, 2]), logits, current_delta=0.3, thd=0.5)
    assert out2[0] == 0  # confident LRT flip
    assert d2 == 0.3


def test_eta_approximation_learns_separable_features():
    rng = np.random.default_rng(3)
    n, d, c = 600, 16, 3
    labels = rng.integers(0, c, n)
    means = rng.normal(0, 3, (c, d))
    feats = means[labels] + rng.normal(0, 0.5, (n, d))
    eta = eta_approximation(feats.astype(np.float32), labels, c,
                            n_epochs=20, lr=0.05, batch_size=100)
    assert eta.shape == (n, c)
    np.testing.assert_allclose(eta.sum(1), 1.0, atol=1e-4)
    # probe should mostly assign highest η to the true class
    acc = (eta.argmax(1) == labels).mean()
    assert acc > 0.9, acc


def test_cap_flips_keeps_most_confident():
    import numpy as np

    from ddp_classification_pytorch_tpu.ops.labelnoise import cap_flips

    y = np.array([0, 0, 0, 0, 1])
    new = np.array([1, 2, 1, 0, 1])  # 3 proposed flips (rows 0,1,2)
    p = np.array([
        [0.4, 0.6, 0.0],   # margin 0.2
        [0.1, 0.0, 0.9],   # margin 0.8  <- most confident
        [0.45, 0.55, 0.0], # margin 0.1
        [1.0, 0.0, 0.0],
        [0.0, 1.0, 0.0],
    ])
    capped = cap_flips(y, new, p, max_flip_frac=2 / 5)
    assert capped.tolist() == [1, 2, 0, 0, 1]  # rows 0,1 kept, row 2 reverted
    # uncapped passes through untouched
    assert cap_flips(y, new, p, 1.0).tolist() == new.tolist()
