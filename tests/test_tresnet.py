"""TResNet-M: shapes, train/eval modes, stats updates, and a train step."""

import jax
import jax.numpy as jnp
import numpy as np

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.models.tresnet import space_to_depth, tresnet_m


def test_space_to_depth_roundtrip():
    x = jnp.arange(2 * 8 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 8, 3)
    y = space_to_depth(x, 4)
    assert y.shape == (2, 2, 2, 48)
    # every input element survives exactly once
    np.testing.assert_array_equal(
        np.sort(np.asarray(y).ravel()), np.sort(np.asarray(x).ravel())
    )


def test_tresnet_forward_shapes_and_stats():
    model = tresnet_m(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    assert "batch_stats" in variables

    logits, mutated = model.apply(
        variables, x, train=True, mutable=["batch_stats"])
    assert logits.shape == (2, 10)

    # train-mode pass must update the running stats
    before = jax.tree_util.tree_leaves(variables["batch_stats"])
    after = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(not np.allclose(a, b) for a, b in zip(before, after))

    eval_logits = model.apply(variables, x, train=False)
    assert eval_logits.shape == (2, 10)


def test_tresnet_feature_mode():
    model = tresnet_m(num_classes=0, dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    feats = model.apply(variables, x, train=False)
    assert feats.shape == (2, 2048)  # stage-4 bottleneck: 512 · expansion 4


def test_tresnet_train_step_runs():
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    cfg = get_preset("baseline")
    cfg.model.arch = "tresnet_m"
    cfg.model.dtype = "float32"
    cfg.data.image_size = 64
    cfg.data.num_classes = 4
    cfg.data.batch_size = 16

    mesh = meshlib.make_mesh()
    with mesh:
        model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
        step = make_train_step(cfg, model, tx)
        rng = np.random.default_rng(0)
        images = jax.device_put(
            rng.normal(size=(16, 64, 64, 3)).astype(np.float32),
            meshlib.batch_sharding(mesh))
        labels = jax.device_put(
            rng.integers(0, 4, 16).astype(np.int32), meshlib.batch_sharding(mesh))
        state, metrics = step(state, images, labels)
        assert np.isfinite(float(metrics["loss"]))


def test_tresnet_odd_stage_dims_forward():
    """image_size ≡ 4 (mod 8) makes stride-2 stage inputs odd: the ceil-mode
    shortcut avg-pool must match BlurPool's padded output (regression: VALID
    avg-pool floored the shortcut to a smaller map and the residual add
    crashed)."""
    import jax
    import jax.numpy as jnp

    from ddp_classification_pytorch_tpu.models.tresnet import tresnet_m

    model = tresnet_m(num_classes=3, dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 36, 36, 3)), train=False)
    out = model.apply(variables, jnp.zeros((2, 36, 36, 3)), train=False)
    assert out.shape == (2, 3)
