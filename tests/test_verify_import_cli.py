"""`cli.verify_import` end-to-end: the one-command certification of a real
`.pth` (VERDICT r3 #8). No real torchvision checkpoint can exist in this
zero-egress sandbox, so the test manufactures the closest thing — a
REAL torch-serialized state_dict of the randomized oracle — and drives
the CLI through its full path: torch.load, strict oracle load, converter,
flax forward, verdict. A corrupted weight must flip the verdict to FAIL
and the exit code to 1; junk input must exit 2.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from ddp_classification_pytorch_tpu.cli import verify_import  # noqa: E402
from ddp_classification_pytorch_tpu.models.torch_oracle import (  # noqa: E402
    make_torch_resnet,
    randomize_,
)


def _save_ckpt(tmp_path, mutate=None):
    tmodel = make_torch_resnet("resnet18", 12)
    randomize_(tmodel, seed=11)
    sd = tmodel.state_dict()
    if mutate:
        mutate(sd)
    path = tmp_path / "resnet18_oracle.pth"
    torch.save(sd, str(path))
    return str(path)


def _run(argv):
    with pytest.raises(SystemExit) as ei:
        verify_import.main(argv)
    return ei.value.code


def test_verify_import_passes_on_faithful_checkpoint(tmp_path, capsys):
    path = _save_ckpt(tmp_path)
    assert _run([path, "--arch", "resnet18"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("PASS") and "12 classes" in out


def test_verify_import_fail_exit_path(tmp_path, capsys):
    """The numeric-FAIL path (exit 1): forced via --tol 0 — f32 reduction
    order guarantees a nonzero max|Δ| between torch and XLA. (Value-level
    corruption of the .pth cannot produce this code: both the oracle and
    the converter read the SAME bytes, so parity holds by construction —
    what verify_import certifies is the converter against the artifact;
    see the missing-key test for how truncation-class damage surfaces.)"""
    path = _save_ckpt(tmp_path)
    assert _run([path, "--arch", "resnet18", "--tol", "0"]) == 1
    assert capsys.readouterr().out.startswith("FAIL")


def test_verify_import_rejects_truncated_checkpoint(tmp_path, capsys):
    """Truncation-class damage (a key missing) must fail the STRICT oracle
    load with exit 2 and name the key."""
    def truncate(sd):
        del sd["layer3.0.bn2.running_var"]

    path = _save_ckpt(tmp_path, truncate)
    assert _run([path, "--arch", "resnet18"]) == 2
    err = capsys.readouterr().err
    assert "layer3.0.bn2.running_var" in err


def test_verify_import_rejects_wrong_arch(tmp_path, capsys):
    path = _save_ckpt(tmp_path)
    # resnet50 oracle cannot strict-load a resnet18 state_dict
    assert _run([path, "--arch", "resnet50"]) == 2


def test_verify_import_rejects_non_checkpoint(tmp_path):
    junk = tmp_path / "junk.pth"
    junk.write_bytes(b"not a checkpoint")
    assert _run([str(junk), "--arch", "resnet18"]) == 2


def test_verify_import_deep_bottleneck_arch(tmp_path, capsys):
    """resnet101 exercises the deep Bottleneck mapping (layer3 ×23) the
    randomized parity suite doesn't cover — the reference zoo ships
    101/152 (SURVEY C11), so the certification command must too."""
    tmodel = make_torch_resnet("resnet101", 7)
    randomize_(tmodel, seed=2)
    path = tmp_path / "r101.pth"
    torch.save(tmodel.state_dict(), str(path))
    assert _run([str(path), "--arch", "resnet101"]) == 0
    assert capsys.readouterr().out.startswith("PASS")
