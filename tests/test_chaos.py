"""Robustness subsystem tests: fault-spec parsing, every injection point,
the non-finite step sentinel (skip + rc-8 escalation), and the
checksum-verified quarantine-and-fallback resume.

Tier-1-lean by design: the jitted-step tests run on a toy quadratic (no
model build), the checkpoint tests on a 4-float TrainState, and the
supervise.sh tests on the scripted stub interpreter from
test_recovery_rc_discipline. One small Trainer covers the loop wiring.
The full multi-process supervise.sh chaos drill is `slow`
(scripts/chaos_drill.sh).
"""

import os
import stat
import subprocess

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from ddp_classification_pytorch_tpu.train.checkpoint import CheckpointManager
from ddp_classification_pytorch_tpu.train.sentinel import (SentinelDiverged,
                                                           StepSentinel)
from ddp_classification_pytorch_tpu.train.state import TrainState
from ddp_classification_pytorch_tpu.train.steps import _build_step
from ddp_classification_pytorch_tpu.utils import chaos as chaoslib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ parsing --
def test_fault_spec_parses_all_kinds_and_ranges():
    plan = chaoslib.FaultPlan.parse(
        "nan_loss@step=7, ckpt_io@epoch=1, loader_io@batch=3..5, "
        "sigterm@step=20..")
    assert len(plan.faults) == 4 and bool(plan)
    assert plan.windows("nan_loss", "step") == [(7, 7)]
    f = plan.faults[2]
    assert (f.kind, f.unit, f.lo, f.hi) == ("loader_io", "batch", 3, 5)
    assert f.matches(3) and f.matches(5) and not f.matches(6)
    open_ended = plan.faults[3]
    assert open_ended.hi is None and open_ended.matches(10_000)
    # round-trips through str for the "[chaos] fault plan active" log line
    assert chaoslib.FaultPlan.parse(str(plan)).windows("nan_loss") == [(7, 7)]


def test_empty_spec_is_falsy_no_op_plan():
    plan = chaoslib.FaultPlan.parse("")
    assert not plan
    assert plan.should_fire("loader_io", epoch=0, batch=0) is None
    assert plan.windows("nan_loss") == []


@pytest.mark.parametrize("bad", [
    "foo@step=1",          # unknown kind
    "nan_loss@epoch=1",    # nan_loss is keyed by step
    "nan_loss@iter=1",     # unknown unit
    "nan_loss@step=",      # no value
    "nan_loss",            # no condition at all
    "sigterm@step=5..3",   # empty range
])
def test_fault_spec_rejects_malformed(bad):
    with pytest.raises(ValueError):
        chaoslib.FaultPlan.parse(bad)


def test_env_spec_overrides_config(monkeypatch):
    monkeypatch.setenv(chaoslib.ENV_SPEC, "sigterm@step=9")
    assert chaoslib.resolve_spec("nan_loss@step=1") == "sigterm@step=9"
    monkeypatch.delenv(chaoslib.ENV_SPEC)
    assert chaoslib.resolve_spec("nan_loss@step=1") == "nan_loss@step=1"
    assert chaoslib.resolve_spec("") == ""


def test_host_faults_fire_once_and_persist_across_plans(tmp_path):
    spec = "loader_io@batch=2"
    plan = chaoslib.FaultPlan.parse(spec, state_dir=str(tmp_path))
    assert plan.should_fire("loader_io", epoch=0, batch=2) is not None
    assert plan.should_fire("loader_io", epoch=0, batch=2) is None  # one-shot
    # a "restarted process" (fresh plan, same state_dir) must not re-fire
    plan2 = chaoslib.FaultPlan.parse(spec, state_dir=str(tmp_path))
    assert plan2.should_fire("loader_io", epoch=1, batch=2) is None
    # without a state_dir the firing state is per-process only
    plan3 = chaoslib.FaultPlan.parse(spec)
    assert plan3.should_fire("loader_io", epoch=0, batch=2) is not None


# ---------------------------------------------------------------- sentinel --
def test_sentinel_counts_skips_and_resets_streak():
    lines = []
    s = StepSentinel(max_bad_steps=5, log=lines.append)
    for ok in (1.0, 0.0, 0.0, 1.0, 0.0):
        s.observe(ok)
    s.flush()
    assert s.skipped_total == 3
    assert s.streak == 1  # trailing skip; the 1.0 in between reset it
    assert lines and "skipped 3" in lines[0]
    s.flush()  # empty window: no-op, no new lines
    assert len(lines) == 1


def test_sentinel_raises_on_sustained_streak_across_windows():
    s = StepSentinel(max_bad_steps=4, log=lambda m: None)
    for ok in (0.0, 0.0):
        s.observe(ok)
    s.flush()  # streak 2 — below threshold
    for ok in (0.0, 0.0):
        s.observe(ok)
    with pytest.raises(SentinelDiverged):
        s.flush()  # streak 4, carried across flush windows
    assert SentinelDiverged.exit_code == 8


def test_sentinel_zero_threshold_never_raises():
    s = StepSentinel(max_bad_steps=0, log=lambda m: None)
    for _ in range(50):
        s.observe(0.0)
    s.flush()
    assert s.skipped_total == 50


# -------------------------------------------------------- jitted step guard --
def _toy_step(chaos=None):
    """_build_step over a toy quadratic: no model build, compiles in ms."""
    tx = optax.sgd(0.1, momentum=0.9)
    params = {"w": jnp.linspace(-1.0, 1.0, 8, dtype=jnp.float32)}
    stats = {"m": jnp.ones((2,), jnp.float32)}

    def loss_fn(params, batch_stats, images, labels, rng):
        pred = (images * params["w"]).sum()
        loss = (pred - labels.sum()) ** 2 * 0.1
        return loss, (jax.tree_util.tree_map(lambda m: m + 1.0, batch_stats),
                      jnp.zeros((1,)))

    step = _build_step(tx, jax.random.PRNGKey(0), loss_fn,
                       lambda loss, aux, labels: {"loss": loss}, chaos=chaos)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       batch_stats=stats, opt_state=tx.init(params))
    images = jnp.arange(8, dtype=jnp.float32)
    labels = jnp.asarray([3], jnp.int32)
    return step, state, images, labels


def _run_steps(step, state, images, labels, n):
    trace = []
    for _ in range(n):
        state, metrics = step(state, images, labels)
        trace.append({
            "w": np.asarray(jax.device_get(state.params["w"])),
            "m": np.asarray(jax.device_get(state.batch_stats["m"])),
            "step": int(state.step),
            "step_ok": float(metrics["step_ok"]),
            "loss": float(metrics["loss"]),
            "grad_norm": float(metrics["grad_norm"]),
        })
    return trace


def test_nonfinite_step_applies_identity_update():
    plan = chaoslib.FaultPlan.parse("nan_loss@step=1..2")
    step, state, images, labels = _toy_step(chaos=plan)
    t = _run_steps(step, state, images, labels, 4)
    assert [r["step_ok"] for r in t] == [1.0, 0.0, 0.0, 1.0]
    assert np.isnan(t[1]["loss"]) and np.isnan(t[2]["loss"])
    # skipped steps: params AND batch stats bit-identical to the last good
    np.testing.assert_array_equal(t[1]["w"], t[0]["w"])
    np.testing.assert_array_equal(t[2]["w"], t[0]["w"])
    np.testing.assert_array_equal(t[2]["m"], t[0]["m"])
    # ...but the step counter still advances (rng/schedule stream moves on)
    assert [r["step"] for r in t] == [1, 2, 3, 4]
    # and the step after the window trains again
    assert not np.array_equal(t[3]["w"], t[2]["w"])
    assert np.isfinite(t[3]["loss"])


def test_absent_spec_is_bit_transparent():
    """`--fault_spec` absent ⇒ bit-for-bit the uninjected step (the
    depth-0-style equivalence contract): an empty plan, and a plan with
    only host-side faults, compile the IDENTICAL jitted program — no
    injection op exists to perturb even a fusion decision."""
    step_a, state_a, images, labels = _toy_step(chaos=None)
    ta = _run_steps(step_a, state_a, images, labels, 4)
    for spec in ("", "ckpt_io@epoch=9,loader_io@batch=9,sigterm@step=9"):
        plan = chaoslib.FaultPlan.parse(spec)
        step_b, state_b, images, labels = _toy_step(chaos=plan)
        tb = _run_steps(step_b, state_b, images, labels, 4)
        for a, b in zip(ta, tb):
            np.testing.assert_array_equal(a["w"], b["w"])
            np.testing.assert_array_equal(a["m"], b["m"])
            assert a["loss"] == b["loss"] and a["grad_norm"] == b["grad_norm"]
            assert a["step_ok"] == b["step_ok"] == 1.0


def test_out_of_window_nan_injection_never_skips():
    """A compiled-in window that never fires: no skips, same training to
    float tolerance (the extra select can shift XLA fusion by an ULP —
    the semantics, not the bits, are the contract once a window exists)."""
    step_a, state_a, images, labels = _toy_step(chaos=None)
    ta = _run_steps(step_a, state_a, images, labels, 4)
    plan = chaoslib.FaultPlan.parse("nan_loss@step=1000..")
    step_b, state_b, images, labels = _toy_step(chaos=plan)
    tb = _run_steps(step_b, state_b, images, labels, 4)
    for a, b in zip(ta, tb):
        assert a["step_ok"] == b["step_ok"] == 1.0
        np.testing.assert_allclose(a["w"], b["w"], rtol=1e-6, atol=1e-7)
        assert a["loss"] == pytest.approx(b["loss"], rel=1e-6)


# ------------------------------------------------------------------ loader --
def test_loader_io_injection_fires_once_then_recovers():
    from ddp_classification_pytorch_tpu.data.loader import ShardedLoader
    from ddp_classification_pytorch_tpu.data.synthetic import SyntheticDataset

    ds = SyntheticDataset(32, 4, 4, seed=0)
    plan = chaoslib.FaultPlan.parse("loader_io@batch=1")
    loader = ShardedLoader(ds, 8, shuffle=False, num_workers=1,
                           host_id=0, num_hosts=1, chaos=plan)
    with pytest.raises(IOError, match="chaos: injected loader failure"):
        list(loader)
    # one-shot: the "restarted" pass reads every batch cleanly
    assert len(list(loader)) == 4
    loader.close()


# ------------------------------------------- checksums + quarantine/fallback --
def _state(v: float) -> TrainState:
    return TrainState(
        step=jnp.asarray(int(v)),
        params={"w": jnp.full((4,), v)},
        batch_stats={"m": jnp.zeros((2,))},
        opt_state=(),
    )


def test_save_writes_matching_sha256_sidecar(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(1.0), 0, metric=0.5)
    mgr.wait()
    for name in ("ckpt_e0.msgpack", "ckpt_best.msgpack"):
        path = str(tmp_path / name)
        assert os.path.exists(path + ".sha256")
        assert mgr.verify_checkpoint(path) == "ok"


def test_quarantine_and_fallback_to_newest_verified(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(0.0), 0)
    mgr.save(_state(1.0), 1)
    mgr.wait()
    # tear the LATEST checkpoint (torn copy / bit rot / injected ckpt_io)
    p = tmp_path / "ckpt_e1.msgpack"
    data = p.read_bytes()
    p.write_bytes(data[: len(data) // 2])

    mgr2 = CheckpointManager(str(tmp_path))
    restored, next_epoch = mgr2.restore_latest(_state(-1.0))
    # fell back one epoch instead of crashing every restart identically
    assert next_epoch == 1
    np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                  np.zeros((4,)))
    assert (tmp_path / "ckpt_e1.msgpack.corrupt").exists()
    assert not (tmp_path / "ckpt_e1.msgpack").exists()  # out of the scan
    # the quarantined file stays quarantined on the NEXT restart too
    _, next_epoch = CheckpointManager(str(tmp_path)).restore_latest(_state(-1.0))
    assert next_epoch == 1


def test_legacy_checkpoint_without_sidecar_still_resumes(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(3.0), 0)
    mgr.wait()
    os.remove(str(tmp_path / "ckpt_e0.msgpack.sha256"))
    assert mgr.verify_checkpoint(str(tmp_path / "ckpt_e0.msgpack")) == "legacy"
    restored, next_epoch = CheckpointManager(str(tmp_path)).restore_latest(
        _state(-1.0))
    assert next_epoch == 1
    np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                  np.full((4,), 3.0))


def test_torn_legacy_checkpoint_is_quarantined_by_deserialization(tmp_path):
    """Pre-checksum torn file: no sidecar to fail, so from_bytes fails —
    auto-resume must quarantine it and fall back, not crash every retry."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(0.0), 0)
    mgr.save(_state(1.0), 1)
    mgr.wait()
    p = tmp_path / "ckpt_e1.msgpack"
    p.write_bytes(p.read_bytes()[:10])
    os.remove(str(p) + ".sha256")  # simulate a pre-checksum run's file

    restored, next_epoch = CheckpointManager(str(tmp_path)).restore_latest(
        _state(-1.0))
    assert next_epoch == 1
    assert (tmp_path / "ckpt_e1.msgpack.corrupt").exists()


def test_explicit_resume_of_corrupt_checkpoint_raises(tmp_path):
    """--resume <corrupt path> is deterministic: ValueError (rc 2 at the
    CLI), not the silent fallback reserved for --auto_resume."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(_state(0.0), 0)
    mgr.wait()
    p = tmp_path / "ckpt_e0.msgpack"
    p.write_bytes(p.read_bytes()[: 8])
    with pytest.raises(ValueError, match="sha256"):
        mgr.restore(_state(-1.0), str(p))


def test_ckpt_io_injection_tears_the_target_epoch_only(tmp_path):
    plan = chaoslib.FaultPlan.parse("ckpt_io@epoch=0")
    mgr = CheckpointManager(str(tmp_path), chaos=plan)
    mgr.save(_state(0.0), 0)
    mgr.save(_state(1.0), 1)
    mgr.wait()
    assert mgr.verify_checkpoint(mgr.epoch_path(0)) == "corrupt"
    assert mgr.verify_checkpoint(mgr.epoch_path(1)) == "ok"  # one-shot
    restored, next_epoch = CheckpointManager(str(tmp_path)).restore_latest(
        _state(-1.0))
    assert next_epoch == 2  # epoch 1 verified; the torn epoch 0 is ignored
    np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                  np.ones((4,)))


def test_prune_removes_sidecars_with_checkpoints(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=1)
    for e in range(3):
        mgr.save(_state(float(e)), e)
    mgr.wait()
    assert sorted(mgr._epoch_checkpoints()) == [2]
    left = sorted(f for f in os.listdir(tmp_path) if f.endswith(".sha256"))
    assert left == ["ckpt_e2.msgpack.sha256"]


# ------------------------------------------------------------ trainer wiring --
def test_trainer_nan_burst_skips_then_sustained_nan_diverges(tmp_path):
    """One tiny Trainer (ONE train-step compile — this is the expensive
    test of the file), both sentinel behaviors from a two-window plan: a
    bounded NaN burst is skipped and training continues; an open-ended
    window trips SentinelDiverged once the consecutive streak reaches
    max_bad_steps."""
    from ddp_classification_pytorch_tpu.config import get_preset
    from ddp_classification_pytorch_tpu.train.loop import Trainer

    cfg = get_preset("baseline")
    cfg.data.dataset = "synthetic"
    cfg.data.image_size = 16
    cfg.data.num_classes = 4
    cfg.data.synthetic_size = 128
    cfg.data.batch_size = 32
    cfg.data.num_workers = 1
    cfg.model.arch = "resnet18"
    cfg.model.variant = "cifar"
    cfg.model.dtype = "float32"
    cfg.run.epochs = 3
    cfg.run.log_every = 2
    cfg.run.out_dir = str(tmp_path)
    cfg.run.write_records = False
    cfg.run.save_every_epoch = False
    # 4 steps/epoch: a burst at steps 1-2 (epoch 0), then NaN forever
    # from step 6 (mid-epoch 1 onward)
    cfg.run.fault_spec = "nan_loss@step=1..2,nan_loss@step=6.."

    tr = Trainer(cfg)
    m = tr.train_epoch(0)  # steps 0-3; 1 and 2 poisoned
    assert m["step_ok"] == pytest.approx(0.5)
    assert tr.sentinel.skipped_total == 2
    assert tr.sentinel.streak == 0  # step 3 was finite and reset it
    # weights were never poisoned by the skipped steps
    assert np.all(np.isfinite(
        np.asarray(jax.device_get(
            jax.tree_util.tree_leaves(tr.state.params)[0]))))

    # sustained divergence: steps 6-7 of epoch 1 and all of epoch 2 are
    # non-finite — the streak carries across the epoch boundary
    tr.sentinel = StepSentinel(3)
    m = tr.train_epoch(1)  # ends with streak 2: below threshold
    assert tr.sentinel.streak == 2 and np.isfinite(m["top1"])
    with pytest.raises(SentinelDiverged):
        tr.train_epoch(2)


# --------------------------------------------------- supervise.sh discipline --
STUB = """#!/usr/bin/env bash
state="${FAKE_STATE:?}"
n=$(cat "$state" 2>/dev/null || echo 0)
n=$((n+1)); echo "$n" > "$state"
rc=$(echo "${FAKE_RCS:?}" | tr ',' '\\n' | sed -n "${n}p")
[ -z "$rc" ] && rc=$(echo "$FAKE_RCS" | tr ',' '\\n' | tail -1)
exit "$rc"
"""


def _stub_env(tmp_path, rcs):
    fakebin = tmp_path / "bin"
    fakebin.mkdir(exist_ok=True)
    stub = fakebin / "python"
    stub.write_text(STUB)
    stub.chmod(stub.stat().st_mode | stat.S_IXUSR)
    env = dict(os.environ)
    env["PATH"] = f"{fakebin}:{env['PATH']}"
    env["FAKE_STATE"] = str(tmp_path / "calls")
    env["FAKE_RCS"] = rcs
    return env


def test_supervise_rc8_is_deterministic_no_restart(tmp_path):
    out = tmp_path / "out"
    env = _stub_env(tmp_path, "8,0")
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"),
         "baseline", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 8, (p.returncode, p.stderr)
    assert int((tmp_path / "calls").read_text()) == 1, \
        "rc=8 (diverged) must stop without a restart"
    log = (out / "restarts.log").read_text()
    assert "rc=8" in log and "action=stop" in log


def test_supervise_appends_restart_lines(tmp_path):
    out = tmp_path / "out"
    env = _stub_env(tmp_path, "1,143,0")
    env["RUNTIME_BACKOFF_S"] = "0"
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh"),
         "baseline", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=30)
    assert p.returncode == 0, p.stderr
    lines = (out / "restarts.log").read_text().strip().splitlines()
    # one per non-zero exit, plus the final clean exit (elastic pods
    # reconstruct their world transitions from this log, so the
    # converged state must appear there too)
    assert len(lines) == 3
    assert "rc=1" in lines[0] and "action=restart" in lines[0]
    assert "rc=143" in lines[1] and "attempt=2/" in lines[1]
    assert "rc=0" in lines[2] and "action=exit" in lines[2]


# ------------------------------------------------------------ full drill --
@pytest.mark.slow
def test_full_chaos_drill(tmp_path):
    """The real thing: scripts/chaos_drill.sh drives supervise.sh + the CLI
    through NaN burst / loader IO / torn checkpoint / SIGTERM and asserts
    convergence to rc 0, then sustained NaN to rc 8 with no restart."""
    env = {k: v for k, v in os.environ.items()
           if k not in (chaoslib.ENV_SPEC, chaoslib.ENV_STATE_DIR)}
    env["CHAOS_PHASES"] = "1 2"  # pod phases 3-5 are test_fleet's drill
    p = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "chaos_drill.sh"),
         str(tmp_path / "drill")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=1200)
    assert p.returncode == 0, (p.stdout[-3000:], p.stderr[-2000:])
    assert "CHAOS DRILL PASS" in p.stdout
