"""Automated preemption-recovery chain test (VERDICT r2 weak #4).

Round 2 proved recovery manually once (a real mid-run kill during the PLC
digits run, docs/convergence.md); this test automates the WHOLE chain as
one path: subprocess PLC training → SIGKILL mid-epoch → restart via
`scripts/supervise.sh` (whose restart command is the start command plus
`--auto_resume`) → assert the epoch counter continues, the optimizer/model
state is restored, the corrected labels + δ are restored, and the
post-resume per-epoch metrics match an uninterrupted control run.

The metric-equality assertion works because every nondeterminism source is
keyed, not ambient: the epoch permutation is seeded by (seed, epoch)
(data/loader.py::shard_indices_for_host), per-sample transform rngs by
(seed, epoch, index, slot), and the restored TrainState is exact — so a
resumed epoch N replays the uninterrupted epoch N bit-for-bit on the same
host.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest
from PIL import Image

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    os.environ.get("SKIP_SUBPROCESS_TESTS") == "1",
    reason="subprocess-heavy chain test disabled by env",
)


def _write_imagefolder(root, classes=2, per_train=64, per_val=16, size=32):
    """Structured images (class-dependent gradients + noise) so two classes
    are actually separable and training/eval metrics move."""
    rng = np.random.default_rng(7)
    for split, per in (("train", per_train), ("val", per_val)):
        for c in range(classes):
            d = root / split / f"class{c}"
            d.mkdir(parents=True)
            for i in range(per):
                ramp = np.linspace(0, 255, size) if c == 0 else np.linspace(255, 0, size)
                base = np.broadcast_to(ramp[None, :], (size, size))
                img = np.stack([base] * 3, 2) + rng.normal(0, 30, (size, size, 3))
                Image.fromarray(np.clip(img, 0, 255).astype(np.uint8)).save(
                    d / f"img{i}.png")


def _cmd(folder, out, epochs):
    # --dtype float32: at lr 0.05 this 2-class toy recipe collapses to
    # CE-loss-exactly-0 with saturated logits, and under bf16 that regime
    # sits on a knife edge where the ULP-level difference between a
    # persistent-cache-DESERIALIZED executable (the resumed process) and
    # the freshly compiled one (the producer) amplifies into NaN within
    # one step — the resumed run then legitimately exits rc 8 via the
    # step sentinel. f32 headroom keeps the replayed trajectory inside
    # the comparison tolerance; the chain under test (kill → supervise →
    # auto-resume → continue) is dtype-independent.
    return [
        sys.executable, "-m", "ddp_classification_pytorch_tpu.cli.train", "plc",
        "--folder", str(folder), "--transform", "cifar", "--image_size", "32",
        "--variant", "cifar", "--model", "resnet18", "--num_classes", "2",
        "--batchsize", "16", "--num_workers", "2", "--lr", "0.05",
        "--dtype", "float32",
        "--epochs", str(epochs), "--correction", "lrt",
        "--plc_warmup_epochs", "0", "--out", str(out), "--seed", "123",
        "--platform", "cpu", "--auto_resume",
    ]


def _env(cache_dir):
    env = dict(os.environ)
    # single virtual device keeps the subprocess light; determinism does not
    # depend on the device count (it is keyed per (seed, epoch, index))
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    # a FRESH compilation-cache dir per invocation: a persistent-cache
    # DESERIALIZED executable differs from the in-memory compiled one at
    # the ULP level (observed live: the resumed process loaded the cache
    # entry its producer wrote, drifted one ULP, and this recipe's
    # saturated-logits regime amplified that into NaN within one step).
    # The replay-equality assertion below requires bit-identical
    # executables, so every subprocess compiles fresh.
    env["JAX_COMPILATION_CACHE_DIR"] = str(cache_dir)
    return env


def _epoch_rows(out_dir):
    """output.txt → {epoch: {metric: value}} (last occurrence wins)."""
    rows = {}
    with open(os.path.join(out_dir, "output.txt")) as f:
        for line in f:
            if not line.startswith("epoch:"):
                continue
            fields = dict(kv.split(":", 1) for kv in line.strip().split("\t"))
            e = int(fields.pop("epoch"))
            rows[e] = {k: float(v) for k, v in fields.items()}
    return rows


def test_kill_mid_epoch_then_supervise_resume_matches_uninterrupted(tmp_path):
    data = tmp_path / "data"
    _write_imagefolder(data)
    epochs = 8
    out_a = tmp_path / "uninterrupted"
    out_b = tmp_path / "preempted"

    # Control: one clean run to completion.
    r = subprocess.run(_cmd(data, out_a, epochs),
                       env=_env(tmp_path / "xla_cache_control"), cwd=REPO,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    rows_a = _epoch_rows(out_a)
    assert set(rows_a) == set(range(epochs))

    # Preempted: SIGKILL as soon as epoch 1's checkpoint lands — a hard
    # kill with later epochs still outstanding, like a real preemption.
    # No grace sleep: on a fast host a fixed sleep could let the remaining
    # epochs finish and make the kill vacuous.
    proc = subprocess.Popen(_cmd(data, out_b, epochs),
                            env=_env(tmp_path / "xla_cache_preempted"), cwd=REPO,
                            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    marker = out_b / "ckpt_e1.msgpack"
    deadline = time.time() + 420
    while not marker.exists():
        assert proc.poll() is None, "training exited before it could be killed"
        assert time.time() < deadline, "no epoch-1 checkpoint within budget"
        time.sleep(0.05)
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=60)
    if proc.returncode == 0:  # host outran the kill — nothing was preempted
        pytest.skip("run completed before SIGKILL landed; host too fast "
                    "for a meaningful preemption")
    assert proc.returncode != 0

    killed_rows = _epoch_rows(out_b)
    assert max(killed_rows) < epochs - 1, "nothing left to resume"

    # Recovery: supervise.sh reruns the IDENTICAL command (it appends
    # --auto_resume itself; the flag is idempotent) until rc=0.
    r2 = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "supervise.sh")]
        + _cmd(data, out_b, epochs)[3:],  # supervise prepends `python -m <module>`
        env={**_env(tmp_path / "xla_cache_resume"), "MAX_RESTARTS": "2"},
        cwd=REPO, capture_output=True, text=True, timeout=900)
    assert r2.returncode == 0, (r2.stdout[-1000:], r2.stderr[-2000:])
    assert "auto-resumed" in r2.stdout

    rows_b = _epoch_rows(out_b)
    # epoch counter continued: every epoch present exactly once, no restart
    # from zero (epoch rows before the kill survive in output.txt)
    assert set(rows_b) == set(range(epochs))

    # post-resume curve matches the uninterrupted control — this is the
    # optimizer/model/label/δ restoration check in one observable: any lost
    # state would diverge the replayed epochs
    for e in range(epochs):
        for k, va in rows_a[e].items():
            if k == "epoch_time":
                continue
            np.testing.assert_allclose(
                rows_b[e][k], va, rtol=1e-4, atol=1e-5,
                err_msg=f"epoch {e} metric {k}: preempted run diverged")

    # corrected labels + δ restored and equal to the control's
    la = np.load(out_a / "plc_labels.npy")
    lb = np.load(out_b / "plc_labels.npy")
    np.testing.assert_array_equal(la, lb)
    import json

    meta_a = json.load(open(out_a / "meta.json"))
    meta_b = json.load(open(out_b / "meta.json"))
    assert meta_a.get("last_epoch") == meta_b.get("last_epoch") == epochs - 1
    if "plc_delta" in meta_a or "plc_delta" in meta_b:
        assert meta_a.get("plc_delta") == meta_b.get("plc_delta")

    # history.json carries the FULL curve after resume (ADVICE r2: resumed
    # runs must append to the pre-preemption history, not overwrite it)
    hist = json.load(open(out_b / "history.json"))
    lengths = {k: len(v) for k, v in hist.items()}
    assert all(n == epochs for n in lengths.values()), lengths
