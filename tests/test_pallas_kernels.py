"""Fused BN+LeakyReLU Pallas kernel vs pure-jnp oracle — values and exact
gradients (including the batch-statistics terms of the BN backward)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_classification_pytorch_tpu.ops.pallas_kernels import (
    batch_norm_leaky_relu,
    fused_bn_leaky_relu,
)


def oracle_bn_leaky(x, scale, bias, eps=1e-5, slope=0.01):
    red = tuple(range(x.ndim - 1))
    mean = jnp.mean(x, axis=red)
    var = jnp.mean(jnp.square(x), axis=red) - jnp.square(mean)
    x_hat = (x - mean) * jax.lax.rsqrt(var + eps)
    y = x_hat * scale + bias
    return jnp.where(y >= 0, y, y * slope)


@pytest.fixture
def data():
    rng = np.random.default_rng(0)
    x = rng.normal(1.5, 2.0, (4, 8, 8, 128)).astype(np.float32)
    scale = rng.normal(1.0, 0.2, (128,)).astype(np.float32)
    bias = rng.normal(0.0, 0.2, (128,)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(scale), jnp.asarray(bias)


def test_forward_matches_oracle(data):
    x, scale, bias = data
    y, mean, var = batch_norm_leaky_relu(x, scale, bias)
    ref = oracle_bn_leaky(x, scale, bias)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(x.mean((0, 1, 2))), atol=1e-5)


def test_gradients_match_oracle(data):
    x, scale, bias = data

    def loss_fused(x, s, b):
        y, _, _ = batch_norm_leaky_relu(x, s, b)
        return jnp.sum(y * jnp.cos(y))  # nonlinear reduction exercises dy

    def loss_oracle(x, s, b):
        y = oracle_bn_leaky(x, s, b)
        return jnp.sum(y * jnp.cos(y))

    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2))(x, scale, bias)
    g_ref = jax.grad(loss_oracle, argnums=(0, 1, 2))(x, scale, bias)
    for a, b_, name in zip(g_fused, g_ref, ("dx", "dscale", "dbias")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=2e-4, rtol=2e-4, err_msg=name
        )


def test_inference_mode_with_running_stats(data):
    x, scale, bias = data
    mean = jnp.full((128,), 0.7)
    var = jnp.full((128,), 2.3)
    y = fused_bn_leaky_relu(x, scale, bias, mean, var)
    x_hat = (x - mean) * jax.lax.rsqrt(var + 1e-5)
    ref = x_hat * scale + bias
    ref = jnp.where(ref >= 0, ref, ref * 0.01)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_non_128_channels_and_bf16():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(0, 1, (2, 4, 4, 64)), jnp.bfloat16)
    scale = jnp.ones((64,))
    bias = jnp.zeros((64,))
    y, _, _ = batch_norm_leaky_relu(x, scale, bias)
    assert y.dtype == jnp.bfloat16
    ref = oracle_bn_leaky(x.astype(jnp.float32), scale, bias)
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(ref), atol=0.05
    )
