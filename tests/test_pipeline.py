"""GPipe executor: pipelined == sequential, values AND gradients, on the
8-device CPU mesh (shard_map + ppermute + psum — the code path a TPU pod
runs over ICI)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_classification_pytorch_tpu.ops.pipeline import gpipe, _stage_apply
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib


def _block_apply(p, x):
    """Toy homogeneous block: x @ W + b, gelu."""
    return jax.nn.gelu(x @ p["w"] + p["b"])


def _stacked(depth=8, ch=16, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(scale=0.3, size=(depth, ch, ch)), jnp.float32),
        "b": jnp.asarray(rng.normal(scale=0.1, size=(depth, ch)), jnp.float32),
    }


def _x(b=8, t=4, ch=16, seed=1):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=(b, t, ch)), jnp.float32)


@pytest.mark.parametrize("stages,micro", [(2, 2), (4, 4), (8, 2)])
def test_gpipe_matches_sequential(stages, micro):
    mesh = meshlib.make_mesh(
        meshlib.MeshSpec(len(jax.devices()) // stages, stages))
    params, x = _stacked(), _x()
    seq = _stage_apply(_block_apply, params, x)
    pipe = jax.jit(lambda p, x: gpipe(
        _block_apply, p, x, mesh=mesh, axis_name=meshlib.MODEL_AXIS,
        microbatches=micro))(params, x)
    np.testing.assert_allclose(np.asarray(pipe), np.asarray(seq), atol=1e-5)


def test_gpipe_gradients_match_sequential():
    mesh = meshlib.make_mesh(meshlib.MeshSpec(2, 4))
    params, x = _stacked(), _x()

    def loss_seq(p):
        return (_stage_apply(_block_apply, p, x) ** 2).mean()

    def loss_pipe(p):
        out = gpipe(_block_apply, p, x, mesh=mesh,
                    axis_name=meshlib.MODEL_AXIS, microbatches=2)
        return (out ** 2).mean()

    g_seq = jax.grad(loss_seq)(params)
    g_pipe = jax.jit(jax.grad(loss_pipe))(params)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(g_pipe[k]), np.asarray(g_seq[k]), atol=1e-5, err_msg=k)


def test_gpipe_single_stage_degenerates_to_sequential():
    mesh = meshlib.make_mesh(meshlib.MeshSpec(len(jax.devices()), 1))
    params, x = _stacked(), _x()
    out = gpipe(_block_apply, params, x, mesh=mesh,
                axis_name=meshlib.MODEL_AXIS, microbatches=4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_stage_apply(_block_apply, params, x)),
        atol=1e-6)


def test_gpipe_validates_divisibility():
    mesh = meshlib.make_mesh(meshlib.MeshSpec(2, 4))
    params, x = _stacked(depth=6), _x()
    with pytest.raises(ValueError, match="not divisible"):
        gpipe(_block_apply, params, x, mesh=mesh,
              axis_name=meshlib.MODEL_AXIS, microbatches=2)
    params, x = _stacked(), _x(b=6)
    with pytest.raises(ValueError, match="batch"):
        gpipe(_block_apply, params, x, mesh=mesh,
              axis_name=meshlib.MODEL_AXIS, microbatches=4)


def _pp_cfg(mp=2, micro=2):
    from ddp_classification_pytorch_tpu.config import get_preset

    cfg = get_preset("baseline")
    cfg.model.arch = "vit_t16"
    cfg.model.dtype = "float32"
    cfg.data.image_size = 64  # 16 tokens
    cfg.data.num_classes = 4
    cfg.data.batch_size = 8
    cfg.parallel.model_axis = mp
    cfg.parallel.pipeline_microbatches = micro
    return cfg


def test_gpipe_vit_forward_matches_single_stage():
    """Same params through a 4-stage pipeline and through the degenerate
    1-stage sequential path must agree."""
    import jax

    from ddp_classification_pytorch_tpu.models.pipeline_vit import GPipeViT

    mesh_pp = meshlib.make_mesh(meshlib.MeshSpec(2, 4))
    mesh_seq = meshlib.make_mesh(meshlib.MeshSpec(len(jax.devices()), 1))
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(8, 64, 64, 3)), jnp.float32)
    pp = GPipeViT("vit_t16", 4, mesh_pp, 2, dtype=jnp.float32)
    seq = GPipeViT("vit_t16", 4, mesh_seq, 2, dtype=jnp.float32)
    vs = pp.init(jax.random.PRNGKey(0), x)
    out_pp = jax.jit(lambda v, x: pp.apply(v, x, train=False))(vs, x)
    out_seq = seq.apply(vs, x, train=False)
    np.testing.assert_allclose(
        np.asarray(out_pp), np.asarray(out_seq), atol=2e-4)


def test_gpipe_vit_train_step_e2e():
    """Full jitted train step: dp×pp mesh, stacked params stage-sharded."""
    import jax

    from ddp_classification_pytorch_tpu.parallel.mesh import MODEL_AXIS
    from ddp_classification_pytorch_tpu.train.state import create_train_state
    from ddp_classification_pytorch_tpu.train.steps import make_train_step

    cfg = _pp_cfg(mp=2, micro=2)
    mesh = meshlib.make_mesh(meshlib.MeshSpec(4, 2))
    with mesh:
        model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
        # stacked block params actually sharded over stages
        leaf = state.params["blocks"]["attn"]["qkv"]["kernel"]
        assert leaf.sharding.spec[0] == MODEL_AXIS
        step = make_train_step(cfg, model, tx)
        rng = np.random.default_rng(0)
        images = jax.device_put(
            rng.normal(size=(8, 64, 64, 3)).astype(np.float32),
            meshlib.batch_sharding(mesh))
        labels = jax.device_put(
            rng.integers(0, 4, 8).astype(np.int32),
            meshlib.batch_sharding(mesh))
        losses = []
        for _ in range(3):
            state, metrics = step(state, images, labels)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_pipeline_flag_rejects_unsupported_configs():
    import pytest as _pytest

    from ddp_classification_pytorch_tpu.models.factory import build_model

    mesh = meshlib.make_mesh(meshlib.MeshSpec(2, 4))
    cfg = _pp_cfg().model
    cfg.arch = "resnet50"
    with _pytest.raises(ValueError, match="requires a ViT"):
        build_model(cfg, 4, mesh=mesh, pipeline_microbatches=2)
    cfg.arch = "vit_t16"
    cfg.head = "nested"
    with _pytest.raises(ValueError, match="head='fc' or 'arcface'"):
        build_model(cfg, 4, mesh=mesh, pipeline_microbatches=2)
    # arcface is SUPPORTED since r4 (GPipeArcFaceViT — the dp×tp×pp
    # composition, tests/test_three_axis_pipeline.py)
    cfg.head = "arcface"
    from ddp_classification_pytorch_tpu.models.pipeline_vit import (
        GPipeArcFaceViT,
    )

    assert isinstance(
        build_model(cfg, 4, mesh=mesh, pipeline_microbatches=2),
        GPipeArcFaceViT)
    cfg.head = "fc"
    cfg.dropout = 0.1
    with _pytest.raises(ValueError, match="dropout"):
        build_model(cfg, 4, mesh=mesh, pipeline_microbatches=2)
