"""ViT backbone family — shapes, heads, and sequence-parallel training.

The multi-device tests run the FULL train step with the token axis ring-
sharded over the mesh 'model' axis (shard_map + ppermute inside the jitted
step) on the 8-device CPU mesh — the framework's long-context path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddp_classification_pytorch_tpu.config import get_preset
from ddp_classification_pytorch_tpu.models.factory import build_model, feat_dim_for
from ddp_classification_pytorch_tpu.models.vit import build_vit
from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
from ddp_classification_pytorch_tpu.train.state import create_train_state
from ddp_classification_pytorch_tpu.train.steps import make_eval_step, make_train_step


def _vit_cfg(head="fc", mp=1):
    cfg = get_preset("baseline")
    cfg.model.arch = "vit_t16"
    cfg.model.dtype = "float32"
    cfg.model.head = head
    cfg.data.image_size = 64  # (64/16)² = 16 tokens; divisible by mp ≤ 8
    cfg.data.num_classes = 12
    cfg.data.batch_size = 8
    cfg.parallel.model_axis = mp
    return cfg


def test_vit_feature_and_logit_shapes():
    model = build_vit("vit_t16", num_classes=0, dtype=jnp.float32)
    x = jnp.zeros((2, 64, 64, 3))
    vs = model.init(jax.random.PRNGKey(0), x, train=False)
    feats = model.apply(vs, x, train=False)
    assert feats.shape == (2, 192)
    clf = build_vit("vit_t16", num_classes=7, dtype=jnp.float32)
    vs = clf.init(jax.random.PRNGKey(0), x, train=False)
    assert clf.apply(vs, x, train=False).shape == (2, 7)


def test_vit_feat_dim_registry():
    cfg = _vit_cfg()
    assert feat_dim_for(cfg.model) == 192


@pytest.mark.parametrize("mp", [2, 4])
def test_vit_train_step_sequence_parallel(mp):
    """Full jitted train step with dp×sp mesh; loss finite and decreasing-ish."""
    cfg = _vit_cfg(mp=mp)
    mesh = meshlib.make_mesh(
        meshlib.MeshSpec(len(jax.devices()) // mp, mp))
    with mesh:
        model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
        step = make_train_step(cfg, model, tx)
        rng = np.random.default_rng(0)
        images = jax.device_put(
            rng.normal(size=(8, 64, 64, 3)).astype(np.float32),
            meshlib.batch_sharding(mesh))
        labels = jax.device_put(
            rng.integers(0, 12, 8).astype(np.int32),
            meshlib.batch_sharding(mesh))
        losses = []
        for _ in range(3):
            state, metrics = step(state, images, labels)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # memorizes a fixed batch within 3 steps


def test_vit_sequence_parallel_matches_single_device():
    """Ring-sharded forward == dense forward on identical params."""
    cfg = _vit_cfg(mp=4)
    mesh = meshlib.make_mesh(meshlib.MeshSpec(2, 4))
    dense_model = build_model(cfg.model, cfg.data.num_classes)      # no mesh
    ring_model = build_model(cfg.model, cfg.data.num_classes, mesh=mesh)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 64, 64, 3)),
                    jnp.float32)
    vs = dense_model.init(jax.random.PRNGKey(0), x, train=False)
    dense = dense_model.apply(vs, x, train=False)
    with mesh:
        ring = ring_model.apply(vs, x, train=False)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), atol=2e-4)


def test_vit_arcface_head_composes():
    """ViT backbone under the ArcFace margin head trains one step."""
    cfg = _vit_cfg(head="arcface", mp=2)
    mesh = meshlib.make_mesh(meshlib.MeshSpec(4, 2))
    with mesh:
        model, tx, state = create_train_state(cfg, mesh, steps_per_epoch=4)
        step = make_train_step(cfg, model, tx)
        images = jax.device_put(jnp.ones((8, 64, 64, 3)),
                                meshlib.batch_sharding(mesh))
        labels = jax.device_put(jnp.arange(8, dtype=jnp.int32) % 12,
                                meshlib.batch_sharding(mesh))
        state, metrics = step(state, images, labels)
        assert np.isfinite(float(metrics["loss"]))
        eval_step = make_eval_step(cfg, model)
        out = eval_step(state, images, labels, jnp.ones((8,)))
        assert np.isfinite(float(out["loss_sum"]))


def test_flash_min_tokens_autopick(monkeypatch):
    """Below the flash_min_tokens floor, --flash_attention must route the
    unsharded path to dense attention (measured: dense is equal-or-better
    in the hundreds of tokens, docs/performance.md knob #4); at/above the
    floor — and always when floor=0 — the Pallas kernel runs."""
    import importlib

    attn_mod = importlib.import_module(
        "ddp_classification_pytorch_tpu.ops.attention")

    calls = []
    real = attn_mod.ring_attention

    def spy(q, k, v, **kw):
        calls.append(kw.get("use_flash", False))
        return real(q, k, v, **kw)

    monkeypatch.setattr("ddp_classification_pytorch_tpu.models.vit.ring_attention", spy)

    x = jnp.zeros((2, 64, 64, 3))  # 16 tokens
    for floor, expect_flash in [(1024, False), (0, True), (16, True)]:
        calls.clear()
        model = build_vit("vit_t16", num_classes=0, dtype=jnp.float32,
                          use_flash=True, flash_min_tokens=floor)
        vs = model.init(jax.random.PRNGKey(0), x, train=False)
        model.apply(vs, x, train=False)
        assert calls and all(c == expect_flash for c in calls), (floor, calls)


def test_flash_min_tokens_config_plumbs_to_model():
    from ddp_classification_pytorch_tpu.models.factory import build_backbone

    cfg = get_preset("baseline")
    cfg.model.arch = "vit_t16"
    cfg.model.flash_attention = True
    cfg.model.flash_min_tokens = 512
    vit = build_backbone(cfg.model, 10)
    assert vit.use_flash is True
    assert vit.flash_min_tokens == 512


def test_ln_bf16_stays_close_to_f32_recipe():
    """`--ln_bf16` (VERDICT r3 #5 bandwidth experiment) changes only the
    LayerNorm compute dtype; in f32 compute the flag must be a no-op, and
    in bf16 compute its outputs must track the f32-LN recipe to bf16
    resolution — it is a perf lever, not a different model."""
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 32, 32, 3)),
                    jnp.float32)

    def logits(ln_bf16, dtype):
        model = build_vit("vit_t16", num_classes=7, dtype=dtype,
                          ln_bf16=ln_bf16)
        v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                       train=False)
        return np.asarray(model.apply(v, x, train=False), np.float32)

    # f32 compute: flag is exactly a no-op (ln dtype == compute dtype)
    np.testing.assert_array_equal(logits(False, jnp.float32),
                                  logits(True, jnp.float32))
    # bf16 compute: bf16 LN tracks the f32-LN recipe to bf16 resolution
    a, b = logits(False, jnp.bfloat16), logits(True, jnp.bfloat16)
    np.testing.assert_allclose(a, b, rtol=0.05, atol=0.05)
    assert np.std(a) > 1e-3


def test_vit_remat_checkpoint_dots_gradients_match():
    """remat with the checkpoint_dots policy must stay numerically
    transparent (same contract tests/test_remat.py pins for ResNet)."""
    import optax

    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 32, 32, 3)),
                    jnp.float32)
    y = jnp.asarray([1, 3], jnp.int32)

    def grads_for(remat):
        model = build_vit("vit_t16", num_classes=5, dtype=jnp.float32,
                          remat=remat)
        v = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)),
                       train=False)

        def loss(params):
            logits = model.apply({"params": params}, x, train=True)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        return jax.grad(loss)(v["params"])

    for a, b in zip(jax.tree_util.tree_leaves(grads_for(False)),
                    jax.tree_util.tree_leaves(grads_for(True))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
