"""Multi-host worker for tests/test_multihost.py (not a test module).

Each process owns ONE virtual CPU device; `jax.distributed.initialize`
(gloo standing in for DCN) joins them into one 2-device platform — the
same SPMD program a 2-host TPU pod runs. The worker drives the PRODUCT
path: `make_mesh` over global devices, `make_global_array` from this
host's slice of a fixed global batch, and the jitted `make_train_step`.
Host 0 writes the per-step losses to the output file for the parent to
compare against a single-process run of the identical global batch.

Why one device per process: jaxlib 0.4.37's gloo CPU collectives share
one context per process pair, and CONCURRENT collectives (one per local
device executor thread, or independent thunks of one program) interleave
nondeterministically across processes — observed as a hard abort in
gloo's tcp pair ("op.preamble.length <= op.nbytes", the peer's bytes for
a different collective landing in ours). With a single local device the
program's collectives issue strictly in program order on both sides and
the run is stable. A real TPU pod does not share the limitation (its
collectives are matched by channel id in hardware); re-widening this
harness to >1 local device needs a jaxlib with per-collective gloo tags.
The upside: the composed dp×tp phase now places the TP PAIR ITSELF
across the real process boundary (mesh 1×2) — every partial-FC
collective crosses it, not just the gradient mean.
"""

import json
import os
import sys


def main() -> None:
    pid, nprocs, port, out = (int(sys.argv[1]), int(sys.argv[2]),
                              sys.argv[3], sys.argv[4])
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    # the parent's oracle runs under tests/conftest.py, which pins
    # jax_threefry_partitionable=True; the library default is still False,
    # and the two derivations draw DIFFERENT init params — the losses can
    # never match without pinning the same rng semantics here
    jax.config.update("jax_threefry_partitionable", True)
    # without a cross-host collectives implementation the multi-process CPU
    # client compiles nothing that spans processes ("Multiprocess
    # computations aren't implemented on the CPU backend") — gloo is the
    # stand-in for DCN here, same as fleet.initialize_with_retry wires up
    # for the pod drills
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(f"localhost:{port}", num_processes=nprocs,
                               process_id=pid)
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from multihost_common import run_steps

    from ddp_classification_pytorch_tpu.data.loader import shard_indices_for_host
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib

    assert jax.process_count() == nprocs and jax.local_device_count() == 1

    # per-host dataset sharding sanity: hosts take disjoint, covering shards
    shards = [
        shard_indices_for_host(64, epoch=0, seed=7, batch_size=8,
                               host_id=h, num_hosts=nprocs)
        for h in range(nprocs)
    ]
    flat = np.concatenate(shards)
    assert len(set(flat.tolist())) == 64, "host shards must cover the dataset"
    assert all(len(s) == 64 // nprocs for s in shards), "equal host shards"

    mesh = meshlib.make_mesh()
    losses = run_steps(mesh, host_rows=slice(pid * 8, (pid + 1) * 8))

    # composed dp×tp mesh with the TP pair across the REAL process
    # boundary (VERDICT r4 #5): same shared runner the parent's oracle
    # uses, 1×2 layout (see module docstring)
    from multihost_common import run_composed_steps

    composed = run_composed_steps(host_rows=slice(0, 16),
                                  spec=meshlib.MeshSpec(1, 2),
                                  replicate_batch=True)

    ckpt_ok = _checkpoint_tp_sharded_roundtrip(out + ".ckptdir", nprocs)
    if jax.process_index() == 0:
        with open(out, "w") as f:
            json.dump({"losses": losses, "composed": composed,
                       "ckpt_ok": ckpt_ok}, f)


def _checkpoint_tp_sharded_roundtrip(ckpt_dir: str, nprocs: int) -> bool:
    """Save + restore a state whose TP-sharded weight shards are NOT
    addressable from host 0 (mesh (1, nprocs): the upper class shards live
    only on process 1) — the case a plain device_get cannot serve. A
    handcrafted two-leaf pytree keeps this phase compile-cheap; the
    semantics (collective gather in save, sharded re-placement in restore)
    are the same ones the Trainer's full TrainState takes. Returns True
    when the restored weight equals the original on every process."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib
    from ddp_classification_pytorch_tpu.train.checkpoint import (
        CheckpointManager,
        _to_host,
    )

    mesh = meshlib.make_mesh(meshlib.MeshSpec(1, nprocs))
    weight = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    state = {
        "weight": jax.device_put(
            weight, NamedSharding(mesh, P(meshlib.MODEL_AXIS, None))),
        "step": jax.device_put(np.int32(7), NamedSharding(mesh, P())),
    }
    assert not state["weight"].is_fully_addressable, (
        "test premise: TP shards must cross the process boundary")
    ck = CheckpointManager(ckpt_dir, save_every_epoch=True)
    ck.save(state, 0, metric=1.0)   # collective gather inside
    # host 0 writes the file; other hosts must not race into restore
    # before the bytes land (in production, restore happens at startup of
    # a NEW run, so this barrier is a test-only concern)
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("ckpt_written")
    restored = ck.restore(state, ck.epoch_path(0))
    same_w = bool(np.allclose(np.asarray(_to_host(restored["weight"])), weight))
    return same_w and int(restored["step"]) == 7


if __name__ == "__main__":
    main()
