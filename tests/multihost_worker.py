"""Multi-host worker for tests/test_multihost.py (not a test module).

Each process owns 4 virtual CPU devices; `jax.distributed.initialize` joins
them into one 8-device platform — the same SPMD program a 2-host TPU pod
runs, with gloo standing in for DCN. The worker drives the PRODUCT path:
`make_mesh` over global devices, `make_global_array` from this host's slice
of a fixed global batch, and the jitted `make_train_step`. Host 0 writes the
per-step losses to the output file for the parent to compare against a
single-process run of the identical global batch.
"""

import json
import os
import sys


def main() -> None:
    pid, nprocs, port, out = (int(sys.argv[1]), int(sys.argv[2]),
                              sys.argv[3], sys.argv[4])
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(f"localhost:{port}", num_processes=nprocs,
                               process_id=pid)
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from multihost_common import run_steps

    from ddp_classification_pytorch_tpu.data.loader import shard_indices_for_host
    from ddp_classification_pytorch_tpu.parallel import mesh as meshlib

    assert jax.process_count() == nprocs and jax.local_device_count() == 4

    # per-host dataset sharding sanity: hosts take disjoint, covering shards
    shards = [
        shard_indices_for_host(64, epoch=0, seed=7, batch_size=8,
                               host_id=h, num_hosts=nprocs)
        for h in range(nprocs)
    ]
    flat = np.concatenate(shards)
    assert len(set(flat.tolist())) == 64, "host shards must cover the dataset"
    assert all(len(s) == 64 // nprocs for s in shards), "equal host shards"

    mesh = meshlib.make_mesh()
    losses = run_steps(mesh, host_rows=slice(pid * 8, (pid + 1) * 8))
    if jax.process_index() == 0:
        with open(out, "w") as f:
            json.dump({"losses": losses}, f)


if __name__ == "__main__":
    main()
