// Native host dataplane: JPEG/PNG decode → crop/resize → flip → normalize,
// multithreaded, one call per batch.
//
// This is the TPU framework's native-code replacement for the reference's
// input pipeline hot path — `DataLoader(num_workers=4, pin_memory=True)`
// worker processes running PIL + torchvision transforms per sample
// (reference BASELINE/main.py:58-76,130-131). One C call fills a whole
// NHWC float32 batch buffer that jax can ship to device without further
// host-side work. Decoding dispatches on file magic bytes to libjpeg or
// libpng (PIL `convert("RGB")` semantics: palette/gray expanded, alpha
// dropped); crops follow torchvision semantics (RandomResizedCrop(scale,
// ratio 3/4..4/3, 10 tries, fallback center; val: resize-short-side +
// center crop) so training recipes match the reference's augmentation
// distribution.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libdataplane.so dataplane.cpp -ljpeg -lpng -lpthread

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>
#ifndef DP_NO_PNG
#include <png.h>
#endif
#include <csetjmp>

namespace {

// --------------------------------------------------------------- RNG -------
// SplitMix64 → xoshiro-like per-item stream; deterministic given (seed, item).
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed) {}
  uint64_t next_u64() {
    s += 0x9E3779B97f4A7C15ULL;
    uint64_t z = s;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
    return z ^ (z >> 31);
  }
  double uniform() {  // [0, 1)
    return (next_u64() >> 11) * (1.0 / 9007199254740992.0);
  }
  double uniform(double lo, double hi) { return lo + (hi - lo) * uniform(); }
  int randint(int n) { return (int)(uniform() * n); }  // [0, n)
};

// ------------------------------------------------------------- decode ------
// Header-declared dimensions are attacker-/corruption-controlled; cap them
// before any allocation so a bogus header cannot drive out.resize() into
// std::bad_alloc (training images are far below these bounds).
constexpr int kMaxDim = 32768;
constexpr long long kMaxPixels = 64LL * 1024 * 1024;  // 192 MB RGB

bool dims_ok(int w, int h) {
  return w > 0 && h > 0 && w <= kMaxDim && h <= kMaxDim &&
         (long long)w * h <= kMaxPixels;
}

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jb, 1);
}

// Decode a JPEG file to RGB u8. Returns true on success.
bool decode_jpeg(const char* path, std::vector<uint8_t>& out, int& w, int& h) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    fclose(f);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  w = cinfo.output_width;
  h = cinfo.output_height;
  if (!dims_ok(w, h)) {
    jpeg_destroy_decompress(&cinfo);
    fclose(f);
    return false;
  }
  out.resize((size_t)w * h * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out.data() + (size_t)cinfo.output_scanline * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  fclose(f);
  return true;
}

#ifndef DP_NO_PNG
// Decode a PNG file to RGB u8 via libpng. PIL-convert("RGB") semantics:
// 16-bit → 8-bit, palette/gray expanded to RGB, alpha channel dropped
// (not composited — PIL's convert discards it too). Interlaced images are
// handled by libpng itself. Returns true on success.
bool decode_png(FILE* f, std::vector<uint8_t>& out, int& w, int& h) {
  png_structp png =
      png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  if (!png) return false;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return false;
  }
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return false;
  }
  png_init_io(png, f);
  png_read_info(png, info);
  png_set_strip_16(png);
  png_set_packing(png);
  png_set_palette_to_rgb(png);
  png_set_expand_gray_1_2_4_to_8(png);
  png_set_gray_to_rgb(png);
  png_set_strip_alpha(png);
  int passes = png_set_interlace_handling(png);
  png_read_update_info(png, info);
  w = (int)png_get_image_width(png, info);
  h = (int)png_get_image_height(png, info);
  if (!dims_ok(w, h)) {
    png_destroy_read_struct(&png, &info, nullptr);
    return false;
  }
  if (png_get_rowbytes(png, info) != (size_t)w * 3) {
    png_destroy_read_struct(&png, &info, nullptr);
    return false;  // transform chain failed to land on tight RGB rows
  }
  out.resize((size_t)w * h * 3);
  // Row-by-row into the caller's buffer: no local non-trivial object lives
  // across the setjmp/longjmp error path (a vector constructed after setjmp
  // would have its destructor skipped by a corrupt-file longjmp — per-file
  // leak); `out` belongs to the caller, so its cleanup is never skipped.
  for (int p = 0; p < passes; ++p)
    for (int y = 0; y < h; ++y)
      png_read_row(png, out.data() + (size_t)y * w * 3, nullptr);
  png_destroy_read_struct(&png, &info, nullptr);
  return true;
}

#endif  // DP_NO_PNG

// Decode a JPEG or PNG file to RGB u8, dispatching on magic bytes.
// (Built with -DDP_NO_PNG when libpng is absent: JPEG-only, PNGs fall
// through to the caller's PIL retry path.)
bool decode_image(const char* path, std::vector<uint8_t>& out, int& w, int& h) {
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  uint8_t magic[8] = {0};
  size_t got = fread(magic, 1, sizeof(magic), f);
  rewind(f);
#ifndef DP_NO_PNG
  if (got >= 8 && png_sig_cmp(magic, 0, 8) == 0) {
    bool ok = decode_png(f, out, w, h);
    fclose(f);
    return ok;
  }
#endif
  fclose(f);
  if (got >= 2 && magic[0] == 0xFF && magic[1] == 0xD8)
    return decode_jpeg(path, out, w, h);
  return false;
}

// ------------------------------------------------------------ resample -----
// Bilinear sample from src (h×w RGB u8) region [y0,y0+ch)×[x0,x0+cw)
// scaled to out_h×out_w, optional horizontal flip, normalized to f32 CHW-less
// NHWC with (v/255 - mean)/std.
void crop_resize_normalize(const uint8_t* src, int w, int h,
                           double x0, double y0, double cw, double ch,
                           float* dst, int out_w, int out_h, bool flip,
                           const float* mean, const float* stdv) {
  const double sx = cw / out_w, sy = ch / out_h;
  for (int oy = 0; oy < out_h; ++oy) {
    // torchvision/PIL bilinear: sample at pixel centers
    double fy = y0 + (oy + 0.5) * sy - 0.5;
    int y_lo = (int)std::floor(fy);
    double wy = fy - y_lo;
    int y0c = std::clamp(y_lo, 0, h - 1);
    int y1c = std::clamp(y_lo + 1, 0, h - 1);
    for (int ox = 0; ox < out_w; ++ox) {
      double fx = x0 + (ox + 0.5) * sx - 0.5;
      int x_lo = (int)std::floor(fx);
      double wx = fx - x_lo;
      int x0c = std::clamp(x_lo, 0, w - 1);
      int x1c = std::clamp(x_lo + 1, 0, w - 1);
      const uint8_t* p00 = src + ((size_t)y0c * w + x0c) * 3;
      const uint8_t* p01 = src + ((size_t)y0c * w + x1c) * 3;
      const uint8_t* p10 = src + ((size_t)y1c * w + x0c) * 3;
      const uint8_t* p11 = src + ((size_t)y1c * w + x1c) * 3;
      int out_x = flip ? (out_w - 1 - ox) : ox;
      float* q = dst + ((size_t)oy * out_w + out_x) * 3;
      for (int c = 0; c < 3; ++c) {
        double v = (1 - wy) * ((1 - wx) * p00[c] + wx * p01[c]) +
                   wy * ((1 - wx) * p10[c] + wx * p11[c]);
        q[c] = ((float)(v / 255.0) - mean[c]) / stdv[c];
      }
    }
  }
}

// torchvision RandomResizedCrop box: sample area∈scale·A, ratio∈(3/4,4/3),
// 10 attempts, else center fallback.
void rrc_box(Rng& rng, int w, int h, double smin, double smax,
             double& x0, double& y0, double& cw, double& ch) {
  const double area = (double)w * h;
  const double log_rmin = std::log(3.0 / 4.0), log_rmax = std::log(4.0 / 3.0);
  for (int i = 0; i < 10; ++i) {
    double target = area * rng.uniform(smin, smax);
    double ratio = std::exp(rng.uniform(log_rmin, log_rmax));
    int tw = (int)std::lround(std::sqrt(target * ratio));
    int th = (int)std::lround(std::sqrt(target / ratio));
    if (tw > 0 && th > 0 && tw <= w && th <= h) {
      x0 = rng.randint(w - tw + 1);
      y0 = rng.randint(h - th + 1);
      cw = tw;
      ch = th;
      return;
    }
  }
  // fallback: clamp ratio, center crop (torchvision semantics)
  double in_ratio = (double)w / h;
  if (in_ratio < 3.0 / 4.0) {
    cw = w;
    ch = std::round(cw / (3.0 / 4.0));
  } else if (in_ratio > 4.0 / 3.0) {
    ch = h;
    cw = std::round(ch * (4.0 / 3.0));
  } else {
    cw = w;
    ch = h;
  }
  x0 = (w - cw) / 2.0;
  y0 = (h - ch) / 2.0;
}

struct BatchJob {
  const char** paths;
  int n;
  float* out;
  int out_h, out_w;
  int train;
  int resize_short;
  double scale_min, scale_max;
  uint64_t seed;
  const float* mean;
  const float* stdv;
  std::atomic<int> next{0};
  std::atomic<int> errors{0};
};

void worker(BatchJob* job) {
  std::vector<uint8_t> buf;
  int w, h;
  for (;;) {
    int i = job->next.fetch_add(1);
    if (i >= job->n) return;
    float* dst = job->out + (size_t)i * job->out_h * job->out_w * 3;
    bool ok = false;
    try {
      ok = decode_image(job->paths[i], buf, w, h);
    } catch (...) {
      // an exception escaping a pool thread would std::terminate the
      // whole trainer; a failed slot must degrade like any other
      ok = false;
    }
    if (!ok) {
      // unreadable/unsupported/oversized: zero-fill; caller retries via PIL
      std::memset(dst, 0, sizeof(float) * job->out_h * job->out_w * 3);
      job->errors.fetch_add(1);
      continue;
    }
    Rng rng(job->seed * 0x9E3779B97f4A7C15ULL + (uint64_t)i * 0xD1B54A32D192ED03ULL);
    double x0, y0, cw, ch;
    bool flip = false;
    if (job->train) {
      rrc_box(rng, w, h, job->scale_min, job->scale_max, x0, y0, cw, ch);
      flip = rng.uniform() < 0.5;
    } else {
      // Resize(resize_short) + CenterCrop(out): equivalent single resample —
      // crop box side = out/resize_short · short_side, centered
      double scale = (double)std::min(w, h) / job->resize_short;
      cw = job->out_w * scale;
      ch = job->out_h * scale;
      x0 = (w - cw) / 2.0;
      y0 = (h - ch) / 2.0;
    }
    crop_resize_normalize(buf.data(), w, h, x0, y0, cw, ch, dst,
                          job->out_w, job->out_h, flip, job->mean, job->stdv);
  }
}

}  // namespace

extern "C" {

// Fill out[n, out_h, out_w, 3] float32. Returns number of decode failures
// (their slots are zero-filled; indices of failures are not reported — the
// Python wrapper re-loads failed slots through PIL when the count is >0).
int dp_load_batch(const char** paths, int n, float* out, int out_h, int out_w,
                  int train, int resize_short, double scale_min,
                  double scale_max, uint64_t seed, const float* mean,
                  const float* stdv, int num_threads) {
  BatchJob job;
  job.paths = paths;
  job.n = n;
  job.out = out;
  job.out_h = out_h;
  job.out_w = out_w;
  job.train = train;
  job.resize_short = resize_short;
  job.scale_min = scale_min;
  job.scale_max = scale_max;
  job.seed = seed;
  job.mean = mean;
  job.stdv = stdv;
  int t = std::max(1, std::min(num_threads, n));
  if (t == 1) {
    worker(&job);
  } else {
    std::vector<std::thread> threads;
    threads.reserve(t);
    for (int i = 0; i < t; ++i) threads.emplace_back(worker, &job);
    for (auto& th : threads) th.join();
  }
  return job.errors.load();
}

// Capability probe: 1 when this build decodes PNG, 0 for the JPEG-only
// -DDP_NO_PNG fallback (callers/tests can degrade instead of failing).
int dp_has_png(void) {
#ifndef DP_NO_PNG
  return 1;
#else
  return 0;
#endif
}

// Probe a JPEG/PNG: returns 0 on success and writes w/h; -1 on failure.
int dp_probe_image(const char* path, int* w, int* h) {
  std::vector<uint8_t> buf;
  int ww, hh;
  if (!decode_image(path, buf, ww, hh)) return -1;
  *w = ww;
  *h = hh;
  return 0;
}

}  // extern "C"
